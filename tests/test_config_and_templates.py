"""Unit tier: YAML config parsing/merging, template evaluation, grammar
generation (reference analogs: model_config_test.go, evaluator_test.go,
grammars/json_schema_test.go)."""
import json

import pytest
import yaml

from localai_tpu.config import ModelConfig, ModelConfigLoader
from localai_tpu.functions import (
    JSON_GRAMMAR, grammar_for_request, json_schema_grammar, parse_tool_calls,
    tools_schema,
)
from localai_tpu.templates import evaluate_chat, evaluate_completion


def test_model_config_yaml_roundtrip(tmp_path):
    (tmp_path / "m.yaml").write_text(yaml.safe_dump({
        "name": "llama3",
        "backend": "llm",
        "context_size": 4096,
        "stopwords": ["</s>"],
        "mesh": {"data": 1, "model": 4},
        "parameters": {"model": "ckpt-dir", "temperature": 0.6,
                       "top_p": 0.9, "max_tokens": 256},
        "template": {"use_tokenizer_template": True},
    }))
    loader = ModelConfigLoader(str(tmp_path))
    cfg = loader.get("llama3")
    assert cfg is not None
    assert cfg.parameters.temperature == 0.6
    assert cfg.mesh.model == 4
    assert cfg.stopwords == ["</s>"]
    assert cfg.model_dir("/models") == "/models/ckpt-dir"


def test_multi_model_single_file(tmp_path):
    (tmp_path / "all.yaml").write_text(yaml.safe_dump([
        {"name": "a", "parameters": {"model": "a-dir"}},
        {"name": "b", "parameters": {"model": "b-dir"}},
    ]))
    loader = ModelConfigLoader(str(tmp_path))
    assert loader.names() == ["a", "b"]


def test_bare_checkpoint_dir_autoregistered(tmp_path):
    d = tmp_path / "bare-model"
    d.mkdir()
    (d / "config.json").write_text("{}")
    loader = ModelConfigLoader(str(tmp_path))
    assert loader.get("bare-model") is not None


def test_hot_reload_picks_up_new_yaml(tmp_path):
    loader = ModelConfigLoader(str(tmp_path))
    assert loader.get("late") is None
    (tmp_path / "late.yaml").write_text(yaml.safe_dump(
        {"name": "late", "parameters": {"model": "x"}}))
    assert loader.get("late") is not None  # per-request rescan


def test_template_inline_chat():
    cfg = ModelConfig(name="t")
    cfg.template.chat_message = (
        "<|{{ role }}|>{{ content }}</|{{ role }}|>")
    cfg.template.chat = "{{ input }}\n<|assistant|>"
    out = evaluate_chat(cfg, [
        {"role": "system", "content": "be brief"},
        {"role": "user", "content": "hi"},
    ])
    assert out == ("<|system|>be brief</|system|>\n<|user|>hi</|user|>\n"
                   "<|assistant|>")


def test_template_completion_and_file(tmp_path):
    (tmp_path / "comp.tmpl").write_text("Q: {{ input }}\nA:")
    cfg = ModelConfig(name="t")
    cfg.config_file = str(tmp_path / "m.yaml")
    cfg.template.completion = "comp"
    assert evaluate_completion(cfg, "why?") == "Q: why?\nA:"


def test_template_multimodal_content_parts():
    cfg = ModelConfig(name="t")
    out = evaluate_chat(cfg, [{"role": "user", "content": [
        {"type": "text", "text": "what is "},
        {"type": "image_url", "image_url": {"url": "x"}},
        {"type": "text", "text": "this?"},
    ]}])
    assert "what is this?" in out


# ------------------------------------------------------------------ grammars

def _terminals(grammar: str) -> str:
    return grammar


def test_json_object_grammar_has_core_rules():
    for rule in ("root ::=", "object ::=", "string ::=", "number ::="):
        assert rule in JSON_GRAMMAR


def test_schema_grammar_object():
    g = json_schema_grammar({
        "type": "object",
        "properties": {
            "name": {"type": "string"},
            "age": {"type": "integer"},
        },
        "required": ["name", "age"],
    })
    assert g.startswith("root ::=")
    assert '"\\"name\\""' in g and '"\\"age\\""' in g
    assert "integer ::=" in g


def test_schema_grammar_enum_and_oneof():
    g = json_schema_grammar({
        "oneOf": [
            {"type": "object", "properties": {"kind": {"const": "a"}},
             "required": ["kind"]},
            {"enum": ["x", "y"]},
        ],
    })
    assert '"\\"a\\""' in g
    assert '"\\"x\\""' in g and '"\\"y\\""' in g


def test_grammar_for_request_modes():
    assert grammar_for_request({"response_format": {"type": "json_object"}}) \
        == JSON_GRAMMAR
    g = grammar_for_request({"response_format": {
        "type": "json_schema",
        "json_schema": {"schema": {"type": "object", "properties": {
            "ok": {"type": "boolean"}}, "required": ["ok"]}},
    }})
    assert '"\\"ok\\""' in g
    tools = [{"type": "function", "function": {
        "name": "get_weather",
        "parameters": {"type": "object", "properties": {
            "city": {"type": "string"}}, "required": ["city"]},
    }}]
    g2 = grammar_for_request({"tools": tools})
    assert '"\\"get_weather\\""' in g2
    assert grammar_for_request({"tools": tools, "tool_choice": "none"}) == ""
    assert grammar_for_request({}) == ""


def test_parse_tool_calls():
    out = parse_tool_calls('{"name": "get_weather", "arguments": {"city": "Paris"}}')
    assert out is not None
    assert out[0]["function"]["name"] == "get_weather"
    assert json.loads(out[0]["function"]["arguments"]) == {"city": "Paris"}
    assert parse_tool_calls("just some text") is None
    assert parse_tool_calls('{"no_name": 1}') is None


def test_tools_schema_shape():
    s = tools_schema([{"function": {"name": "f",
                                    "parameters": {"type": "object"}}}])
    assert s["properties"]["name"]["const"] == "f"


def test_tools_answer_no_action_alternative():
    """tool_choice auto (or absent) includes the reference's no-action
    "answer" alternative so the grammar can produce prose; required /
    pinned choices stay tool-only (reference pkg/functions/functions.go)."""
    from localai_tpu.functions import parse_tool_response

    tools = [{"type": "function", "function": {
        "name": "get_weather", "parameters": {"type": "object"}}}]
    s = tools_schema(tools, allow_answer=True)
    names = [a["properties"]["name"]["const"] for a in s["oneOf"]]
    assert names == ["get_weather", "answer"]

    assert '"\\"answer\\""' in grammar_for_request({"tools": tools})
    assert '"\\"answer\\""' in grammar_for_request(
        {"tools": tools, "tool_choice": "auto"})
    assert '"\\"answer\\""' not in grammar_for_request(
        {"tools": tools, "tool_choice": "required"})
    assert '"\\"answer\\""' not in grammar_for_request(
        {"tools": tools,
         "tool_choice": {"type": "function",
                         "function": {"name": "get_weather"}}})

    # parse_tool_response unwraps the no-action object into prose content
    calls, answer = parse_tool_response(
        '{"name": "answer", "arguments": {"message": "it is sunny"}}')
    assert calls is None and answer == "it is sunny"
    calls, answer = parse_tool_response(
        '{"name": "get_weather", "arguments": {"city": "Oslo"}}')
    assert answer is None and calls[0]["function"]["name"] == "get_weather"
    assert parse_tool_response("plain prose") == (None, None)


def test_template_unsupported_fields_warn(caplog):
    """LocalAI YAMLs using the reference's functions/multimodal/reply-prefix
    template fields get a structured warning instead of silent dropping
    (VERDICT Weak #8)."""
    import logging

    from localai_tpu.config import ModelConfig

    with caplog.at_level(logging.WARNING, logger="localai_tpu"):
        cfg = ModelConfig.from_dict({"name": "ported", "template": {
            "chat": "tmpl", "function": "fn-tmpl", "multimodal": "mm",
            "reply_prefix": "> ",
        }})
    assert cfg.unsupported_template_fields == [
        "function", "multimodal", "reply_prefix"]
    warning = "\n".join(r.getMessage() for r in caplog.records)
    assert "ported" in warning and "reply_prefix" in warning
    assert "function" in warning and "multimodal" in warning
    # supported-only templates stay silent
    caplog.clear()
    with caplog.at_level(logging.WARNING, logger="localai_tpu"):
        clean = ModelConfig.from_dict(
            {"name": "ok", "template": {"chat": "tmpl"}})
    assert clean.unsupported_template_fields == []
    assert not caplog.records
    # empty values don't count as usage
    quiet = ModelConfig.from_dict(
        {"name": "q", "template": {"reply_prefix": ""}})
    assert quiet.unsupported_template_fields == []


# ------------------------------------------------------------------ watchdog

def test_watchdog_reaps_idle(tmp_path, tmp_path_factory):
    import time

    from fixtures import tiny_checkpoint
    from localai_tpu.config import AppConfig
    from localai_tpu.core.manager import ModelManager

    import os
    os.environ["LOCALAI_JAX_PLATFORM"] = "cpu"
    ckpt = tiny_checkpoint(tmp_path_factory)
    cfg = ModelConfig(name="tiny", context_size=64, parallel=1, dtype="float32")
    cfg.parameters.model = ckpt
    cfg.prefill_buckets = [32]
    app = AppConfig(models_path="", watchdog_idle_timeout=1.0)
    mgr = ModelManager(app)
    try:
        h = mgr.load(cfg)
        assert h.alive()
        mgr.start_watchdog(interval=0.3)
        deadline = time.monotonic() + 20
        while mgr.get("tiny") is not None and time.monotonic() < deadline:
            time.sleep(0.3)
        assert mgr.get("tiny") is None, "watchdog never reaped idle backend"
        # the reaper drops the handle from the map BEFORE terminating the
        # child (and waits up to 10s for it to die) — poll, don't race it
        while h.alive() and time.monotonic() < deadline:
            time.sleep(0.1)
        assert not h.alive()
    finally:
        mgr.stop_all()
