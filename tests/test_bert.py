"""BERT encoder (universal embeddings role) vs HF torch parity on a
locally-built tiny random checkpoint."""
import numpy as np
import pytest


@pytest.fixture(scope="module")
def bert_ckpt(tmp_path_factory):
    import torch
    from transformers import BertConfig, BertModel

    d = str(tmp_path_factory.mktemp("bert"))
    torch.manual_seed(0)
    cfg = BertConfig(
        vocab_size=200, hidden_size=64, num_hidden_layers=2,
        num_attention_heads=4, intermediate_size=128,
        max_position_embeddings=64, type_vocab_size=2,
    )
    m = BertModel(cfg)
    m.eval()
    m.save_pretrained(d, safe_serialization=True)
    return d


def test_config_and_params_load(bert_ckpt):
    from localai_tpu.models.bert import load_bert_config, load_bert_params

    cfg = load_bert_config(bert_ckpt)
    assert cfg.hidden_size == 64 and cfg.num_layers == 2
    params = load_bert_params(bert_ckpt, cfg)
    assert params["layers"]["wqkv"].shape == (2, 64, 192)
    assert params["word_emb"].shape == (200, 64)


def test_hidden_states_match_hf(bert_ckpt):
    import torch
    from transformers import BertModel

    import jax.numpy as jnp
    from localai_tpu.models.bert import (
        bert_encode, load_bert_config, load_bert_params,
    )

    cfg = load_bert_config(bert_ckpt)
    params = load_bert_params(bert_ckpt, cfg)
    ids = np.array([[1, 5, 9, 13, 0, 0], [2, 6, 10, 0, 0, 0]], np.int64)
    lengths = np.array([4, 3], np.int32)
    mask = (np.arange(6)[None, :] < lengths[:, None]).astype(np.int64)

    ours = np.asarray(bert_encode(params, cfg, jnp.asarray(ids, jnp.int32),
                                  jnp.asarray(lengths)))
    m = BertModel.from_pretrained(bert_ckpt)
    m.eval()
    with torch.no_grad():
        ref = m(input_ids=torch.tensor(ids),
                attention_mask=torch.tensor(mask)).last_hidden_state.numpy()
    for b in range(2):
        n = lengths[b]
        np.testing.assert_allclose(ours[b, :n], ref[b, :n],
                                   rtol=2e-4, atol=2e-4)


def test_pooled_matches_hf_mean_pooling(bert_ckpt):
    import torch
    from transformers import BertModel

    import jax.numpy as jnp
    from localai_tpu.models.bert import (
        bert_pooled, load_bert_config, load_bert_params,
    )

    cfg = load_bert_config(bert_ckpt)
    params = load_bert_params(bert_ckpt, cfg)
    ids = np.array([[3, 7, 11, 15, 19, 0]], np.int64)
    lengths = np.array([5], np.int32)
    mask = (np.arange(6)[None, :] < lengths[:, None]).astype(np.int64)

    ours = np.asarray(bert_pooled(params, cfg, jnp.asarray(ids, jnp.int32),
                                  jnp.asarray(lengths)))
    m = BertModel.from_pretrained(bert_ckpt)
    m.eval()
    with torch.no_grad():
        h = m(input_ids=torch.tensor(ids),
              attention_mask=torch.tensor(mask)).last_hidden_state.numpy()
    mm = mask[..., None].astype(np.float32)
    ref = (h * mm).sum(1) / mm.sum(1)
    ref = ref / np.linalg.norm(ref, axis=-1, keepdims=True)
    np.testing.assert_allclose(ours, ref, rtol=2e-4, atol=2e-4)


def test_bert_embedder_buckets(bert_ckpt):
    from localai_tpu.models.bert import (
        BertEmbedder, load_bert_config, load_bert_params,
    )

    cfg = load_bert_config(bert_ckpt)
    params = load_bert_params(bert_ckpt, cfg)
    emb = BertEmbedder(cfg, params, buckets=(8, 16))
    vecs = emb.embed([[1, 2, 3], [4, 5], [6, 7, 8, 9, 10, 11, 12, 13, 14]])
    assert vecs.shape == (3, 64)
    np.testing.assert_allclose(np.linalg.norm(vecs, axis=-1), 1.0, rtol=1e-4)
    with pytest.raises(ValueError):
        emb.embed([list(range(1, 20))])


def test_servicer_embedding_only_load(bert_ckpt):
    """LoadModel on a BERT dir serves Embedding and rejects Predict."""
    from localai_tpu.backend.llm import LLMServicer
    from localai_tpu.backend import pb

    s = LLMServicer()
    r = s.LoadModel(pb.ModelOptions(model=bert_ckpt), None)
    assert r.success, r.message
    assert s.engine is None and s.embedder is not None
    res = s.Embedding(pb.PredictOptions(
        prompt_ids=[1, 2, 3]), _AbortContext())
    assert len(res.embeddings) == 64


class _AbortContext:
    def abort(self, code, details):
        raise AssertionError(f"aborted: {code} {details}")
