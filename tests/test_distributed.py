"""Multi-host serving tests: REAL multi-process jax.distributed over virtual
CPU devices — 2 processes × 4 devices = one 8-device global mesh, rank 0
driving the Engine, rank 1 replaying via the Follower protocol
(parallel/distributed.py). The reference has no automated multi-node tests
(SURVEY §4); this is the worker_llamacpp.go role under test.

These spawn fresh subprocesses (jax.distributed can't re-init in-process), so
they manage their own JAX env instead of the session conftest's.
"""
import json
import os
import socket
import subprocess
import sys

import pytest

from fixtures import tiny_checkpoint

_RANK_SCRIPT = r"""
import json, os, sys
rank = int(sys.argv[1]); nproc = int(sys.argv[2])
coord_port, rep_port, ckpt, out_path = sys.argv[3], int(sys.argv[4]), sys.argv[5], sys.argv[6]
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
import jax
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_default_matmul_precision", "float32")
from localai_tpu.parallel.distributed import Follower, Replicator, init_distributed
init_distributed(f"127.0.0.1:{coord_port}", nproc, rank)
assert len(jax.devices()) == 4 * nproc
assert len(jax.local_devices()) == 4

from localai_tpu.engine import Engine, EngineConfig, GenRequest
from localai_tpu.engine.loader import load_config, load_params, load_tokenizer
from localai_tpu.ops.sampling import SamplingParams
from localai_tpu.parallel.mesh import MeshConfig, build_mesh

mesh = build_mesh(MeshConfig(data=2, model=4))
cfg = load_config(ckpt, dtype="float32")
params = load_params(ckpt, cfg, dtype="float32", mesh=mesh)
tok = load_tokenizer(ckpt)

rep = Replicator(rep_port, nproc - 1, host="127.0.0.1") if rank == 0 else None
eng = Engine(cfg, params, tok, EngineConfig(
    max_slots=2, max_context=64, prefill_buckets=(16,), mesh=mesh,
    replicator=rep))

if rank == 0:
    rep.wait_for_followers()
    prompt = tok.encode("pack my box with five dozen")
    toks = [o.token_id for o in eng.generate(GenRequest(
        list(prompt), SamplingParams(temperature=0.0), max_tokens=8,
        ignore_eos=True))]
    rep.close()
    json.dump(toks, open(out_path, "w"))
else:
    chan = Follower(f"127.0.0.1:{rep_port}")
    eng.follow(chan)
    chan.close()
print(f"RANK_{rank}_DONE", flush=True)
"""

_SINGLE_SCRIPT = r"""
import json, os, sys
ckpt, out_path = sys.argv[1], sys.argv[2]
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
import jax
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_default_matmul_precision", "float32")
from localai_tpu.engine import Engine, EngineConfig, GenRequest
from localai_tpu.engine.loader import load_config, load_params, load_tokenizer
from localai_tpu.ops.sampling import SamplingParams
from localai_tpu.parallel.mesh import MeshConfig, build_mesh
mesh = build_mesh(MeshConfig(data=2, model=4))
cfg = load_config(ckpt, dtype="float32")
params = load_params(ckpt, cfg, dtype="float32", mesh=mesh)
tok = load_tokenizer(ckpt)
eng = Engine(cfg, params, tok, EngineConfig(
    max_slots=2, max_context=64, prefill_buckets=(16,), mesh=mesh))
prompt = tok.encode("pack my box with five dozen")
toks = [o.token_id for o in eng.generate(GenRequest(
    list(prompt), SamplingParams(temperature=0.0), max_tokens=8,
    ignore_eos=True))]
json.dump(toks, open(out_path, "w"))
"""


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    p = s.getsockname()[1]
    s.close()
    return p


def _env():
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    env.pop("JAX_PLATFORMS", None)
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env["PYTHONPATH"] = root + os.pathsep + env.get("PYTHONPATH", "")
    return env


def test_two_process_engine_matches_single_process(tmp_path_factory):
    """Greedy engine decode on a 2-host × 4-device distributed mesh must be
    token-identical to the single-process 8-device mesh run."""
    ckpt = tiny_checkpoint(tmp_path_factory)
    tmp = tmp_path_factory.mktemp("dist")
    coord, rep = _free_port(), _free_port()

    single_out = str(tmp / "single.json")
    r = subprocess.run([sys.executable, "-c", _SINGLE_SCRIPT, ckpt,
                        single_out],
                       env=_env(), capture_output=True, text=True, timeout=300)
    assert r.returncode == 0, r.stderr[-2000:]
    expect = json.load(open(single_out))
    assert len(expect) == 8

    dist_out = str(tmp / "dist.json")
    procs = [
        subprocess.Popen(
            [sys.executable, "-c", _RANK_SCRIPT, str(rank), "2", str(coord),
             str(rep), ckpt, dist_out],
            env=_env(), stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True)
        for rank in range(2)
    ]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=300)
            outs.append(out)
    finally:
        for p in procs:
            p.kill()
    for rank, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"rank {rank}:\n{out[-3000:]}"
        assert f"RANK_{rank}_DONE" in out
    got = json.load(open(dist_out))
    assert got == expect
