"""Serving SLO layer tests (ISSUE 11): streaming-histogram math against a
numpy reference, the flat GetMetrics round-trip, Prometheus exposition
format, flight-recorder rings + auto-dump on an injected engine crash, the
disabled-path gate, and the per-request timings surface end to end.

Cheap units run in tier-1; everything that drives an engine or the HTTP
stack carries `slow`.
"""
import glob
import json
import math
import os
import threading
import time

import numpy as np
import pytest
import requests
import yaml

from fixtures import tiny_checkpoint

from localai_tpu.telemetry.metrics import (
    FlightRecorder, Hist, SLORegistry, parse_flat, snapshot_from_hists,
)
from localai_tpu.telemetry.profiler import BUCKETS_S


# ------------------------------------------------------------------ units


def _ref_edge(samples, q):
    """The bucket upper bound Hist.percentile must report: the edge of the
    first bucket whose cumulative count reaches q*n (numpy reference)."""
    edges = np.asarray(BUCKETS_S)
    idx = np.searchsorted(edges, samples, side="left")   # first ub >= v
    counts = np.bincount(idx, minlength=len(edges))
    target = q * len(samples)
    acc = 0
    for i, n in enumerate(counts):
        acc += n
        if acc >= target and n:
            return edges[i] if math.isfinite(edges[i]) else edges[i - 1]
    return edges[-2]


def test_hist_percentile_matches_numpy_reference():
    rng = np.random.default_rng(11)
    # log-uniform over the interesting range, plus exact-edge values (the
    # `v <= ub` boundary) and overflow samples for the open-ended bucket
    samples = list(np.exp(rng.uniform(np.log(60e-6), np.log(4.0), 500)))
    samples += [1e-3, 20e-3, 1.0] * 5 + [7.5, 11.0]
    h = Hist()
    for v in samples:
        h.observe(v)
    assert h.count == len(samples)
    assert abs(h.sum - sum(samples)) < 1e-9 * len(samples)
    for q in (0.5, 0.9, 0.95, 0.99):
        got = h.percentile(q)
        assert got == _ref_edge(samples, q), q
        # the reported edge brackets the true quantile from above (or is
        # the honest floor for overflow samples)
        true = float(np.quantile(samples, q))
        if true <= BUCKETS_S[-2]:
            assert got >= true * 0.999
    # coarse but bounded: one bucket of slack around the true p50
    assert h.percentile(0.5) <= BUCKETS_S[-2]


def test_hist_open_bucket_reports_last_finite_edge():
    h = Hist()
    for _ in range(10):
        h.observe(100.0)          # everything in the +inf bucket
    assert h.percentile(0.5) == BUCKETS_S[-2]
    assert h.percentile(0.99) == BUCKETS_S[-2]


def test_hist_weighted_observe_equals_repeats():
    a, b = Hist(), Hist()
    for v in (0.8e-3, 3e-3, 40e-3, 0.3):
        a.observe(v, n=5)
        for _ in range(5):
            b.observe(v)
    assert a.counts == b.counts
    assert a.count == b.count == 20
    assert abs(a.sum - b.sum) < 1e-12
    for q in (0.5, 0.95):
        assert a.percentile(q) == b.percentile(q)


def test_registry_flat_parse_roundtrip():
    reg = SLORegistry()
    rng = np.random.default_rng(7)
    for path in ("loop", "ragged"):
        for v in rng.uniform(1e-3, 0.5, 40):
            reg.observe("ttft", path, float(v))
            reg.observe("e2e", path, float(v) * 4)
    reg.observe("tpot", "loop", 2e-3, n=64)
    flat = reg.flat()
    # headline keys the satellite requires, straight from the histogram
    assert flat["ttft_ms_p50"] == reg.merged("ttft").percentile(0.5) * 1e3
    assert flat["ttft_ms_p95"] == reg.merged("ttft").percentile(0.95) * 1e3
    back = parse_flat(flat)
    assert set(back) == {("ttft", "loop"), ("ttft", "ragged"),
                         ("e2e", "loop"), ("e2e", "ragged"),
                         ("tpot", "loop")}
    for key, h in reg._hists.items():
        assert back[key].counts == h.counts, key
        assert back[key].count == h.count
        assert abs(back[key].sum - h.sum) < 1e-9
    # the scrape-side snapshot equals the in-process one
    assert snapshot_from_hists(back) == reg.snapshot()


def test_snapshot_shape_and_by_path():
    reg = SLORegistry()
    reg.observe("ttft", "loop", 5e-3)
    reg.observe("ttft", "ragged", 50e-3)
    snap = reg.snapshot()
    e = snap["ttft"]
    assert e["count"] == 2 and e["mean_ms"] > 0
    assert set(e["by_path"]) == {"loop", "ragged"}
    assert e["by_path"]["ragged"]["p50_ms"] >= e["by_path"]["loop"]["p50_ms"]
    for k in ("p50_ms", "p95_ms", "p99_ms"):
        assert k in e
    assert "tpot" not in snap     # no samples → no entry
    reg.reset()
    assert reg.snapshot() == {}


def test_prometheus_exposition_format():
    """_SLOCollector must emit a well-formed histogram: cumulative monotone
    buckets ending at le="+Inf" == _count, and a consistent _sum."""
    from localai_tpu.server import http

    if not http._HAVE_PROM:
        pytest.skip("prometheus_client not available")
    from prometheus_client import generate_latest

    reg = SLORegistry()
    rng = np.random.default_rng(3)
    for v in rng.uniform(1e-3, 2.0, 100):
        reg.observe("ttft", "loop", float(v))
    http._SLO_SCRAPE["obs-test"] = parse_flat(reg.flat())
    try:
        text = generate_latest().decode()
    finally:
        http._SLO_SCRAPE.pop("obs-test", None)
    lines = [ln for ln in text.splitlines()
             if ln.startswith("localai_request_ttft_seconds")
             and 'model="obs-test"' in ln]
    assert lines, text[:2000]
    buckets, count, total = [], None, None
    for ln in lines:
        name, val = ln.rsplit(" ", 1)
        if "_bucket{" in name:
            le = name.split('le="')[1].split('"')[0]
            buckets.append((le, float(val)))
        elif name.startswith("localai_request_ttft_seconds_count"):
            count = float(val)
        elif name.startswith("localai_request_ttft_seconds_sum"):
            total = float(val)
    assert count == 100 and total == pytest.approx(reg.merged("ttft").sum)
    # every edge present, cumulative and monotone, +Inf last and == count
    assert [b[0] for b in buckets][-1] == "+Inf"
    assert len(buckets) == len(BUCKETS_S)
    vals = [b[1] for b in buckets]
    assert vals == sorted(vals)
    assert vals[-1] == count


def test_flightrec_rings_wrap_and_auto_dump(tmp_path, monkeypatch):
    rec = FlightRecorder(requests=8, ticks=4, events=4)
    for i in range(20):
        rec.record_request({"request_id": f"r{i}"})
        rec.record_tick({"tick": i})
        rec.record_event("tripwire", n=i)
    assert len(rec.requests) == 8 and len(rec.ticks) == 4
    assert [r["request_id"] for r in rec.requests] == \
        [f"r{i}" for i in range(12, 20)]         # newest survive the wrap
    assert all("t_wall" in e for e in rec.events)

    monkeypatch.setenv("LOCALAI_FLIGHTREC_DIR", str(tmp_path))
    path = rec.auto_dump("tripwire:test")
    assert path and os.path.exists(path)
    dump = json.loads(open(path).read())
    assert dump["reason"] == "tripwire:test"
    assert dump["requests"][-1]["request_id"] == "r19"
    assert dump["events"][-1]["kind"] == "tripwire"
    # the cap: a crash loop cannot fill the disk
    paths = {path}
    for _ in range(FlightRecorder.MAX_AUTO_DUMPS + 4):
        p = rec.auto_dump("again")
        if p:
            paths.add(p)
    assert len(paths) == FlightRecorder.MAX_AUTO_DUMPS
    assert rec.auto_dump("capped") == ""


def test_metrics_enable_gate():
    from localai_tpu import telemetry

    try:
        telemetry.set_metrics_enabled(False)
        assert telemetry.metrics_enabled() is False
        assert telemetry.maybe_slo() is None
        telemetry.set_metrics_enabled(True)
        reg = telemetry.maybe_slo()
        assert isinstance(reg, SLORegistry)
        # forcing the gate again resets the singleton (fresh registry)
        telemetry.set_metrics_enabled(True)
        assert telemetry.maybe_slo() is not reg
    finally:
        telemetry.set_metrics_enabled(None)


def test_stale_artifact_embeds_probe_report(tmp_path, capsys, monkeypatch):
    """A probe timeout must leave a debuggable trail: the stale scoreboard
    line carries the probe report — stuck phase + thread stack dump — not a
    bare timeout string."""
    import bench

    d = tmp_path / "runs"
    d.mkdir()
    (d / "chip.json").write_text(json.dumps({
        "device": "TPU v5e", "value": 726.7,
        "recorded_at": "2026-07-30T10:00:00"}))

    def fake_probe(args):
        args.probe_report = {
            "ok": False, "phases": list(bench.PROBE_PHASES),
            "attempts": [{
                "timeout_s": 60, "rc": 1, "timed_out": True, "ok": False,
                "phases_s": {"plugin_handshake": 0.01},
                "last_phase": "client_init", "stuck_phase": "client_init",
                "stack_dump": "Timeout (0:00:55)!\nThread 0x... (most recent"
                              " call first):\n  File \"probe.py\"...",
            }],
        }
        return True, "probe timed out (stuck in client_init)", "cpu"

    monkeypatch.setattr(bench, "probe_accelerator", fake_probe)
    rc = bench.main(["--runs-dir", str(d)])
    assert rc == 0
    line = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert line["stale"] is True
    report = line["probe_report"]
    assert report["ok"] is False
    attempt = report["attempts"][0]
    assert attempt["stuck_phase"] == "client_init"
    assert "Thread" in attempt["stack_dump"]


def test_tripwire_trip_records_event_and_dumps(tmp_path, monkeypatch):
    """dispatch_budget leaves a black-box record when it trips."""
    from localai_tpu import telemetry
    from localai_tpu.testing.tripwires import dispatch_budget

    class _FakeEngine:
        metrics = {"decode_dispatches": 0, "tokens_generated": 0}

    monkeypatch.setenv("LOCALAI_FLIGHTREC_DIR", str(tmp_path))
    telemetry.reset_flightrec()
    try:
        eng = _FakeEngine()
        with pytest.raises(AssertionError, match="dispatch budget"):
            with dispatch_budget(eng, max_per_128_tokens=1.0):
                eng.metrics["decode_dispatches"] += 50
                eng.metrics["tokens_generated"] += 16
        rec = telemetry.flightrec()
        trips = [e for e in rec.events if e["kind"] == "tripwire"]
        assert trips and trips[-1]["guard"] == "dispatch_budget"
        assert trips[-1]["dispatches"] == 50
        dumps = glob.glob(str(tmp_path / "*tripwire*"))
        assert dumps
        assert json.loads(open(dumps[0]).read())["reason"].startswith(
            "tripwire:")
    finally:
        telemetry.reset_flightrec()


# ------------------------------------------------- engine-driving (slow)


@pytest.fixture(scope="module")
def ckpt(tmp_path_factory):
    return tiny_checkpoint(tmp_path_factory)


def _engine(ckpt, **ec_kw):
    from localai_tpu.engine import (
        Engine, EngineConfig, Tokenizer, load_config, load_params,
    )

    cfg = load_config(ckpt, dtype="float32")
    params = load_params(ckpt, cfg)
    tok = Tokenizer.from_dir(ckpt)
    return Engine(cfg, params, tok, EngineConfig(
        max_slots=4, max_context=128, prefill_buckets=(32, 64),
        prefill_chunk=64, **ec_kw)), tok


def _run_collect(eng, tok, n_req=4, max_tokens=8):
    """Drive the engine to completion, returning each request's final
    (terminal) StepOutput."""
    from localai_tpu.engine import GenRequest

    outs = [eng.submit(GenRequest(
        prompt_ids=tok.encode(f"request number {i} says"),
        max_tokens=max_tokens, ignore_eos=True))[1] for i in range(n_req)]
    while eng.step():
        pass
    finals = []
    for q in outs:
        while not q.empty():
            o = q.get_nowait()
            if o.finished:
                finals.append(o)
    return finals


@pytest.mark.slow
def test_engine_timeline_integrity_concurrent(ckpt):
    """4 concurrent streams: every terminal StepOutput carries a complete
    phase timeline, the registry counts match, and the flight recorder's
    request ring holds every timeline."""
    from localai_tpu import telemetry

    telemetry.set_metrics_enabled(True)   # fresh registry
    telemetry.reset_flightrec()
    try:
        eng, tok = _engine(ckpt)
        assert eng._slo is not None and eng._flightrec is not None
        n, max_tokens = 4, 8
        finals = _run_collect(eng, tok, n_req=n, max_tokens=max_tokens)
        assert len(finals) == n
        for o in finals:
            t = o.timings
            assert t is not None, o
            assert t["request_id"].startswith("rid-")
            assert t["path"] in ("loop", "dense", "ragged", "spec")
            assert t["generated_tokens"] == max_tokens
            assert t["dispatches"] >= 1
            assert t["kv_policy"]
            assert t["queue_wait_ms"] >= 0
            assert t["ttft_ms"] is not None and t["ttft_ms"] > 0
            assert t["e2e_ms"] >= t["ttft_ms"]
            assert t["finish_reason"] == "length"
        reg = eng._slo
        assert reg.merged("ttft").count == n
        assert reg.merged("e2e").count == n
        assert reg.merged("queue_wait").count == n
        # TPOT is token-weighted and burst-amortized: never more samples
        # than post-first tokens (tail tokens of a final burst may share
        # the finishing host arrival and go unobserved)
        assert reg.merged("tpot").count <= n * (max_tokens - 1)
        flat = reg.flat()
        assert flat["ttft_ms_p50"] > 0 and flat["ttft_ms_p95"] > 0
        rec = telemetry.flightrec()
        ring_ids = {r["request_id"] for r in rec.requests}
        assert {t["request_id"] for t in
                (o.timings for o in finals)} <= ring_ids
    finally:
        telemetry.set_metrics_enabled(None)
        telemetry.reset_flightrec()


@pytest.mark.slow
def test_engine_metrics_disabled_no_timings(ckpt):
    """LOCALAI_METRICS=0: the engine holds no registry/recorder and the
    outputs carry no timelines — the hot path pays one None-check."""
    from localai_tpu import telemetry

    telemetry.set_metrics_enabled(False)
    telemetry.reset_flightrec()
    try:
        eng, tok = _engine(ckpt)
        assert eng._slo is None and eng._flightrec is None
        finals = _run_collect(eng, tok, n_req=2, max_tokens=8)
        assert len(finals) == 2
        assert all(o.timings is None for o in finals)
        assert len(telemetry.flightrec().requests) == 0

        # overhead guard (PR 2 precedent): recording on the SAME engine must
        # stay within noise of disabled — the per-token cost is a few dict
        # increments, nowhere near a device dispatch
        def timed():
            t0 = time.perf_counter()
            _run_collect(eng, tok, n_req=2, max_tokens=32)
            return time.perf_counter() - t0

        timed()                      # warm
        disabled = min(timed() for _ in range(3))
        telemetry.set_metrics_enabled(True)
        eng._slo = telemetry.maybe_slo()
        eng._flightrec = telemetry.flightrec()
        enabled = min(timed() for _ in range(3))
        assert eng._slo.merged("ttft").count >= 2   # it did record
        assert enabled < disabled * 2.0, (
            f"SLO recording too expensive: {enabled:.3f}s vs "
            f"{disabled:.3f}s disabled")
    finally:
        telemetry.set_metrics_enabled(None)
        telemetry.reset_flightrec()


@pytest.mark.slow
def test_engine_crash_auto_dumps_flightrec(ckpt, tmp_path, monkeypatch):
    """Injected fatal step (LOCALAI_FAULT=engine_crash) while a request is
    mid-generation: the dying request gets a terminal 'error' chunk WITH its
    timeline, and the flight recorder auto-dumps a post-mortem containing
    that timeline + the engine_fatal event."""
    from localai_tpu import telemetry
    from localai_tpu.engine import GenRequest
    from localai_tpu.testing import faults

    telemetry.set_metrics_enabled(True)
    telemetry.reset_flightrec()
    monkeypatch.setenv("LOCALAI_FLIGHTREC_DIR", str(tmp_path))
    monkeypatch.delenv("LOCALAI_FAULT_DIR", raising=False)
    faults._local_counts.pop("engine_crash", None)
    # small fused blocks so one step cannot finish the whole request (the
    # default single-dispatch loop would emit all 64 tokens at once and the
    # crash would find nothing in flight)
    eng, tok = _engine(ckpt, max_restarts=0, decode_loop=4, decode_block=2)
    try:
        rid, q = eng.submit(GenRequest(
            prompt_ids=tok.encode("doomed request says"),
            max_tokens=64, ignore_eos=True))
        # step synchronously until the request is mid-generation (started
        # timeline, not finished), THEN arm the fault and hand the engine
        # to the serving loop: its next step() crashes deterministically
        first = None
        for _ in range(500):
            eng.step()
            if not q.empty():
                first = q.get_nowait()
                break
        assert first is not None and not first.finished
        monkeypatch.setenv("LOCALAI_FAULT", "engine_crash::1")
        eng.start()
        terminal = None
        deadline = time.monotonic() + 120
        while time.monotonic() < deadline:
            o = q.get(timeout=60)
            if o.finished:
                terminal = o
                break
        assert terminal is not None
        assert terminal.finish_reason == "error"
        assert terminal.timings is not None
        assert terminal.timings["finish_reason"] == "error"
        # the terminal chunk is enqueued before _loop writes the black box —
        # give the dying loop a beat
        dumps = []
        while not dumps and time.monotonic() < deadline:
            dumps = glob.glob(str(tmp_path / "*engine_fatal*.json"))
            time.sleep(0.05)
        assert dumps, os.listdir(tmp_path)
        dump = json.loads(open(dumps[0]).read())
        assert dump["reason"] == "engine_fatal"
        assert any(e["kind"] == "engine_fatal" for e in dump["events"])
        # the black box holds the dying request's timeline
        assert any(r.get("finish_reason") == "error"
                   for r in dump["requests"]), dump["requests"]
    finally:
        monkeypatch.delenv("LOCALAI_FAULT", raising=False)
        eng.stop()
        telemetry.set_metrics_enabled(None)
        telemetry.reset_flightrec()


# --------------------------------------------- HTTP stack surfaces (slow)


@pytest.fixture(scope="module")
def obs_stack(tmp_path_factory):
    """HTTP server + real backend subprocess with metrics at their default
    (ON) and trace/profile untouched — the SLO surfaces must work without
    any opt-in env."""
    import asyncio
    import socket

    from aiohttp import web

    from localai_tpu.config import AppConfig, ModelConfigLoader
    from localai_tpu.core.manager import ModelManager
    from localai_tpu.server.http import API

    ckpt = tiny_checkpoint(tmp_path_factory)
    models = tmp_path_factory.mktemp("models-obs")
    (models / "tiny.yaml").write_text(yaml.safe_dump({
        "name": "tiny",
        "backend": "llm",
        "context_size": 128,
        "parallel": 4,
        "dtype": "float32",
        "prefill_buckets": [32, 64],
        "parameters": {"model": ckpt, "temperature": 0.0, "max_tokens": 8},
    }))

    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()

    os.environ["LOCALAI_JAX_PLATFORM"] = "cpu"
    app_cfg = AppConfig(address=f"127.0.0.1:{port}", models_path=str(models),
                        parallel_requests=4)
    configs = ModelConfigLoader(str(models))
    manager = ModelManager(app_cfg)
    api = API(app_cfg, configs, manager)

    loop = asyncio.new_event_loop()

    def run():
        asyncio.set_event_loop(loop)
        runner = web.AppRunner(api.app)
        loop.run_until_complete(runner.setup())
        site = web.TCPSite(runner, "127.0.0.1", port)
        loop.run_until_complete(site.start())
        loop.run_forever()

    t = threading.Thread(target=run, daemon=True)
    t.start()
    base = f"http://127.0.0.1:{port}"
    for _ in range(50):
        try:
            requests.get(base + "/healthz", timeout=1)
            break
        except requests.ConnectionError:
            time.sleep(0.1)
    yield base, manager
    manager.stop_all()
    loop.call_soon_threadsafe(loop.stop)


@pytest.mark.slow
def test_sse_timings_and_slo_surfaces(obs_stack):
    """One streamed chat: the final usage chunk carries the llama.cpp-style
    `timings` block, and all three export surfaces agree — /debug/slo,
    /debug/flightrec, and the /metrics histogram series."""
    base, _ = obs_stack
    r = requests.post(base + "/v1/chat/completions", json={
        "model": "tiny", "stream": True,
        "messages": [{"role": "user", "content": "stream please"}],
        "max_tokens": 6,
    }, stream=True, timeout=300)
    assert r.status_code == 200, r.text
    timings = None
    for line in r.iter_lines():
        if not line or not line.startswith(b"data: "):
            continue
        payload = line[6:]
        if payload == b"[DONE]":
            break
        chunk = json.loads(payload)
        if "timings" in chunk:
            timings = chunk["timings"]
    assert timings is not None, "no timings block in the SSE stream"
    assert timings["path"] in ("loop", "dense", "ragged", "spec")
    assert timings["ttft_ms"] > 0
    assert timings["e2e_ms"] >= timings["ttft_ms"]
    assert timings["generated_tokens"] >= 1

    slo = requests.get(base + "/debug/slo", timeout=60).json()
    assert slo["metrics_enabled"] is True
    assert slo["bucket_edges_s"] == [b for b in BUCKETS_S
                                     if b != float("inf")]
    tiny = slo["models"]["tiny"]
    assert tiny["ttft"]["count"] >= 1
    assert tiny["e2e"]["p50_ms"] > 0

    rec = requests.get(base + "/debug/flightrec", timeout=60).json()
    reqs = rec["models"]["tiny"]["requests"]
    assert reqs and any(t["generated_tokens"] >= 1 for t in reqs)
    assert "events" in rec["server"]

    m = requests.get(base + "/metrics", timeout=60).text
    assert "localai_request_ttft_seconds_bucket" in m
    assert 'le="+Inf"' in m
    assert "localai_request_e2e_seconds_count" in m
    # the mis-typed supervision gauge is now a counter
    assert "# TYPE localai_backend_supervision_total counter" in m


@pytest.mark.slow
def test_getmetrics_histogram_keys(obs_stack):
    """The backend's GetMetrics map carries the flat hist_* keys plus the
    histogram-backed ttft_ms_p50/p95 (and the legacy ttft_ms_last)."""
    base, manager = obs_stack
    # ensure at least one request has been served
    r = requests.post(base + "/v1/chat/completions", json={
        "model": "tiny",
        "messages": [{"role": "user", "content": "warm"}],
        "max_tokens": 4,
    }, timeout=300)
    assert r.status_code == 200, r.text
    h = manager.get("tiny")
    m = h.client.metrics()
    assert any(k.startswith("hist_ttft__") for k in m), sorted(m)[:40]
    assert m["ttft_ms_p50"] > 0 and m["ttft_ms_p95"] >= m["ttft_ms_p50"]
    assert "ttft_ms_last" in m          # kept for one release
    hists = parse_flat(m)
    snap = snapshot_from_hists(hists)
    assert snap["ttft"]["count"] >= 1
