"""Paged fast path (PR: scatter-append + table-aware fused decode):

- dense-vs-paged stream parity through the FUSED decode block at 8 and 32
  slots (the 32-slot sweep is `slow` — tier-1 runs the 8-slot one),
- a jaxpr-inspection proof that the compiled paged decode step contains no
  gather/scatter over the full [NB, KVH, BS, D] pool on the Pallas tier
  (`paged_view` is CPU-reference-tier only, asserted separately),
- the block-level prefix cache: a second admission of a shared 256-token
  prompt maps the cached physical pages into its table (2 fewer fresh
  blocks) and produces the identical stream.
"""
import threading

import numpy as np
import pytest

from fixtures import tiny_checkpoint
from localai_tpu.engine import (
    Engine, EngineConfig, GenRequest, Tokenizer, load_config, load_params,
)
from localai_tpu.ops.sampling import SamplingParams


@pytest.fixture(scope="module")
def loaded(tmp_path_factory):
    ckpt = tiny_checkpoint(tmp_path_factory, max_position=768)
    cfg = load_config(ckpt, dtype="float32")
    params = load_params(ckpt, cfg)
    tok = Tokenizer.from_dir(ckpt)
    return cfg, params, tok


def _collect(eng, reqs):
    eng.start()
    outs = {}

    def run(i, req):
        rid, q = eng.submit(req)
        ids = []
        while True:
            o = q.get(timeout=300)
            if o.token_id >= 0:
                ids.append(o.token_id)
            if o.finished:
                outs[i] = ids
                return

    ths = [threading.Thread(target=run, args=(i, r))
           for i, r in enumerate(reqs)]
    [t.start() for t in ths]
    [t.join(timeout=600) for t in ths]
    eng.stop()
    return outs


def _reqs(cfg, n, max_tokens=24):
    """Distinct short prompts (single-shot prefill — the fused decode block
    is then the only multi-token device path a request rides)."""
    rng = np.random.default_rng(7)
    return [GenRequest(
        rng.integers(5, cfg.vocab_size, 6).tolist(),
        SamplingParams(temperature=0.8, seed=1000 + i),
        max_tokens=max_tokens, ignore_eos=True) for i in range(n)]


def _parity(loaded, slots, kv_pages):
    cfg, params, tok = loaded
    ec = dict(max_slots=slots, max_context=256, prefill_buckets=(32,),
              decode_block=16, prompt_cache=False)
    ref = _collect(Engine(cfg, params, tok, EngineConfig(**ec)),
                   _reqs(cfg, slots))
    got = _collect(Engine(cfg, params, tok,
                          EngineConfig(kv_pages=kv_pages, **ec)),
                   _reqs(cfg, slots))
    assert sorted(ref) == sorted(got) == list(range(slots))
    for i in ref:
        assert got[i] == ref[i], f"slot {i} diverged paged vs dense"


def test_fused_block_parity_8_slots(loaded):
    _parity(loaded, 8, kv_pages=12)


@pytest.mark.slow
def test_fused_block_parity_32_slots(loaded):
    _parity(loaded, 32, kv_pages=40)


# --------------------------------------------------------- jaxpr inspection

def _jaxpr_pool_hits(jaxpr, pool_elems):
    """All gather/scatter-family eqns (recursively, through scan/cond/jit
    bodies) touching an aval at least as big as the block pool."""
    bad = []

    def visit(jx):
        for eqn in jx.eqns:
            if eqn.primitive.name in (
                    "gather", "scatter", "scatter-add", "scatter-mul",
                    "scatter_apply", "dynamic_update_slice"):
                for v in list(eqn.invars) + list(eqn.outvars):
                    aval = getattr(v, "aval", None)
                    if aval is not None and getattr(aval, "size", 0) \
                            >= pool_elems:
                        bad.append((eqn.primitive.name, tuple(aval.shape)))
            for p in eqn.params.values():
                for sub in (p if isinstance(p, (list, tuple)) else [p]):
                    sub = getattr(sub, "jaxpr", sub)  # ClosedJaxpr → Jaxpr
                    if hasattr(sub, "eqns"):
                        visit(sub)
    visit(jaxpr.jaxpr)
    return bad


def _decode_step_jaxpr(monkeypatch, force_pallas):
    import jax
    import jax.numpy as jnp

    from localai_tpu.models.llama import (
        LlamaConfig, decode_step, init_params,
    )
    from localai_tpu.ops.paged import BLOCK, init_paged
    from localai_tpu.ops.rope import rope_table

    if force_pallas:
        monkeypatch.setenv("LOCALAI_FORCE_PALLAS", "1")
        monkeypatch.delenv("LOCALAI_NO_PALLAS", raising=False)
    else:
        monkeypatch.setenv("LOCALAI_NO_PALLAS", "1")
        monkeypatch.delenv("LOCALAI_FORCE_PALLAS", raising=False)
    cfg = LlamaConfig(vocab_size=128, hidden_size=64, intermediate_size=128,
                      num_layers=2, num_heads=4, num_kv_heads=2, head_dim=32,
                      max_position=512, dtype="float32")
    B, MAXB, NB = 4, 2, 9
    params = init_params(cfg, jax.random.PRNGKey(0), jnp.float32)
    cos, sin = rope_table(cfg.rope, MAXB * BLOCK)
    kc, vc = init_paged(cfg.num_layers, NB, cfg.num_kv_heads, cfg.head_dim,
                        jnp.float32)
    tokens = jnp.ones((B,), jnp.int32)
    lengths = jnp.full((B,), 5, jnp.int32)
    active = jnp.ones((B,), bool)
    table = jnp.zeros((B, MAXB), jnp.int32)

    jaxpr = jax.make_jaxpr(
        lambda kc, vc, tokens, lengths, active, table: decode_step(
            params, cfg, tokens, lengths, cos, sin, kc, vc, active, table)
    )(kc, vc, tokens, lengths, active, table)
    pool_elems = NB * cfg.num_kv_heads * BLOCK * cfg.head_dim
    return jaxpr, pool_elems


def test_paged_decode_jaxpr_no_full_pool_ops(monkeypatch):
    """Acceptance (b): on the Pallas tier the fused paged decode step's
    jaxpr contains NO gather/scatter over anything pool-sized — KV reads
    stream through the table inside ragged_decode, KV writes go through the
    scatter-append kernel."""
    jaxpr, pool_elems = _decode_step_jaxpr(monkeypatch, force_pallas=True)
    hits = _jaxpr_pool_hits(jaxpr, pool_elems)
    assert not hits, f"full-pool gather/scatter on the hot path: {hits}"


def test_paged_decode_jaxpr_detector_not_vacuous(monkeypatch):
    """The same detector DOES fire on the XLA reference tier (paged_view
    gather + index scatter) — proving the assertion above has teeth."""
    jaxpr, pool_elems = _decode_step_jaxpr(monkeypatch, force_pallas=False)
    assert _jaxpr_pool_hits(jaxpr, pool_elems)


def test_fused_decode_never_calls_paged_view(loaded, monkeypatch):
    """paged_view is the CPU-reference tier: the Pallas-tier serving loop
    (short prompts → single-shot prefill + fused decode) must never touch
    it."""
    import localai_tpu.ops.paged as paged_mod

    cfg, params, tok = loaded
    monkeypatch.setenv("LOCALAI_FORCE_PALLAS", "1")

    def boom(*a, **kw):
        raise AssertionError("paged_view called on the Pallas hot path")

    monkeypatch.setattr(paged_mod, "paged_view", boom)
    eng = Engine(cfg, params, tok, EngineConfig(
        max_slots=2, max_context=256, prefill_buckets=(32,), decode_block=8,
        kv_pages=6, prompt_cache=False))
    outs = _collect(eng, _reqs(cfg, 2, max_tokens=10))
    assert sorted(outs) == [0, 1]
    assert all(len(v) == 10 for v in outs.values())


# ------------------------------------------------------ block prefix cache

def _drain(eng, q):
    ids = []
    while True:
        eng.step()
        while not q.empty():
            o = q.get_nowait()
            if o.token_id >= 0:
                ids.append(o.token_id)
            if o.finished:
                return ids


def _count_takes(eng, monkeypatch):
    taken = []
    real = eng._take_blocks

    def counting(k, keep_slot):
        got = real(k, keep_slot)
        if got is not None:
            taken.extend(got)
        return got

    monkeypatch.setattr(eng, "_take_blocks", counting)
    return taken


def test_prefix_cache_shares_blocks_across_slots(loaded, monkeypatch):
    """Acceptance (c): a second admission sharing a 256-token prefix maps
    the 2 cached physical blocks into its own table — 2 fewer fresh blocks
    than a cold admission of the same prompt — and the stream is identical.

    Layout: p1 runs and releases (its 2 full blocks get hash-registered);
    a live request then occupies the retaining slot, so p2 lands in a COLD
    slot and can only reuse via the block-level index, not the slot cache."""
    cfg, params, tok = loaded
    rng = np.random.default_rng(3)
    base = rng.integers(5, cfg.vocab_size, 256).tolist()
    p1 = base + rng.integers(5, cfg.vocab_size, 40).tolist()
    p2 = base + rng.integers(5, cfg.vocab_size, 30).tolist()
    assert p1[256:286] != p2[256:]
    greedy = SamplingParams(temperature=0.0)
    ec = EngineConfig(max_slots=2, max_context=512, prefill_buckets=(64,),
                      prefill_chunk=128, decode_block=8, kv_pages=16)

    eng = Engine(cfg, params, tok, ec)
    _, q = eng.submit(GenRequest(list(p1), greedy, max_tokens=8,
                                 ignore_eos=True))
    _drain(eng, q)
    # pin the slot that retains p1's pages with a LIVE request, so p2 gets
    # the other (cold) slot: only the hash index can serve its prefix.
    # max_tokens must exceed the engine's decode_loop (64): a shorter pin
    # finishes inside the first while-loop dispatch and frees the slot
    # before p2 is admitted
    _, q_live = eng.submit(GenRequest(list(p1), greedy, max_tokens=200,
                                      ignore_eos=True))
    while q_live.empty():
        eng.step()
    hits0 = eng.metrics["prompt_cache_hits"]
    taken = _count_takes(eng, monkeypatch)
    _, q2 = eng.submit(GenRequest(list(p2), greedy, max_tokens=8,
                                  ignore_eos=True))
    warm_ids = _drain(eng, q2)
    warm_takes = len(taken)
    assert eng.metrics["prompt_cache_hits"] == hits0 + 1
    assert eng.metrics["prompt_tokens_reused"] >= 256

    cold_eng = Engine(cfg, params, tok, ec)
    cold_taken = _count_takes(cold_eng, monkeypatch)
    _, qc = cold_eng.submit(GenRequest(list(p2), greedy, max_tokens=8,
                                       ignore_eos=True))
    cold_ids = _drain(cold_eng, qc)
    assert warm_ids == cold_ids, "shared prefix pages changed the logits"
    assert len(cold_taken) - warm_takes == 2, (
        f"expected exactly 2 fewer fresh blocks (cold {len(cold_taken)}, "
        f"warm {warm_takes})")


def test_prefix_cache_cow_never_corrupts_the_donor(loaded):
    """The borrower writes only past the shared prefix: re-running the DONOR
    prompt after a borrower generated from the shared pages must reproduce
    the original stream (a write into a shared page would corrupt it)."""
    cfg, params, tok = loaded
    rng = np.random.default_rng(11)
    base = rng.integers(5, cfg.vocab_size, 256).tolist()
    p1 = base + rng.integers(5, cfg.vocab_size, 20).tolist()
    p2 = base + rng.integers(5, cfg.vocab_size, 10).tolist()
    greedy = SamplingParams(temperature=0.0)
    ec = EngineConfig(max_slots=2, max_context=512, prefill_buckets=(64,),
                      prefill_chunk=128, decode_block=8, kv_pages=16)
    eng = Engine(cfg, params, tok, ec)

    def run(p):
        _, q = eng.submit(GenRequest(list(p), greedy, max_tokens=8,
                                     ignore_eos=True))
        return _drain(eng, q)

    first = run(p1)
    run(p2)          # borrows p1's prefix pages (or its own retained slot)
    again = run(p1)  # donor replay — byte-identical or a page was written
    assert first == again
