"""Engine-integrated speculative decoding (engine/spec.py): the serving-path
DraftModel role (reference backend.proto:218,150). Verifies greedy parity
with the non-spec engine, >1 token/step acceptance with a perfect draft, and
concurrent-slot + chunked-prefill operation."""
import jax
import numpy as np
import pytest

from localai_tpu.engine import Engine, EngineConfig, GenRequest
from localai_tpu.models.llama import LlamaConfig, init_params
from localai_tpu.ops.sampling import SamplingParams

TARGET = LlamaConfig(vocab_size=128, hidden_size=64, intermediate_size=128,
                     num_layers=2, num_heads=4, num_kv_heads=2, head_dim=16,
                     max_position=256, dtype="float32")
DRAFT = LlamaConfig(vocab_size=128, hidden_size=32, intermediate_size=64,
                    num_layers=1, num_heads=2, num_kv_heads=2, head_dim=16,
                    max_position=256, dtype="float32")


@pytest.fixture(scope="module")
def models():
    return (init_params(TARGET, jax.random.PRNGKey(0)),
            init_params(DRAFT, jax.random.PRNGKey(7)))


def _run(params_t, draft, prompt, n_new, gamma=4, slots=1, buckets=(32,),
         temperature=0.0, seed=11):
    eng = Engine(TARGET, params_t, None, EngineConfig(
        max_slots=slots, max_context=256, prefill_buckets=buckets,
        gamma=gamma), draft=draft)
    return [o.token_id for o in eng.generate(GenRequest(
        list(prompt), SamplingParams(temperature=temperature, seed=seed),
        max_tokens=n_new, ignore_eos=True))]


def test_spec_greedy_matches_plain_engine(models):
    params_t, params_d = models
    prompt = [3, 14, 15, 9, 2, 6]
    plain = _run(params_t, None, prompt, 24)
    spec = _run(params_t, (DRAFT, params_d), prompt, 24)
    assert spec == plain


def test_perfect_draft_accepts_gamma_per_step(models):
    """draft == target, greedy: every proposal accepted → gamma+1 tokens per
    spec step and acceptance metrics near 1."""
    params_t, _ = models
    eng = Engine(TARGET, params_t, None, EngineConfig(
        max_slots=1, max_context=256, prefill_buckets=(32,), gamma=4),
        draft=(TARGET, params_t))
    prompt = [5, 9, 2, 7]
    toks = [o.token_id for o in eng.generate(GenRequest(
        list(prompt), SamplingParams(temperature=0.0), max_tokens=20,
        ignore_eos=True))]
    plain = _run(params_t, None, prompt, 20)
    assert toks == plain
    assert eng.metrics["draft_proposed"] > 0
    rate = eng.metrics["draft_accepted"] / eng.metrics["draft_proposed"]
    assert rate > 0.95
    # >1 token/step: 19 post-admission tokens in ~ceil(19/5) spec steps
    steps = eng.metrics["draft_proposed"] // 4
    assert (len(toks) - 1) / steps > 1.0


def test_spec_concurrent_slots_greedy_parity(models):
    """Two concurrent spec streams must each match their solo plain run."""
    params_t, params_d = models
    p1, p2 = [3, 14, 15, 9], [27, 1, 8, 2, 8]
    ref1 = _run(params_t, None, p1, 16)
    ref2 = _run(params_t, None, p2, 16)

    eng = Engine(TARGET, params_t, None, EngineConfig(
        max_slots=2, max_context=256, prefill_buckets=(32,), gamma=3),
        draft=(DRAFT, params_d))
    r1 = eng.submit(GenRequest(list(p1), SamplingParams(temperature=0.0),
                               max_tokens=16, ignore_eos=True))
    r2 = eng.submit(GenRequest(list(p2), SamplingParams(temperature=0.0),
                               max_tokens=16, ignore_eos=True))
    for _ in range(500):
        if not eng.step():
            break
    outs = {q: [] for _, q in (r1, r2)}
    for _, q in (r1, r2):
        while not q.empty():
            outs[q].append(q.get().token_id)
    assert outs[r1[1]] == ref1
    assert outs[r2[1]] == ref2


def test_spec_chunked_prefill_long_prompt(models):
    """Prompt longer than the biggest bucket → chunked prefill mirrored into
    the draft cache; output must still match the plain engine."""
    params_t, params_d = models
    rng = np.random.default_rng(0)
    prompt = rng.integers(1, 128, 100).tolist()
    plain = _run(params_t, None, prompt, 12, buckets=(32,))
    spec = _run(params_t, (DRAFT, params_d), prompt, 12, buckets=(32,))
    assert spec == plain


def test_spec_rejects_grammar(models):
    params_t, params_d = models
    eng = Engine(TARGET, params_t, None, EngineConfig(
        max_slots=1, max_context=64, prefill_buckets=(32,)),
        draft=(DRAFT, params_d))
    with pytest.raises(ValueError, match="grammar"):
        eng.submit(GenRequest([1, 2, 3], SamplingParams(),
                              grammar='root ::= "a"'))


def test_spec_stochastic_runs_and_terminates(models):
    """Temperature sampling through the spec path: correct count, all ids in
    range (distribution preservation is by construction; this is a smoke)."""
    params_t, params_d = models
    toks = _run(params_t, (DRAFT, params_d), [3, 1, 4, 1, 5], 32,
                temperature=0.9, seed=5)
    assert len(toks) == 32
    assert all(0 <= t < 128 for t in toks)


@pytest.mark.parametrize("data,model", [(2, 2), (2, 4)])
def test_spec_under_mesh_matches_unmeshed(models, data, model):
    """Spec decoding under a TP/DP mesh == no-mesh spec run, token for token.

    model=2 shards the DRAFT too (kv_heads=2 divides); model=4 exercises the
    replicated-draft fallback (kv_heads=2 does not divide 4)."""
    from localai_tpu.models.llama import (
        max_model_axis, param_specs, replicated_specs,
    )
    from localai_tpu.parallel.mesh import MeshConfig, build_mesh, shard_params

    params_t, params_d = models
    prompt = [3, 14, 15, 9, 2, 6]
    plain = _run(params_t, (DRAFT, params_d), prompt, 24)

    import jax

    mesh = build_mesh(MeshConfig(data=data, model=model),
                      jax.devices()[: data * model])
    pt = shard_params(params_t, param_specs(TARGET), mesh)
    dspecs = (param_specs(DRAFT) if max_model_axis(DRAFT, model) == model
              else replicated_specs(DRAFT))
    pd = shard_params(params_d, dspecs, mesh)
    eng = Engine(TARGET, pt, None, EngineConfig(
        max_slots=2, max_context=256, prefill_buckets=(32,), gamma=4,
        mesh=mesh), draft=(DRAFT, pd))
    out = [o.token_id for o in eng.generate(GenRequest(
        list(prompt), SamplingParams(temperature=0.0, seed=11),
        max_tokens=24, ignore_eos=True))]
    assert out == plain
    assert eng.metrics["draft_proposed"] > 0   # the spec path actually ran


@pytest.mark.parametrize("cache_type", ["", "int8"])
def test_spec_on_paged_kv_matches_dense(models, cache_type):
    """Speculative decoding with a PAGED target cache (dense draft) must
    reproduce the dense-cache spec engine token-for-token — greedy and
    seeded-stochastic, multiple concurrent slots."""
    params_t, params_d = models

    def run(kv_pages):
        eng = Engine(TARGET, params_t, None, EngineConfig(
            max_slots=2, max_context=256, prefill_buckets=(32,), gamma=4,
            kv_pages=kv_pages, cache_type=cache_type),
            draft=(DRAFT, params_d))
        eng.start()
        reqs = [
            GenRequest([3, 14, 15, 9, 2, 6],
                       SamplingParams(temperature=0.0),
                       max_tokens=20, ignore_eos=True),
            GenRequest([5, 9, 2, 7],
                       SamplingParams(temperature=0.9, top_k=0, seed=13),
                       max_tokens=20, ignore_eos=True),
        ]
        outs = [eng.submit(r) for r in reqs]
        res = []
        for rid, q in outs:
            ids = []
            while True:
                o = q.get(timeout=240)
                if o.token_id >= 0:
                    ids.append(o.token_id)
                if o.finished:
                    break
            res.append(ids)
        eng.stop()
        return res

    assert run(0) == run(8)
