"""Device-side grammar tables (ISSUE 12): dense automaton tables vs the
host matcher, and the engine paths that consume them — the fused decode
loop, the ragged pack, and the speculative verify window.

Table-unit cases run in tier-1; the engine parity sweeps are slow-marked
and run standalone via `-m grammar`.
"""
import numpy as np
import pytest

from fixtures import tiny_checkpoint
from localai_tpu.functions.grammars import JSON_GRAMMAR, json_schema_grammar
from localai_tpu.functions.matcher import CompiledGrammar, GrammarCache

pytestmark = pytest.mark.grammar


# ------------------------------------------------------------ table units

VOCAB = ['{', '}', '"', 'a', 'b', ':', ',', ' ', '0', '1', 'x']


def _bits_of(mask_u32, nbytes):
    return mask_u32.view(np.uint8)[:nbytes]


def test_table_matches_matcher_walk():
    """Every (state, token) the matcher can walk agrees with the dense
    table: same allowed-token mask at each step, and trans[] lands in a
    state whose mask equals the matcher's mask after accept."""
    g = CompiledGrammar('root ::= "a" [01]+ ("x" | "b")?', VOCAB)
    tbl = g.table(64)
    assert tbl is not None and tbl.n_states >= 2
    s = g.state()
    st = 0
    for tok in [VOCAB.index('a'), VOCAB.index('0'), VOCAB.index('1'),
                VOCAB.index('x')]:
        assert np.array_equal(_bits_of(tbl.masks[st], g.nbytes),
                              s.mask_bits())
        assert tbl.trans[st, tok] >= 0
        assert s.accept(tok)
        st = tbl.trans[st, tok]
    assert tbl.accepting[st]
    # masked-off tokens have no transition anywhere the mask bit is 0
    for state in range(tbl.n_states):
        bits = _bits_of(tbl.masks[state], g.nbytes)
        for t in range(len(VOCAB)):
            allowed = bits[t >> 3] >> (t & 7) & 1
            assert (tbl.trans[state, t] >= 0) == bool(allowed)


def test_table_accepting_tracks_matcher_done():
    g = CompiledGrammar('root ::= "a" "b"', VOCAB)
    tbl = g.table(16)
    s = g.state()
    st = 0
    assert not tbl.accepting[st]
    for tok in (VOCAB.index('a'), VOCAB.index('b')):
        st = tbl.trans[st, tok]
        s.accept(tok)
    assert s.done and tbl.accepting[st]


def test_table_overflow_returns_none():
    """Unbounded-nesting grammars never close their token-reachable state
    set — table() reports None and the engine keeps those on the per-token
    host matcher path instead of shipping a truncated automaton."""
    g = CompiledGrammar('root ::= "b" | "a" root "x"', VOCAB)
    assert g.table(64) is None
    # a closing grammar still overflows when the cap is below its state
    # count — same None contract, memoized per cap
    h = CompiledGrammar('root ::= "a" [01]+ ("x" | "b")?', VOCAB)
    assert h.table(1) is None
    assert h.table(64) is not None
    assert h.table(1) is None  # memo keeps per-cap answers separate


def test_table_memoized_per_cap():
    g = CompiledGrammar('root ::= "a" "b"', VOCAB)
    t1 = g.table(16)
    assert g.table(16) is t1  # double-checked insert returns the cached one


# ------------------------------------------------------------ engine paths

SCHEMA = {"type": "object",
          "properties": {"a": {"type": "integer"},
                         "b": {"type": "string"}},
          "required": ["a", "b"]}


@pytest.fixture(scope="module")
def loaded(tmp_path_factory):
    from localai_tpu.engine import Tokenizer, load_config, load_params

    ckpt = tiny_checkpoint(tmp_path_factory)
    cfg = load_config(ckpt, dtype="float32")
    params = load_params(ckpt, cfg)
    tok = Tokenizer.from_dir(ckpt)
    return cfg, params, tok


def _drain(eng, reqs, steps=2000):
    outs = [eng.submit(r) for r in reqs]
    for _ in range(steps):
        if not eng.step():
            break
    res = []
    for _, q in outs:
        ids, reason = [], None
        while not q.empty():
            o = q.get_nowait()
            if o.token_id >= 0:
                ids.append(o.token_id)
            if o.finished:
                reason = o.finish_reason
        res.append((ids, reason))
    return res


def _greq(tok, temp=0.0, seed=5, n=24, g=None):
    from localai_tpu.engine import GenRequest
    from localai_tpu.ops.sampling import SamplingParams

    return GenRequest(tok.encode("emit json:"),
                      SamplingParams(temperature=temp, seed=seed),
                      max_tokens=n,
                      grammar=g or json_schema_grammar(SCHEMA))


def _preq(tok, n=10):
    from localai_tpu.engine import GenRequest
    from localai_tpu.ops.sampling import SamplingParams

    return GenRequest(tok.encode("the quick brown fox"),
                      SamplingParams(temperature=0.0),
                      max_tokens=n, ignore_eos=True)


def _assert_conformant(tok, gbnf, ids):
    m = GrammarCache(tok).get(gbnf).state()
    for t in ids:
        if tok.eos_ids and t in tok.eos_ids:
            return
        assert m.accept(t), f"illegal token {t} ({tok.decode([t])!r})"


@pytest.mark.slow
@pytest.mark.parametrize("temp", [0.0, 0.9])
def test_loop_grammar_parity_vs_host_masking(loaded, temp):
    """A table-backed grammar slot rides the single-dispatch while loop and
    emits the SAME stream as the host-masked per-step reference (greedy and
    sampled — the loop's device mask gather + state advance is bit-exact
    against mask_bits)."""
    from localai_tpu.engine import Engine, EngineConfig

    cfg, params, tok = loaded
    e_tab = Engine(cfg, params, tok, EngineConfig(
        max_slots=2, max_context=128, prefill_buckets=(16,),
        prompt_cache=False))
    # decode_block=1: every host-masked step samples under a FRESH mask
    # (the fused-block rollback path re-keys the sampler on a stale-mask
    # miss — a different, equally-valid stream)
    e_host = Engine(cfg, params, tok, EngineConfig(
        max_slots=2, max_context=128, prefill_buckets=(16,),
        prompt_cache=False, grammar_table_states=0, decode_block=1,
        decode_loop=0))
    a = _drain(e_tab, [_greq(tok, temp)])
    b = _drain(e_host, [_greq(tok, temp)])
    assert a == b, (temp, a, b)
    assert e_tab.metrics.get("grammar_table_states", 0) > 0
    # the table engine must NOT have fallen back to per-token dispatches
    assert e_tab.metrics["decode_dispatches"] < \
        e_host.metrics["decode_dispatches"] / 4


@pytest.mark.slow
def test_ragged_grammar_parity(loaded):
    """Grammar slots pack into the ragged stream alongside plain tenants
    (greedy + sampled), matching the rollback-free dense reference; a
    tables-off engine (hostonly masks) matches too."""
    from localai_tpu.engine import Engine, EngineConfig

    cfg, params, tok = loaded

    def ec(**kw):
        return EngineConfig(max_slots=4, max_context=128,
                            prefill_buckets=(16, 64), prefill_chunk=16,
                            kv_pages=10, prompt_cache=False, **kw)

    e_rag = Engine(cfg, params, tok, ec(ragged_token_budget=64))
    e_ref = Engine(cfg, params, tok, ec(decode_block=1, decode_loop=0))
    reqs = lambda: [_greq(tok, 0.0), _preq(tok), _greq(tok, 0.9, seed=9)]
    ra = _drain(e_rag, reqs())
    rb = _drain(e_ref, reqs())
    assert ra == rb, (ra, rb)
    assert e_rag.metrics["ragged_dispatches"] > 0

    e_rag0 = Engine(cfg, params, tok,
                    ec(ragged_token_budget=64, grammar_table_states=0))
    rc = _drain(e_rag0, [_greq(tok, 0.0), _preq(tok)])
    assert rc == ra[:2], (rc, ra[:2])


@pytest.mark.slow
def test_ragged_overflow_grammar_hostonly(loaded):
    """The recursive JSON grammar overflows the table and keeps the host
    mask path: greedy parity holds exactly (path-independent); sampled
    streams stay grammar-conformant (the fused-block fallback re-keys on
    rollback, so exact sampled parity is not a contract there)."""
    from localai_tpu.engine import Engine, EngineConfig

    cfg, params, tok = loaded

    def ec(**kw):
        return EngineConfig(max_slots=4, max_context=128,
                            prefill_buckets=(16, 64), prefill_chunk=16,
                            kv_pages=10, prompt_cache=False, **kw)

    e_rag = Engine(cfg, params, tok, ec(ragged_token_budget=64))
    e_ref = Engine(cfg, params, tok, ec(decode_block=1, decode_loop=0))
    rj = _drain(e_rag, [_greq(tok, 0.0, g=JSON_GRAMMAR), _preq(tok)])
    rk = _drain(e_ref, [_greq(tok, 0.0, g=JSON_GRAMMAR), _preq(tok)])
    assert rj == rk, (rj, rk)
    assert e_rag.metrics.get("grammar_table_overflows", 0) > 0
    rs = _drain(e_rag, [_greq(tok, 0.9, seed=3, g=JSON_GRAMMAR)])
    _assert_conformant(tok, JSON_GRAMMAR, rs[0][0])


@pytest.mark.slow
def test_mm_packed_prefill_parity(loaded):
    """Multimodal embedding chunks pack into the flat ragged stream (the
    per-row inject lane) and produce the same stream as the dense mm
    prefill path."""
    from localai_tpu.engine import Engine, EngineConfig, GenRequest
    from localai_tpu.ops.sampling import SamplingParams

    cfg, params, tok = loaded
    embed = np.asarray(params["embed"], np.float32)
    prompt = tok.encode("the quick brown fox jumps over")

    def mmreq():
        r = GenRequest(list(prompt), SamplingParams(temperature=0.0),
                       max_tokens=10, ignore_eos=True)
        r.mm_embeds = embed[prompt[1:4]] + 0.25
        r.mm_positions = np.arange(1, 4)
        return r

    def ec(**kw):
        return EngineConfig(max_slots=4, max_context=128,
                            prefill_buckets=(16, 64), prefill_chunk=16,
                            kv_pages=10, prompt_cache=False, **kw)

    e_rag = Engine(cfg, params, tok, ec(ragged_token_budget=64))
    e_ref = Engine(cfg, params, tok, ec(decode_block=1, decode_loop=0))
    ma = _drain(e_rag, [mmreq(), _preq(tok)])
    mb = _drain(e_ref, [mmreq(), _preq(tok)])
    assert ma == mb, (ma, mb)
    assert e_rag.metrics["ragged_dispatches"] > 0


@pytest.mark.slow
def test_spec_as_ragged_parity(loaded):
    """Speculative decode as a ragged pack variant: the verify windows ride
    ragged_forward and the token streams match the dense spec engine
    exactly (same draft keys, same accept test)."""
    from localai_tpu.engine import Engine, EngineConfig, load_config, \
        load_params

    cfg, params, tok = loaded

    def ec(**kw):
        return EngineConfig(max_slots=4, max_context=128,
                            prefill_buckets=(16, 64), prefill_chunk=16,
                            kv_pages=14, prompt_cache=False, gamma=3, **kw)

    draft = (cfg, params)  # perfect draft: every proposal accepted
    e_sr = Engine(cfg, params, tok, ec(ragged_token_budget=96), draft=draft)
    e_sd = Engine(cfg, params, tok, ec(), draft=draft)
    sa = _drain(e_sr, [_preq(tok, 16), _preq(tok, 16)])
    sb = _drain(e_sd, [_preq(tok, 16), _preq(tok, 16)])
    assert sa == sb, (sa, sb)
    assert e_sr.metrics["ragged_dispatches"] > 0
    assert e_sr.metrics["draft_accepted"] > 0


@pytest.mark.slow
@pytest.mark.tripwire
def test_soup_tripwires_zero_fallback_zero_recompiles(loaded):
    """The acceptance stream: grammar + multimodal + speculative + plain
    tenants on ONE draft+ragged engine. After warmup and one warm stream,
    a repeat soup adds ZERO compilations, stays inside the dispatch
    budget, and never touches the dense fallback; every tenant's tokens
    ride the spec-ragged path."""
    from localai_tpu.engine import Engine, EngineConfig, GenRequest
    from localai_tpu.ops.sampling import SamplingParams
    from localai_tpu.testing.tripwires import (
        CompileCounter, decode_cache_sizes, decode_compile_count,
        dispatch_budget,
    )

    cfg, params, tok = loaded
    embed = np.asarray(params["embed"], np.float32)
    prompt = tok.encode("the quick brown fox jumps over")

    def mmreq():
        r = GenRequest(list(prompt), SamplingParams(temperature=0.0),
                       max_tokens=10, ignore_eos=True)
        r.mm_embeds = embed[prompt[1:3]] + 0.25
        r.mm_positions = np.arange(1, 3)
        return r

    def soup():
        return [_greq(tok, 0.0), mmreq(), _preq(tok, 12),
                _greq(tok, 0.9, seed=11)]

    eng = Engine(cfg, params, tok, EngineConfig(
        max_slots=4, max_context=128, prefill_buckets=(16, 64),
        prefill_chunk=16, kv_pages=14, prompt_cache=False, gamma=3,
        ragged_token_budget=96), draft=(cfg, params))
    eng.warmup()
    eng.record_paths = True

    out1 = _drain(eng, soup())  # warm stream (admit-tail mask variant etc.)
    assert all(r[1] is not None for r in out1), out1
    warm = decode_compile_count(eng)

    d0, r0 = eng.metrics["decode_dispatches"], \
        eng.metrics["ragged_dispatches"]
    with CompileCounter() as cc, dispatch_budget(eng):
        out2 = _drain(eng, soup())
    assert all(r[1] is not None for r in out2), out2
    assert cc.total == 0, cc.counts
    assert decode_compile_count(eng) == warm, decode_cache_sizes(eng)
    # zero dense fallback: every decode tick was a spec-ragged dispatch
    dense = (eng.metrics["decode_dispatches"] - d0) \
        - (eng.metrics["ragged_dispatches"] - r0)
    assert dense == 0, eng.metrics
    _assert_conformant(tok, json_schema_grammar(SCHEMA), out2[0][0])
    _assert_conformant(tok, json_schema_grammar(SCHEMA), out2[3][0])
    # per-tenant path accounting: every emitted token rode the spec path
    assert len(eng.req_path_counts) >= 8
    for counts in eng.req_path_counts.values():
        assert set(counts) == {"spec"}, eng.req_path_counts
