"""Ragged continuous batching (one paged-attention dispatch for mixed
prefill + decode):

- model-level parity: a mixed ragged tick (1-token decode rows beside a
  long prefill chunk in ONE flat stream) reproduces the dense decode_step
  and dense chunked prefill bit-for-bit on the f32 tier — logits AND pool
  contents — on both the XLA reference tier and the Pallas kernels
  (interpret mode), plus lenient-parity twins for the bf16 and int8-KV
  pools,
- TP: the shard_map wrappers (attention + scatter, f32 and q8) match the
  unsharded reference on the 4-device mesh,
- engine-level parity: a mixed-length request stream through a ragged
  engine (`ragged_token_budget`) produces token streams identical to the
  dense paged engine, and admission packs its first prefill chunk into a
  ragged dispatch in the SAME tick,
- structural proofs: the compiled ragged forward contains no gather/
  scatter over the full KV pool on the Pallas tier (the detector fires on
  the XLA tier, so it has teeth), and its activation footprint scales with
  the packed token budget, NOT with the slot count — the no-bucket-padding
  property that makes 256-slot serving affordable.
"""
import dataclasses

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from fixtures import tiny_checkpoint
from localai_tpu.engine import (
    Engine, EngineConfig, GenRequest, Tokenizer, load_config, load_params,
)
from localai_tpu.models.llama import (
    LlamaConfig, decode_step, init_params, prefill, ragged_forward,
)
from localai_tpu.ops.paged import BLOCK, init_paged
from localai_tpu.ops.rope import rope_table
from localai_tpu.ops.sampling import SamplingParams

pytestmark = pytest.mark.ragged

TINY = LlamaConfig(
    vocab_size=256, hidden_size=64, intermediate_size=128, num_layers=2,
    num_heads=4, num_kv_heads=2, head_dim=16, max_position=256,
    dtype="float32",
)


def _tier(monkeypatch, tier):
    if tier == "pallas":
        monkeypatch.setenv("LOCALAI_FORCE_PALLAS", "1")
        monkeypatch.delenv("LOCALAI_NO_PALLAS", raising=False)
    else:
        monkeypatch.setenv("LOCALAI_NO_PALLAS", "1")
        monkeypatch.delenv("LOCALAI_FORCE_PALLAS", raising=False)


def _mixed_tick(cache_type="", dtype=jnp.float32, cfg=None):
    """Dense reference vs one ragged mixed tick over the same pool: decode
    slots A (kv 5) and B (kv 7) ride 1-token QBLK rows while slot C's
    12-token prefill chunk packs behind them. Returns (ragged logits,
    dense decode logits, dense prefill-C logits, ragged pool, dense decode
    pool, dense prefill pool)."""
    cfg = cfg or TINY
    params = init_params(cfg, jax.random.PRNGKey(0))
    cos, sin = rope_table(cfg.rope, 256)
    kc, vc = init_paged(cfg.num_layers, 10, cfg.num_kv_heads, cfg.head_dim,
                        dtype, cache_type=cache_type)
    table = jnp.array([[1, 2], [3, 4], [5, 6]], jnp.int32)
    pa = jax.random.randint(jax.random.PRNGKey(1), (1, 5), 0, 256)
    pb = jax.random.randint(jax.random.PRNGKey(2), (1, 7), 0, 256)
    pc = jax.random.randint(jax.random.PRNGKey(3), (1, 12), 0, 256)
    la, kc, vc = prefill(params, cfg, pa, jnp.array([5]), cos, sin, kc, vc,
                         jnp.array([0]), table=table)
    lb, kc, vc = prefill(params, cfg, pb, jnp.array([7]), cos, sin, kc, vc,
                         jnp.array([1]), table=table)
    ta = jnp.argmax(la, -1).astype(jnp.int32)[0]
    tb = jnp.argmax(lb, -1).astype(jnp.int32)[0]
    dl, kc_d, vc_d = decode_step(
        params, cfg, jnp.array([ta, tb, 0]), jnp.array([5, 7, 0], jnp.int32),
        cos, sin, kc, vc, active=jnp.array([True, True, False]), table=table)
    lc, kc_c, _ = prefill(params, cfg, pc, jnp.array([12]), cos, sin, kc, vc,
                          jnp.array([2]), table=table)
    tokens = jnp.zeros((32,), jnp.int32)
    tokens = tokens.at[0].set(ta).at[8].set(tb).at[16:28].set(pc[0])
    rl, kc_r, _ = ragged_forward(
        params, cfg, tokens, cos, sin, kc, vc,
        block_seq=jnp.array([0, 1, 2, 2], jnp.int32),
        qstart=jnp.array([0, 8, 16], jnp.int32),
        qlen=jnp.array([1, 1, 12], jnp.int32),
        kvlen=jnp.array([6, 8, 12], jnp.int32),
        tables=table, logit_rows=jnp.array([0, 8, 27], jnp.int32))
    return rl, dl, lc, kc_r, kc_d, kc_c


# tier-1 keeps the pallas (chip-kernel) tier; the XLA-reference tier rides
# the slow lane — the engine stream tests prove that path end to end with
# exact token parity, and the single-core tier-1 wall clock is budget-bound
@pytest.mark.parametrize("tier", [
    pytest.param("xla", marks=pytest.mark.slow),
    "pallas",
])
def test_mixed_tick_matches_dense(monkeypatch, tier):
    """Acceptance: ONE ragged dispatch == dense decode_step + dense prefill
    over the same paged pool — logits and written pool blocks identical."""
    _tier(monkeypatch, tier)
    rl, dl, lc, kc_r, kc_d, kc_c = _mixed_tick()
    np.testing.assert_allclose(np.asarray(rl[:2]), np.asarray(dl[:2]),
                               rtol=2e-4, atol=2e-4)
    np.testing.assert_allclose(np.asarray(rl[2]), np.asarray(lc[0]),
                               rtol=2e-4, atol=2e-4)
    # decode writes (A at row 5 of block 1, B at row 7 of block 3) and the
    # chunk's writes (C rows 0..11 of block 5) match the dense paths
    np.testing.assert_allclose(np.asarray(kc_r[:, 1, :, :6]),
                               np.asarray(kc_d[:, 1, :, :6]), atol=1e-5)
    np.testing.assert_allclose(np.asarray(kc_r[:, 3, :, :8]),
                               np.asarray(kc_d[:, 3, :, :8]), atol=1e-5)
    np.testing.assert_allclose(np.asarray(kc_r[:, 5, :, :12]),
                               np.asarray(kc_c[:, 5, :, :12]), atol=1e-5)


# quantized-pool tiers ride the slow lane (resilience-suite precedent:
# tier-1 keeps the cheap core proofs on the 870s single-core budget; the
# slow CI job runs the full matrix)
@pytest.mark.slow
@pytest.mark.parametrize("tier", ["xla", "pallas"])
def test_mixed_tick_q8_pool(monkeypatch, tier):
    """int8-KV twin: quantized pools dequantize+reduce in tier-specific
    orders, so parity is lenient — and everything must stay finite."""
    _tier(monkeypatch, tier)
    rl, dl, lc, *_ = _mixed_tick(cache_type="q8_0")
    np.testing.assert_allclose(np.asarray(rl[:2]), np.asarray(dl[:2]),
                               rtol=5e-2, atol=5e-2)
    np.testing.assert_allclose(np.asarray(rl[2]), np.asarray(lc[0]),
                               rtol=5e-2, atol=5e-2)
    assert np.isfinite(np.asarray(rl)).all()


@pytest.mark.slow
def test_mixed_tick_bf16_pool(monkeypatch):
    _tier(monkeypatch, "xla")
    rl, dl, lc, *_ = _mixed_tick(
        dtype=jnp.bfloat16,
        cfg=dataclasses.replace(TINY, dtype="bfloat16"))
    np.testing.assert_allclose(np.asarray(rl[:2]), np.asarray(dl[:2]),
                               rtol=5e-2, atol=5e-2)
    np.testing.assert_allclose(np.asarray(rl[2]), np.asarray(lc[0]),
                               rtol=5e-2, atol=5e-2)


# ------------------------------------------------------------------- TP

@pytest.fixture(scope="module")
def mesh4():
    if len(jax.devices()) < 4:
        pytest.skip("needs >= 4 devices")
    from localai_tpu.parallel.mesh import MeshConfig, build_mesh

    return build_mesh(MeshConfig(data=1, model=4), jax.devices()[:4])


def _tp_case():
    """[T=16] flat stream: seq0 = one decode row (kv 9), seq1 = an 8-token
    chunk (kv 8). KVH=4 so the 4-wide model axis gets one KV head each."""
    KVH, D, NB = 4, 16, 6
    k = jax.random.normal(jax.random.PRNGKey(0), (NB, KVH, BLOCK, D),
                          jnp.float32)
    v = jax.random.normal(jax.random.PRNGKey(1), (NB, KVH, BLOCK, D),
                          jnp.float32)
    q = jax.random.normal(jax.random.PRNGKey(2), (16, KVH, D), jnp.float32)
    meta = dict(block_seq=jnp.array([0, 1], jnp.int32),
                qstart=jnp.array([0, 8], jnp.int32),
                qlen=jnp.array([1, 8], jnp.int32),
                kvlen=jnp.array([9, 8], jnp.int32),
                tables=jnp.array([[1, 2], [3, 4]], jnp.int32))
    return k, v, q, meta


@pytest.mark.tp
@pytest.mark.slow
def test_sharded_attention_matches_unsharded(mesh4, monkeypatch):
    from localai_tpu.ops.pallas import (
        ragged_attention_xla, ragged_paged_attention_sharded,
    )

    monkeypatch.setenv("LOCALAI_FORCE_PALLAS", "1")
    k, v, q, meta = _tp_case()
    ref = ragged_attention_xla(q, k, v, **meta)
    got = ragged_paged_attention_sharded(mesh4, q, k, v, **meta)
    live = [0] + list(range(8, 16))  # dead pad rows are don't-care
    np.testing.assert_allclose(np.asarray(got)[live], np.asarray(ref)[live],
                               rtol=2e-5, atol=2e-5)


@pytest.mark.tp
@pytest.mark.slow
def test_sharded_scatter_matches_xla(mesh4, monkeypatch):
    from localai_tpu.ops.pallas import (
        ragged_scatter_append_sharded, ragged_scatter_xla,
    )

    monkeypatch.setenv("LOCALAI_FORCE_PALLAS", "1")
    k, v, q, _ = _tp_case()
    kn = jax.random.normal(jax.random.PRNGKey(3), (16, 4, 16), jnp.float32)
    vn = jax.random.normal(jax.random.PRNGKey(4), (16, 4, 16), jnp.float32)
    pb = jnp.array([1] + [0] * 7 + [3] * 8, jnp.int32)
    off = jnp.array([9] + list(range(7)) + list(range(8, 16)), jnp.int32)
    rk, rv = ragged_scatter_xla(k, v, kn, vn, pb, off)
    gk, gv = ragged_scatter_append_sharded(mesh4, k, v, kn, vn, pb, off)
    # padding rows (block 0) collide by design; compare the live targets
    np.testing.assert_allclose(np.asarray(gk[1:]), np.asarray(rk[1:]),
                               atol=1e-6)
    np.testing.assert_allclose(np.asarray(gv[1:]), np.asarray(rv[1:]),
                               atol=1e-6)


@pytest.mark.tp
@pytest.mark.slow
def test_sharded_q8_ops_match_xla(mesh4, monkeypatch):
    from localai_tpu.ops.pallas import (
        ragged_attention_xla_q8, ragged_paged_attention_q8_sharded,
        ragged_scatter_append_q8_sharded, ragged_scatter_xla_q8,
    )

    monkeypatch.setenv("LOCALAI_FORCE_PALLAS", "1")
    k, v, q, meta = _tp_case()
    kq, vq = init_paged(1, 6, 4, 16, cache_type="q8_0")
    kq, ks = kq.q[0], kq.s[0]
    vqq, vs = vq.q[0], vq.s[0]
    kn = jax.random.normal(jax.random.PRNGKey(3), (16, 4, 16), jnp.float32)
    vn = jax.random.normal(jax.random.PRNGKey(4), (16, 4, 16), jnp.float32)
    pb = jnp.array([1] + [0] * 7 + [3] * 8, jnp.int32)
    off = jnp.array([9] + list(range(7)) + list(range(8, 16)), jnp.int32)
    ra = ragged_scatter_xla_q8(kq, ks, vqq, vs, kn, vn, pb, off)
    ga = ragged_scatter_append_q8_sharded(mesh4, kq, ks, vqq, vs, kn, vn,
                                          pb, off)
    for r, g in zip(ra, ga):
        np.testing.assert_allclose(np.asarray(g[1:]), np.asarray(r[1:]),
                                   atol=1e-6)
    ref = ragged_attention_xla_q8(q, *ra, **meta)
    got = ragged_paged_attention_q8_sharded(mesh4, q, *ga, **meta)
    live = [0] + list(range(8, 16))  # dead pad rows are don't-care
    np.testing.assert_allclose(np.asarray(got)[live], np.asarray(ref)[live],
                               rtol=2e-2, atol=2e-2)


# ------------------------------------------------------------ engine parity

@pytest.fixture(scope="module")
def loaded(tmp_path_factory):
    ckpt = tiny_checkpoint(tmp_path_factory)
    cfg = load_config(ckpt, dtype="float32")
    params = load_params(ckpt, cfg)
    tok = Tokenizer.from_dir(ckpt)
    return cfg, params, tok


def _mixed_reqs(cfg):
    rng = np.random.default_rng(0)
    lens = (5, 12, 33, 7, 21, 3)
    sps = [SamplingParams(temperature=0.0),
           SamplingParams(temperature=0.8, seed=11),
           SamplingParams(temperature=0.7, top_p=0.9, seed=3),
           SamplingParams(temperature=0.0),
           SamplingParams(temperature=1.0, top_k=5, seed=7),
           SamplingParams(temperature=0.0)]
    return [GenRequest(rng.integers(5, cfg.vocab_size, n).tolist(), sp,
                       max_tokens=10, ignore_eos=True)
            for n, sp in zip(lens, sps)]


def _run_stream(cfg, params, tok, budget):
    eng = Engine(cfg, params, tok, EngineConfig(
        max_slots=4, max_context=128, prefill_buckets=(16, 64),
        prefill_chunk=16, kv_pages=10, prompt_cache=False,
        ragged_token_budget=budget))
    reqs = _mixed_reqs(cfg)
    outs = [eng.submit(r) for r in reqs[:3]]
    for _ in range(3):
        eng.step()          # admit the rest mid-decode (mixed ticks)
    outs += [eng.submit(r) for r in reqs[3:]]
    for _ in range(500):
        if not eng.step():
            break
    toks = []
    for _, q in outs:
        seq = []
        while not q.empty():
            o = q.get_nowait()
            if o.token_id >= 0:
                seq.append(o.token_id)
        toks.append(seq)
    return toks, dict(eng.metrics)


def test_engine_ragged_stream_parity(loaded):
    """Acceptance: identical token streams ragged vs dense across mixed
    lengths and mixed sampling knobs (greedy, seeded top-p, seeded top-k),
    with admissions landing mid-decode — and the ragged engine actually
    ran mixed dispatches."""
    cfg, params, tok = loaded
    dense, _ = _run_stream(cfg, params, tok, budget=0)
    ragged, m = _run_stream(cfg, params, tok, budget=64)
    assert all(len(s) == 10 for s in dense)
    assert dense == ragged
    assert m["ragged_dispatches"] > 0
    assert m["ragged_tokens_packed"] > m["ragged_dispatches"]


def test_admission_packs_kv_in_the_same_tick(loaded):
    """A chunked admission's first prefill window rides the SAME tick's
    ragged dispatch (admission is host-only bookkeeping): after one step()
    the engine has already packed prompt tokens, with no dense prefill
    dispatch in between."""
    cfg, params, tok = loaded
    eng = Engine(cfg, params, tok, EngineConfig(
        max_slots=4, max_context=128, prefill_buckets=(16, 64),
        prefill_chunk=16, kv_pages=10, prompt_cache=False,
        ragged_token_budget=64))
    prompt = np.random.default_rng(1).integers(
        5, cfg.vocab_size, 40).tolist()
    _, q = eng.submit(GenRequest(prompt, SamplingParams(temperature=0.0),
                                 max_tokens=4, ignore_eos=True))
    eng.step()
    assert eng.metrics["ragged_dispatches"] == 1
    assert eng.metrics["ragged_tokens_packed"] == 16  # first chunk, packed
    for _ in range(100):
        if not eng.step():
            break
    ids = []
    while not q.empty():
        o = q.get_nowait()
        if o.token_id >= 0:
            ids.append(o.token_id)
    # the packed-KV stream must equal the dense engine's
    ref_eng = Engine(cfg, params, tok, EngineConfig(
        max_slots=4, max_context=128, prefill_buckets=(16, 64),
        prefill_chunk=16, kv_pages=10, prompt_cache=False))
    _, rq = ref_eng.submit(GenRequest(prompt,
                                      SamplingParams(temperature=0.0),
                                      max_tokens=4, ignore_eos=True))
    for _ in range(100):
        if not ref_eng.step():
            break
    ref = []
    while not rq.empty():
        o = rq.get_nowait()
        if o.token_id >= 0:
            ref.append(o.token_id)
    assert ids == ref and len(ids) == 4


def test_ragged_requires_paged_kv(loaded):
    cfg, params, tok = loaded
    with pytest.raises(ValueError, match="paged"):
        Engine(cfg, params, tok, EngineConfig(
            max_slots=2, max_context=128, prefill_buckets=(16,),
            ragged_token_budget=64))


# ------------------------------------------------------ structural proofs

def _ragged_jaxpr(monkeypatch, tier, t=64, nseq=8, nb=12):
    _tier(monkeypatch, tier)
    cfg = dataclasses.replace(TINY, hidden_size=32, intermediate_size=64,
                              num_heads=4, num_kv_heads=2, head_dim=8)
    params = init_params(cfg, jax.random.PRNGKey(0))
    cos, sin = rope_table(cfg.rope, 256)
    kc, vc = init_paged(cfg.num_layers, nb, cfg.num_kv_heads, cfg.head_dim,
                        jnp.float32)
    jaxpr = jax.make_jaxpr(
        lambda kc, vc, tokens, bs, q0, q1, kl, tb, lr: ragged_forward(
            params, cfg, tokens, cos, sin, kc, vc, bs, q0, q1, kl, tb, lr)
    )(kc, vc, jnp.zeros((t,), jnp.int32),
      jnp.zeros((t // 8,), jnp.int32), jnp.zeros((nseq,), jnp.int32),
      jnp.zeros((nseq,), jnp.int32), jnp.zeros((nseq,), jnp.int32),
      jnp.zeros((nseq, 2), jnp.int32), jnp.zeros((nseq,), jnp.int32))
    pool_elems = nb * cfg.num_kv_heads * BLOCK * cfg.head_dim
    return jaxpr, pool_elems


def _pool_hits(jaxpr, pool_elems):
    bad = []

    def visit(jx):
        for eqn in jx.eqns:
            if eqn.primitive.name in (
                    "gather", "scatter", "scatter-add", "scatter-mul",
                    "scatter_apply", "dynamic_update_slice"):
                for var in list(eqn.invars) + list(eqn.outvars):
                    aval = getattr(var, "aval", None)
                    if aval is not None and getattr(aval, "size", 0) \
                            >= pool_elems:
                        bad.append((eqn.primitive.name, tuple(aval.shape)))
            for p in eqn.params.values():
                for sub in (p if isinstance(p, (list, tuple)) else [p]):
                    sub = getattr(sub, "jaxpr", sub)
                    if hasattr(sub, "eqns"):
                        visit(sub)
    visit(jaxpr.jaxpr)
    return bad


def test_ragged_jaxpr_no_full_pool_ops(monkeypatch):
    """Acceptance: on the Pallas tier the ragged forward's jaxpr contains
    NO gather/scatter over anything pool-sized — KV reads stream through
    the tables inside the kernel, writes ride the flat-row scatter DMA."""
    jaxpr, pool_elems = _ragged_jaxpr(monkeypatch, "pallas")
    hits = _pool_hits(jaxpr, pool_elems)
    assert not hits, f"full-pool gather/scatter in the ragged program: {hits}"


def test_ragged_jaxpr_detector_not_vacuous(monkeypatch):
    """The same detector DOES fire on the XLA reference tier (per-q-block
    gather + index scatter over the pool) — the assertion has teeth."""
    jaxpr, pool_elems = _ragged_jaxpr(monkeypatch, "xla")
    assert _pool_hits(jaxpr, pool_elems)


def _activation_footprint(jaxpr, pool_elems):
    """Sum of computed (outvar) float-aval sizes, excluding pool-sized
    buffers that just flow through — the program's activation bill."""
    total = 0

    def visit(jx):
        nonlocal total
        for eqn in jx.eqns:
            for var in eqn.outvars:
                aval = getattr(var, "aval", None)
                if aval is None or not hasattr(aval, "dtype"):
                    continue
                if jnp.issubdtype(aval.dtype, jnp.floating) \
                        and aval.size < pool_elems:
                    total += aval.size
            for p in eqn.params.values():
                for sub in (p if isinstance(p, (list, tuple)) else [p]):
                    sub = getattr(sub, "jaxpr", sub)
                    if hasattr(sub, "eqns"):
                        visit(sub)
    visit(jaxpr.jaxpr)
    return total


def test_ragged_work_scales_with_tokens_not_slots(monkeypatch):
    """The no-bucket-padding proof: doubling the SLOT count (same packed
    budget) leaves the activation footprint nearly unchanged, while
    doubling the token budget roughly doubles it. A bucketed program pads
    per sequence, so its footprint scales with slots — this one's scales
    with the tokens actually packed, which is what makes 256 slots
    affordable."""
    base, pe = _ragged_jaxpr(monkeypatch, "xla", t=64, nseq=8)
    more_slots, _ = _ragged_jaxpr(monkeypatch, "xla", t=64, nseq=32)
    more_tokens, _ = _ragged_jaxpr(monkeypatch, "xla", t=128, nseq=8)
    s0 = _activation_footprint(base, pe)
    s_slots = _activation_footprint(more_slots, pe)
    s_tokens = _activation_footprint(more_tokens, pe)
    assert s_slots < 1.3 * s0, (s0, s_slots)
    assert s_tokens > 1.6 * s0, (s0, s_tokens)
