"""Ring attention vs the single-device reference on the virtual 8-device
mesh — the sequence-parallel long-context path."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from localai_tpu.ops.attention import mha_prefill
from localai_tpu.parallel.ring_attention import build_seq_mesh, ring_prefill


def _rand(key, shape):
    return jax.random.normal(jax.random.PRNGKey(key), shape, jnp.float32)


@pytest.mark.parametrize("H,KVH", [(4, 4), (4, 2)])
def test_ring_matches_reference(H, KVH):
    B, S, D = 2, 64, 16
    q = _rand(0, (B, S, H, D))
    k = _rand(1, (B, S, KVH, D))
    v = _rand(2, (B, S, KVH, D))
    lengths = jnp.array([S, 41], jnp.int32)
    mesh = build_seq_mesh(8)
    out = ring_prefill(q, k, v, lengths, mesh)
    ref = mha_prefill(q, k, v, lengths)
    for b in range(B):
        n = int(lengths[b])
        np.testing.assert_allclose(np.asarray(out[b, :n]),
                                   np.asarray(ref[b, :n]),
                                   rtol=2e-5, atol=2e-5)


def test_ring_sliding_window():
    B, S, H, D = 1, 32, 2, 8
    q, k, v = _rand(3, (B, S, H, D)), _rand(4, (B, S, H, D)), _rand(5, (B, S, H, D))
    lengths = jnp.array([S], jnp.int32)
    mesh = build_seq_mesh(4)
    out = ring_prefill(q, k, v, lengths, mesh, sliding_window=8)
    ref = mha_prefill(q, k, v, lengths, sliding_window=8)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_ring_output_stays_sequence_sharded():
    B, S, H, D = 1, 64, 2, 8
    mesh = build_seq_mesh(8)
    from jax.sharding import NamedSharding, PartitionSpec as P

    # lint: allow(sharding-spec-source) — kernel-level test: inputs are
    # deliberately hand-placed on the 'seq' axis to drive ring_prefill
    q = jax.device_put(_rand(6, (B, S, H, D)),
                       NamedSharding(mesh, P(None, "seq", None, None)))
    # lint: allow(sharding-spec-source)
    k = jax.device_put(_rand(7, (B, S, H, D)),
                       NamedSharding(mesh, P(None, "seq", None, None)))
    # lint: allow(sharding-spec-source)
    v = jax.device_put(_rand(8, (B, S, H, D)),
                       NamedSharding(mesh, P(None, "seq", None, None)))
    out = ring_prefill(q, k, v, jnp.array([S], jnp.int32), mesh)
    assert not out.sharding.is_fully_replicated
    spec = out.sharding.spec
    assert spec[1] == "seq"
