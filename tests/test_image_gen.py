"""Diffusion engine: sampler determinism + conditioning effect + RPC/PNG
contract."""
import numpy as np
import pytest


@pytest.fixture(scope="module")
def model():
    from localai_tpu.models.diffusion import DiffusionConfig, DiffusionModel

    cfg = DiffusionConfig(channels=16, channel_mults=(1, 2), image_size=16,
                          text_dim=32, text_layers=1, vocab_size=256,
                          max_text_len=16)
    return DiffusionModel(cfg)


def test_sampler_shapes_and_determinism(model):
    import jax.numpy as jnp

    toks = model._tokens("a red cat")
    a = model._sample(model.params, tokens=toks, steps=4, seed=3)
    b = model._sample(model.params, tokens=toks, steps=4, seed=3)
    c = model._sample(model.params, tokens=toks, steps=4, seed=4)
    assert a.shape == (1, 16, 16, 3)
    assert float(jnp.abs(a).max()) <= 1.0 and float(a.min()) >= 0.0
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    assert np.abs(np.asarray(a) - np.asarray(c)).max() > 0  # seed matters


def test_text_conditioning_changes_output(model):
    a = model._sample(model.params, tokens=model._tokens("a red cat"),
                      steps=4, seed=0)
    b = model._sample(model.params, tokens=model._tokens("a blue dog"),
                      steps=4, seed=0)
    assert np.abs(np.asarray(a) - np.asarray(b)).max() > 1e-4


def test_generate_image_png(model, tmp_path):
    from PIL import Image

    dst = str(tmp_path / "out.png")
    model.generate_image("test", dst, width=32, height=24, steps=3)
    img = Image.open(dst)
    assert img.size == (32, 24)


def test_generate_video_gif(model, tmp_path):
    from PIL import Image

    dst = str(tmp_path / "out.gif")
    model.generate_video("test", dst, num_frames=2, fps=2, width=16,
                         height=16, steps=2)
    img = Image.open(dst)
    assert img.n_frames == 2


def test_image_rpc(tmp_path):
    from localai_tpu.backend.client import BackendClient
    from localai_tpu.backend.server import serve

    server, _, port = serve("127.0.0.1:0", "image")
    try:
        c = BackendClient(f"127.0.0.1:{port}")
        assert c.wait_ready(attempts=20, sleep=0.1)
        assert c.load_model(model="diffusion").success
        dst = str(tmp_path / "rpc.png")
        r = c.generate_image(positive_prompt="a cat", dst=dst, width=32,
                             height=32, step=2)
        assert r.success
        from PIL import Image

        assert Image.open(dst).size == (32, 32)
        c.close()
    finally:
        server.stop(grace=1)
