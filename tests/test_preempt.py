"""Preemption-safe serving suite (ISSUE 19) — ResumeToken round-trips, the
HostKVPool in-flight spill claims (pin semantics + threaded spill/evict race),
the HTTP bridge's resume-lane selection, and the full checkpoint/resume flows:
engine-level spill-drain parity across engine death (greedy, sampled via the
persisted RNG key, tiny-pool re-prefill fallback, second preempt mid-resume)
and chaos E2E through the HTTP->gRPC->engine stack (`preempt` SIGTERM notice,
`kill9_middecode` ungraceful death with/without the host KV tier, the
deterministic-replay fallback, and drain-during-preempt never hanging a
stream).

Unit pieces run in tier-1; the engine-driving flows carry `slow` + `preempt`
and the process-spawning chaos scenarios carry `slow` + `resilience`, matching
the CI lane split in test_resilience.py.
"""
import json
import threading
import time

import numpy as np
import pytest
import requests
import yaml

from fixtures import tiny_checkpoint
from test_resilience import _free_port, _read_until_content, _serve, _sse_events

# ------------------------------------------------------- ResumeToken units


def test_resume_token_roundtrip():
    from localai_tpu.engine.resume import ResumeToken

    tok = ResumeToken(prompt_ids=[1, 2, 3], emitted=[4, 5], key=[7, 9],
                      sent_chars=11, chain=["ab12", "cd34"],
                      deadline_left=2.5, request_id="req-1", model="m")
    assert tok.generated == 2                      # auto-filled from emitted
    assert tok.resume_prompt == [1, 2, 3, 4, 5]
    back = ResumeToken.from_json(tok.to_json())
    assert back == tok
    assert back.payload() == {"emitted": 2, "key": [7, 9], "sent_chars": 11}


def test_resume_token_minimal_dict_and_defaults():
    from localai_tpu.engine.resume import ResumeToken

    tok = ResumeToken.from_dict({"prompt_ids": [1], "emitted": []})
    assert tok.key is None and tok.chain == [] and tok.generated == 0
    assert tok.deadline_left == 0.0 and tok.model == ""
    assert tok.payload() == {"emitted": 0, "key": None, "sent_chars": 0}
    # a caller-trimmed emitted list keeps its explicit generated count
    t2 = ResumeToken(prompt_ids=[1], emitted=[2], generated=5)
    assert t2.generated == 5


def test_resume_token_rejects_unknown_version():
    from localai_tpu.engine.resume import ResumeToken

    with pytest.raises(ValueError, match="version"):
        ResumeToken.from_dict({"v": 2, "prompt_ids": [], "emitted": []})


# ------------------------------------------- pool spill claims (ISSUE 19)


def _blk(seed: int = 0):
    from localai_tpu.engine.kvhost import HostKVBlock

    r = np.random.default_rng(seed)
    return HostKVBlock(
        kq=r.integers(-128, 127, (1, 1, 4, 2)).astype(np.int8),
        ks=r.random((1, 1, 1, 4)).astype(np.float32),
        vq=r.integers(-128, 127, (1, 1, 4, 2)).astype(np.int8),
        vs=r.random((1, 1, 1, 4)).astype(np.float32),
    )


BLK_BYTES = _blk().nbytes        # 48


def _h(i: int) -> bytes:
    return i.to_bytes(16, "big")


def test_spill_claim_refuses_zero_budget_and_dups():
    from localai_tpu.engine.kvhost import HostKVPool

    dead = HostKVPool(budget_bytes=0)
    assert not dead.begin_spill(_h(1)) and dead.stats()["rejects"] == 1
    pool = HostKVPool(budget_bytes=1 << 20)
    pool.put(_h(1), _blk(1))
    assert not pool.begin_spill(_h(1))       # already resident
    assert pool.begin_spill(_h(2))
    assert not pool.begin_spill(_h(2))       # identical spill in flight
    assert pool.stats()["pending_spills"] == 1
    pool.end_spill(_h(2), _blk(2))
    assert pool.contains(_h(2)) and pool.stats()["pending_spills"] == 0


def test_spill_claim_pins_chain_against_eviction():
    """The ISSUE 19 spill/evict race: an open spill batch pins every
    resident block of its group, so LRU pressure victimizes newcomers
    instead of freeing a chain head whose in-flight tail would be useless
    without it."""
    from localai_tpu.engine.kvhost import HostKVPool

    pool = HostKVPool(budget_bytes=3 * BLK_BYTES)
    g = _h(100)
    pool.put(_h(1), _blk(1), group=g)
    pool.put(_h(2), _blk(2), group=g)
    assert pool.begin_spill(_h(3), group=g)        # pins h1+h2
    pool.put(_h(4), _blk(4), group=_h(200))        # budget now full
    pool.put(_h(5), _blk(5), group=_h(200))        # overflow: g is LRU...
    # ...but its blocks are pinned — the newcomer loses instead
    assert pool.contains(_h(1)) and pool.contains(_h(2))
    assert not pool.contains(_h(5))
    # landing the claimed tail closes the batch, unpins the chain, and
    # settles any eviction the pins deferred (tail-first inside the group)
    pool.end_spill(_h(3), _blk(3))
    st = pool.stats()
    assert st["pending_spills"] == 0
    assert st["bytes"] <= 3 * BLK_BYTES
    assert pool.contains(_h(1))                    # chain head survives


def test_spill_claim_abandon_and_unclaimed_end():
    from localai_tpu.engine.kvhost import HostKVPool

    pool = HostKVPool(budget_bytes=1 << 20)
    assert pool.begin_spill(_h(1))
    assert pool.end_spill(_h(1), None) == 0        # abandoned D2H copy
    assert not pool.contains(_h(1))
    assert pool.stats()["pending_spills"] == 0
    # ending a never-claimed hash degrades to plain put / no-op
    pool.end_spill(_h(2), _blk(2))
    assert pool.contains(_h(2))
    assert pool.end_spill(_h(3), None) == 0
    assert not pool.contains(_h(3))


def test_spill_evict_race_threaded_stress():
    """Spiller vs evictor hammering one pool: no deadlock, no exception,
    and the books balance afterwards — budget respected, no claim or pin
    leaked, used_bytes equal to the sum of resident blocks."""
    from localai_tpu.engine.kvhost import HostKVPool

    pool = HostKVPool(budget_bytes=8 * BLK_BYTES)
    errs = []

    def spiller():
        try:
            for i in range(200):
                h, g = _h(1000 + i), _h(5000 + i // 4)
                if pool.begin_spill(h, group=g):
                    pool.end_spill(h, _blk(i) if i % 5 else None)
        except Exception as e:          # pragma: no cover - failure path
            errs.append(e)

    def churner():
        try:
            for i in range(200):
                pool.put(_h(2000 + i), _blk(i), group=_h(6000 + i // 3))
                pool.get(_h(1000 + i))
        except Exception as e:          # pragma: no cover - failure path
            errs.append(e)

    threads = [threading.Thread(target=spiller),
               threading.Thread(target=spiller),
               threading.Thread(target=churner)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=60)
        assert not t.is_alive(), "spill/evict stress deadlocked"
    assert not errs, errs
    st = pool.stats()
    assert st["pending_spills"] == 0
    assert st["bytes"] <= 8 * BLK_BYTES
    with pool._lock:
        assert sum(e.block.nbytes for e in pool._entries.values()) \
            == pool.used_bytes
        assert all(e.pins == 0 for e in pool._entries.values()), \
            "an open spill batch leaked pins"


# ------------------------------------------------- taxonomy / fault specs


def test_preempt_reason_codes_registered():
    from localai_tpu.telemetry.sched import REASON_CODES, reason_category

    assert reason_category("preempt_spill") == "kv"
    for code in ("resume_readmit", "resume_reprefill"):
        assert code in REASON_CODES
        assert reason_category(code) == "admission"


def test_fault_kinds_preempt_and_kill9(monkeypatch):
    from localai_tpu.testing import faults

    monkeypatch.setenv("LOCALAI_FAULT",
                       "preempt:2.5:1:gt,kill9_middecode:3::kt")
    monkeypatch.delenv("LOCALAI_FAULT_DIR", raising=False)
    monkeypatch.setattr(faults, "_local_counts", {})
    monkeypatch.setenv("LOCALAI_FAULT_MODEL", "gt")
    assert faults.fire("preempt") == 2.5           # arg = grace seconds
    assert faults.fire("preempt") is None          # limit 1 spent
    assert faults.fire("kill9_middecode") is None  # scoped to kt
    monkeypatch.setenv("LOCALAI_FAULT_MODEL", "kt")
    assert faults.fire("kill9_middecode") == 3.0   # unlimited
    assert faults.fire("kill9_middecode") == 3.0


# ------------------------------------------------- bridge resume lanes


def _api(**app_kw):
    from localai_tpu.config import AppConfig
    from localai_tpu.core.manager import ModelManager
    from localai_tpu.server.http import API

    app_cfg = AppConfig(**app_kw)
    return API(app_cfg, None, ModelManager(app_cfg))


def _mcfg(**kw):
    from localai_tpu.config import ModelConfig

    return ModelConfig(name="m", backend="llm", parallel=1, **kw)


def test_resume_opts_graceful_checkpoint_lane():
    from localai_tpu.engine.resume import ResumeToken

    api = _api()
    ckpt = ResumeToken(prompt_ids=[1, 2], emitted=[3, 4], key=[5, 6],
                       sent_chars=7, chain=["ab"], model="m").to_dict()
    opts = {"prompt_ids": [1, 2], "tokens": 16, "temperature": 0.0,
            "prompt": "x", "messages_json": "[]", "tools_json": "[]"}
    got = api._resume_opts(_mcfg(), opts, [1, 2], [3], 1, ckpt)
    assert got is not None
    ropts, mode, suppress, base = got
    assert mode == "resume" and suppress == [] and base == 2
    assert ropts["prompt_ids"] == [1, 2, 3, 4]     # engine-authoritative
    back = ResumeToken.from_json(ropts["resume_json"])
    assert back.key == [5, 6] and back.chain == ["ab"]
    # template/tool inputs must not be re-expanded on the resume leg
    for dead in ("prompt", "messages_json", "tools_json"):
        assert dead not in ropts


def test_resume_opts_synthesized_lane_needs_host_tier():
    api = _api()
    opts = {"prompt_ids": [1, 2], "tokens": 16, "temperature": 0.9}
    # pool enabled (model-level budget): bridge synthesizes the token
    got = api._resume_opts(_mcfg(kv_host_bytes=1 << 20), opts,
                           [1, 2], [7, 8, 9], 5, None)
    assert got is not None
    ropts, mode, suppress, base = got
    assert mode == "resume" and base == 3 and suppress == []
    assert ropts["prompt_ids"] == [1, 2, 7, 8, 9]
    tok = json.loads(ropts["resume_json"])
    assert tok["key"] is None and tok["chain"] == []   # died with the pool
    assert tok["sent_chars"] == 5
    # sampled + no pool anywhere: no lane — PR 4 terminal-error contract
    assert api._resume_opts(_mcfg(), opts, [1, 2], [7], 3, None) is None
    # nothing streamed yet → plain retry path, not a resume
    assert api._resume_opts(_mcfg(kv_host_bytes=1), opts,
                            [1, 2], [], 0, None) is None


def test_resume_opts_replay_lane_and_exclusions():
    api = _api()
    det = {"prompt_ids": [1, 2], "tokens": 16, "temperature": 0.0}
    got = api._resume_opts(_mcfg(), det, [1, 2], [5, 6, 7, 8, 9, 10], 9, None)
    assert got is not None
    ropts, mode, suppress, base = got
    assert mode == "replay"
    assert base == 2 and suppress == [7, 8, 9, 10]  # 4-token verify tail
    assert ropts["prompt_ids"] == [1, 2, 5, 6]
    assert ropts["tokens"] == 14                    # 16 - 2 folded
    # tools / stop strings / multimodal never replay
    assert api._resume_opts(_mcfg(), dict(det, tools_json="[{}]"),
                            [1], [5], 1, None) is None
    assert api._resume_opts(_mcfg(), dict(det, stop_prompts=["x"]),
                            [1], [5], 1, None) is None
    assert api._resume_opts(_mcfg(kv_host_bytes=1), dict(det, images=["i"]),
                            [1], [5], 1, None) is None


# --------------------------------------------------------- engine-level

TINY = dict(vocab_size=128, hidden_size=64, intermediate_size=128,
            num_layers=2, num_heads=4, num_kv_heads=2, head_dim=16,
            max_position=512, dtype="float32")


@pytest.fixture(scope="module")
def tiny_parts():
    import jax

    from localai_tpu.models.llama import LlamaConfig, init_params

    cfg = LlamaConfig(**TINY)
    return cfg, init_params(cfg, jax.random.PRNGKey(0))


def _mk(tiny_parts, kvhost=None, kv_host_bytes=0, loop=8, block=4):
    from localai_tpu.engine.engine import Engine, EngineConfig

    cfg, params = tiny_parts
    return Engine(cfg, params, None, EngineConfig(
        max_slots=2, max_context=512, prefill_buckets=(64,),
        prefill_chunk=64, kv_pages=6, prompt_cache=True,
        decode_loop=loop, decode_block=block,
        cache_type="int8", kv_host_bytes=kv_host_bytes), kvhost=kvhost)


def _run(eng, ids, n, params_=None, resume=None):
    from localai_tpu.engine.engine import GenRequest
    from localai_tpu.ops.sampling import SamplingParams

    rid, out = eng.submit(GenRequest(
        prompt_ids=list(ids), max_tokens=n,
        params=params_ or SamplingParams(temperature=0.0),
        ignore_eos=True, resume=resume))
    toks = []
    while True:
        eng.step()
        while not out.empty():
            so = out.get()
            if so.token_id >= 0:
                toks.append(so.token_id)
            if so.finished:
                while eng.step():
                    pass
                return toks


def _run_until_preempt(eng, ids, n, k, params_=None, resume=None):
    """Step until >=k tokens observed, then spill-drain; returns
    (emitted-so-far, resume manifest)."""
    from localai_tpu.engine.engine import GenRequest
    from localai_tpu.ops.sampling import SamplingParams

    rid, out = eng.submit(GenRequest(
        prompt_ids=list(ids), max_tokens=n,
        params=params_ or SamplingParams(temperature=0.0),
        ignore_eos=True, resume=resume))
    toks = []
    while len(toks) < k:
        eng.step()
        while not out.empty():
            so = out.get()
            if so.token_id >= 0:
                toks.append(so.token_id)
            assert not so.finished, "finished before the preempt landed"
    man = eng.preempt()
    term = None
    while not out.empty():
        so = out.get()
        if so.token_id >= 0:
            toks.append(so.token_id)
        if so.finished:
            term = so
    assert term is not None and term.finish_reason == "preempted"
    assert term.resume is not None
    return toks, man


PROMPT = np.random.default_rng(7).integers(1, 127, 200).tolist()
N = 48


@pytest.mark.slow
@pytest.mark.preempt
def test_greedy_parity_across_engine_death(tiny_parts):
    from localai_tpu.engine.resume import ResumeToken

    ref = _run(_mk(tiny_parts), PROMPT, N)
    eng = _mk(tiny_parts, kv_host_bytes=1 << 26)
    got, man = _run_until_preempt(eng, PROMPT, N, 10)
    assert eng.metrics["preempts"] == 1
    assert eng.metrics["preempt_spilled_blocks"] > 0
    tok = ResumeToken.from_dict(man[0])
    assert tok.emitted == got
    assert tok.chain, "a 200-token prompt must spill full KV blocks"
    assert tok.key is None                         # greedy: no RNG state
    # the engine object dies; only the host pool survives the "process"
    fresh = _mk(tiny_parts, kvhost=eng._kvhost)
    rest = _run(fresh, tok.resume_prompt, N - tok.generated,
                resume=tok.payload())
    assert got + rest == ref, "greedy resume diverged from the unbroken run"
    assert fresh.metrics["resume_readmits"] == 1
    assert fresh.metrics["resume_reprefills"] == 0


@pytest.mark.slow
@pytest.mark.preempt
def test_sampled_parity_via_persisted_rng_key(tiny_parts):
    from localai_tpu.engine.resume import ResumeToken
    from localai_tpu.ops.sampling import SamplingParams

    sp = SamplingParams(temperature=0.9, top_k=40, seed=123)
    ref = _run(_mk(tiny_parts), PROMPT, N, params_=sp)
    eng = _mk(tiny_parts, kv_host_bytes=1 << 26)
    got, man = _run_until_preempt(eng, PROMPT, N, 10, params_=sp)
    tok = ResumeToken.from_dict(man[0])
    assert tok.key is not None, "sampled checkpoint must carry the RNG key"
    fresh = _mk(tiny_parts, kvhost=eng._kvhost)
    rest = _run(fresh, tok.resume_prompt, N - tok.generated, params_=sp,
                resume=tok.payload())
    assert got + rest == ref, "sampled resume diverged (RNG key not restored)"


@pytest.mark.slow
@pytest.mark.preempt
def test_tiny_pool_falls_back_to_reprefill(tiny_parts):
    from localai_tpu.engine.resume import ResumeToken

    ref = _run(_mk(tiny_parts), PROMPT, N)
    eng = _mk(tiny_parts, kv_host_bytes=64)        # can't hold one block
    got, man = _run_until_preempt(eng, PROMPT, N, 10)
    tok = ResumeToken.from_dict(man[0])
    fresh = _mk(tiny_parts)                        # and no pool at all
    rest = _run(fresh, tok.resume_prompt, N - tok.generated,
                resume=tok.payload())
    assert got + rest == ref, "re-prefill fallback diverged"
    assert fresh.metrics["resume_reprefills"] == 1
    assert fresh.metrics["resume_readmits"] == 0


@pytest.mark.slow
@pytest.mark.preempt
def test_second_preempt_during_resume_folds_base(tiny_parts):
    """Drain interaction: a resume run preempted AGAIN must checkpoint
    against the ORIGINAL prompt boundary (resume_base folding), not the
    prompt+emitted resubmission, so a third engine still resumes cleanly."""
    from localai_tpu.engine.resume import ResumeToken

    ref = _run(_mk(tiny_parts), PROMPT, N)
    eng1 = _mk(tiny_parts, kv_host_bytes=1 << 26)
    got1, man1 = _run_until_preempt(eng1, PROMPT, N, 10)
    tok1 = ResumeToken.from_dict(man1[0])
    # short fused bursts so the second preempt lands well before max_tokens
    eng2 = _mk(tiny_parts, kvhost=eng1._kvhost, kv_host_bytes=1 << 26,
               loop=4, block=2)
    got2, man2 = _run_until_preempt(eng2, tok1.resume_prompt,
                                    N - tok1.generated, 4,
                                    resume=tok1.payload())
    tok2 = ResumeToken.from_dict(man2[0])
    assert tok2.prompt_ids == PROMPT, "resume_base folding lost the boundary"
    assert tok2.emitted == got1 + got2
    eng3 = _mk(tiny_parts, kvhost=eng2._kvhost)
    rest = _run(eng3, tok2.resume_prompt, N - tok2.generated,
                resume=tok2.payload())
    assert got1 + got2 + rest == ref, "double-preempt resume diverged"


# --------------------------------------------------- chaos: HTTP stack

_FAULTS = ",".join([
    "preempt:0:1:gtiny",           # SIGTERM notice after gtiny's first token
    "kill9_middecode:2:1:ktiny",   # SIGKILL at ktiny's 2nd emitted token
    "kill9_middecode:2:1:ntiny",   # ditto, model without the host KV tier
    "kill9_middecode:2:1:rtiny",   # ditto, greedy → deterministic replay
    "stall_stream:1.5:1:ptiny",    # holds a stream open for the drain race
])


@pytest.fixture(scope="module")
def preempt_faultenv(tmp_path_factory):
    import os

    fault_dir = str(tmp_path_factory.mktemp("faults-preempt"))
    old = {k: os.environ.get(k)
           for k in ("LOCALAI_FAULT", "LOCALAI_FAULT_DIR")}
    os.environ["LOCALAI_FAULT"] = _FAULTS
    os.environ["LOCALAI_FAULT_DIR"] = fault_dir
    yield fault_dir
    for k, v in old.items():
        if v is None:
            os.environ.pop(k, None)
        else:
            os.environ[k] = v


def _write_kv_model(models, name, ckpt, kv_host_bytes=0):
    # 512-token context + 256-token generations below: the preempt SIGTERM
    # fires after the FIRST emitted token, so the generation must outlast
    # the signal→spill-drain latency or the stream finishes before the
    # freeze and nothing is left to resume
    (models / f"{name}.yaml").write_text(yaml.safe_dump({
        "name": name,
        "backend": "llm",
        "context_size": 512,
        "parallel": 2,
        "dtype": "float32",
        "prefill_buckets": [32, 64],
        "kv_pages": 8,
        "kv_host_bytes": kv_host_bytes,
        "parameters": {"model": ckpt, "temperature": 0.0, "max_tokens": 8},
    }))


@pytest.fixture(scope="module")
def pstack(tmp_path_factory, preempt_faultenv):
    import os

    from localai_tpu.config import AppConfig

    ckpt = tiny_checkpoint(tmp_path_factory, max_position=512)
    models = tmp_path_factory.mktemp("models-preempt")
    for name in ("gtiny", "ktiny", "ptiny"):
        _write_kv_model(models, name, ckpt, kv_host_bytes=1 << 26)
    for name in ("ntiny", "rtiny"):
        _write_kv_model(models, name, ckpt)
    os.environ["LOCALAI_JAX_PLATFORM"] = "cpu"
    app_cfg = AppConfig(
        address=f"127.0.0.1:{_free_port()}", models_path=str(models),
        parallel_requests=2, retry_budget=1, spawn_retries=1,
        spawn_timeout=60.0, drain_timeout=10.0)
    base, manager, api, stop = _serve(app_cfg, models)
    yield base, manager, api
    stop()


def _pchat(base, model, n=256, stream=True, temperature=None, timeout=300):
    body = {
        "model": model,
        "messages": [{"role": "user", "content": "the quick brown"}],
        "max_tokens": n,
        "stream": stream,
    }
    if temperature is not None:
        body["temperature"] = temperature
    return requests.post(base + "/v1/chat/completions", json=body,
                         stream=stream, timeout=timeout)


def _delta_text(events):
    return "".join(
        e["choices"][0].get("delta", {}).get("content") or ""
        for e in events
        if isinstance(e, dict) and e.get("choices"))


def _assert_uninterrupted(events):
    assert events and events[-1] == "DONE", f"stream did not finish: {events}"
    errors = [e for e in events if isinstance(e, dict) and "error" in e]
    assert not errors, f"resume leaked an error event: {errors}"
    finals = [e for e in events if isinstance(e, dict) and e.get("choices")
              and e["choices"][0].get("finish_reason")]
    assert finals, "stream ended without finish_reason"


@pytest.mark.slow
@pytest.mark.resilience
def test_graceful_preempt_one_uninterrupted_stream(pstack):
    """SIGTERM preemption notice mid-stream: the dying backend spill-drains
    a full ResumeToken, the bridge re-issues it on the respawned backend,
    and the client sees ONE clean stream whose text byte-matches an
    unbroken run — with localai_resume_total{outcome="ok"} to prove the
    checkpoint lane (not a silent full retry) carried it."""
    base, manager, _ = pstack
    events = _sse_events(_pchat(base, "gtiny", timeout=(30, 300)))
    _assert_uninterrupted(events)
    text = _delta_text(events)
    assert text, "no content reached the client"
    # fault limit 1 is consumed: this reference run is unbroken, greedy
    ref = _pchat(base, "gtiny", stream=False)
    assert ref.status_code == 200, ref.text
    assert text == ref.json()["choices"][0]["message"]["content"], \
        "resumed stream text diverged from the unbroken run"
    m = requests.get(base + "/metrics", timeout=30)
    assert b'localai_resume_total{model="gtiny",outcome="ok"}' in m.content


@pytest.mark.slow
@pytest.mark.resilience
def test_kill9_middecode_with_host_tier_resumes(pstack):
    """kill -9 at the 2nd emitted token, host KV tier enabled: no drain ran
    and no checkpoint exists — the bridge synthesizes a ResumeToken from
    its own accumulated stream state and the client still sees one
    uninterrupted, byte-exact stream."""
    base, manager, _ = pstack
    events = _sse_events(_pchat(base, "ktiny", timeout=(30, 300)))
    _assert_uninterrupted(events)
    text = _delta_text(events)
    ref = _pchat(base, "ktiny", stream=False)
    assert ref.status_code == 200, ref.text
    assert text == ref.json()["choices"][0]["message"]["content"]
    m = requests.get(base + "/metrics", timeout=30)
    assert b'localai_resume_total{model="ktiny",outcome="ok"}' in m.content


@pytest.mark.slow
@pytest.mark.resilience
def test_kill9_no_pool_sampled_keeps_terminal_error_contract(pstack):
    """Resume disabled (no host tier) and non-deterministic sampling: no
    lane applies, so the PR 4 contract holds — a clean terminal SSE error
    event and [DONE], never a hung connection."""
    base, _, _ = pstack
    events = _sse_events(_pchat(base, "ntiny", temperature=0.9,
                                timeout=(30, 300)))
    assert events and events[-1] == "DONE", f"hung/severed stream: {events}"
    errors = [e for e in events if isinstance(e, dict) and "error" in e]
    assert errors, f"expected a terminal SSE error event, got {events}"
    assert errors[-1]["error"]["code"] in (502, 503)


@pytest.mark.slow
@pytest.mark.resilience
def test_kill9_no_pool_greedy_deterministic_replay(pstack):
    """Resume disabled but the request is temperature-0: the replay lane
    re-prefills prompt+emitted minus a verification tail and the stream
    completes seamlessly, counted as outcome="replay"."""
    base, _, _ = pstack
    events = _sse_events(_pchat(base, "rtiny", timeout=(30, 300)))
    _assert_uninterrupted(events)
    text = _delta_text(events)
    ref = _pchat(base, "rtiny", stream=False)
    assert ref.status_code == 200, ref.text
    assert text == ref.json()["choices"][0]["message"]["content"]
    m = requests.get(base + "/metrics", timeout=30)
    assert b'localai_resume_total{model="rtiny",outcome="replay"}' in m.content


@pytest.mark.slow
@pytest.mark.resilience
def test_preempt_endpoint_then_drain_never_hangs_stream(pstack):
    """/backend/preempt validation plus the drain interaction: a preempt
    fired into a live stream, immediately followed by a full drain, must
    still terminate the client stream with [DONE] — resumed or failed,
    but never wedged. Runs last in this module: the drain stops the stack."""
    base, manager, _ = pstack
    r = requests.post(base + "/backend/preempt", json={}, timeout=30)
    assert r.status_code == 400                      # model is required

    s = _pchat(base, "ptiny", timeout=(30, 120))
    it = s.iter_lines()
    assert _read_until_content(it)       # stream live; stall holds it ~1.5 s
    done = {}

    def preempt():
        done["p"] = requests.post(base + "/backend/preempt",
                                  json={"model": "ptiny"}, timeout=60)

    def shutdown():
        done["s"] = requests.post(base + "/backend/shutdown", json={},
                                  timeout=120)

    tp = threading.Thread(target=preempt)
    tp.start()
    time.sleep(0.3)
    ts = threading.Thread(target=shutdown)
    ts.start()
    tail = []
    for line in it:                      # MUST terminate, resumed or not
        if line.startswith(b"data: "):
            payload = line[6:]
            tail.append("DONE" if payload == b"[DONE]"
                        else json.loads(payload))
    assert tail and tail[-1] == "DONE", f"drain+preempt hung the stream: {tail}"
    tp.join(timeout=60)
    ts.join(timeout=120)
    assert done["p"].status_code == 200
    assert done["s"].status_code == 200 and done["s"].json()["success"]
