"""Fused multi-step ragged ticks (ISSUE 16).

Tier-1 (cheap units): the decode-token-aware dispatch budget has teeth in
BOTH directions, `_ragged_loop_fn` rides the compile-count guard's attr
list, and bench.py's probe-keepalive reuse path works on CPU (fake child —
the protocol, not the chip, is under test).

Slow (engine-driving, per PR 8/10 precedent): exact token parity fused vs
single-step ragged across greedy + sampled + grammar tenants with
admissions landing mid-decode, a same-tick admission forcing the
prefill early exit, and the zero-recompile guard across two mixed streams.
"""
import numpy as np
import pytest

from fixtures import tiny_checkpoint
from localai_tpu.engine import (
    Engine, EngineConfig, GenRequest, Tokenizer, load_config, load_params,
)
from localai_tpu.ops.sampling import SamplingParams

pytestmark = pytest.mark.ragged


# -------------------------------------------------- dispatch-budget teeth

class _StubEngine:
    """dispatch_budget only reads engine.metrics — a dict stands in."""

    def __init__(self, **metrics):
        self.metrics = dict(
            decode_dispatches=0, tokens_generated=0,
            ragged_dispatches=0, ragged_prefill_tokens=0,
            spec_ragged_dispatches=0)
        self.metrics.update(metrics)


def _flightrec_sandbox(monkeypatch, tmp_path):
    from localai_tpu import telemetry

    monkeypatch.setenv("LOCALAI_FLIGHTREC_DIR", str(tmp_path))
    telemetry.reset_flightrec()


def test_dispatch_budget_trips_on_single_step_ragged(monkeypatch, tmp_path):
    """Teeth, trip direction: the blanket ragged exemption is GONE — a
    decode-heavy single-step ragged stream (~1 dispatch per generated
    token, no prefill credit) blows a 3/128 budget."""
    from localai_tpu import telemetry
    from localai_tpu.testing.tripwires import dispatch_budget

    _flightrec_sandbox(monkeypatch, tmp_path)
    try:
        eng = _StubEngine()
        with pytest.raises(AssertionError, match="dispatch budget"):
            with dispatch_budget(eng, max_per_128_tokens=3.0):
                eng.metrics["decode_dispatches"] += 128
                eng.metrics["ragged_dispatches"] += 128
                eng.metrics["tokens_generated"] += 128
    finally:
        telemetry.reset_flightrec()


def test_dispatch_budget_passes_fused_and_prefill_credit():
    """Teeth, pass direction: a fused multi-step stream (few dispatches,
    many tokens) and a prefill-heavy pack stream (`ragged_prefill_tokens`
    earns credit) both clear the same budget the single-step stream
    trips."""
    from localai_tpu.testing.tripwires import dispatch_budget

    eng = _StubEngine()
    with dispatch_budget(eng, max_per_128_tokens=3.0):
        # fused: 128 tokens over 3 dispatches (~16 steps/dispatch + ticks)
        eng.metrics["decode_dispatches"] += 3
        eng.metrics["ragged_dispatches"] += 3
        eng.metrics["tokens_generated"] += 128
    eng = _StubEngine()
    with dispatch_budget(eng, max_per_128_tokens=3.0):
        # admission burst: 3 dispatches packing 128 prefill tokens and
        # generating nothing yet — budget comes from the packed tokens
        eng.metrics["decode_dispatches"] += 3
        eng.metrics["ragged_dispatches"] += 3
        eng.metrics["ragged_prefill_tokens"] += 128


def test_dispatch_budget_spec_ragged_stays_exempt(monkeypatch, tmp_path):
    """Spec-as-ragged keeps the exemption (gamma-fused by construction,
    gated by acceptance telemetry): the same dispatch count that trips as
    plain ragged passes when attributed to spec_ragged_dispatches."""
    from localai_tpu import telemetry
    from localai_tpu.testing.tripwires import dispatch_budget

    eng = _StubEngine()
    with dispatch_budget(eng, max_per_128_tokens=3.0):
        eng.metrics["decode_dispatches"] += 64
        eng.metrics["ragged_dispatches"] += 64
        eng.metrics["spec_ragged_dispatches"] += 64
        eng.metrics["tokens_generated"] += 128
    _flightrec_sandbox(monkeypatch, tmp_path)
    try:
        eng = _StubEngine()
        with pytest.raises(AssertionError, match="dispatch budget"):
            with dispatch_budget(eng, max_per_128_tokens=3.0):
                eng.metrics["decode_dispatches"] += 64
                eng.metrics["ragged_dispatches"] += 64
                eng.metrics["tokens_generated"] += 128
    finally:
        telemetry.reset_flightrec()


def test_ragged_loop_fn_rides_compile_count_guard():
    from localai_tpu.testing.tripwires import DECODE_FN_ATTRS

    assert "_ragged_loop_fn" in DECODE_FN_ATTRS


# ------------------------------------------------- probe keepalive (CPU)

_FAKE_PROBE_CHILD = r"""
import sys
for p in ("plugin_handshake", "client_init", "first_device_put",
          "first_compile"):
    print(f"PROBE_PHASE {p} 0.0s", flush=True)
print("PROBE_OK cpu cpu 0s", flush=True)
for line in sys.stdin:
    cmd = line.strip()
    if cmd == "PING":
        print("PROBE_ALIVE cpu cpu", flush=True)
    elif cmd == "QUIT":
        break
"""


def test_probe_keepalive_reuses_live_client(monkeypatch):
    """--probe-keepalive: the first probe cold-starts one child; the next
    probe PINGs it instead of re-running the ladder (the pre-initialized
    device-client reuse path). Fake child — protocol-level unit test."""
    import bench

    monkeypatch.setattr(bench, "_KEEPALIVE_CHILD", _FAKE_PROBE_CHILD)
    monkeypatch.setattr(bench, "_KEEPALIVE", None)
    args = bench.build_parser().parse_args(
        ["--mode", "engine", "--probe-keepalive"])
    use_cpu, err, kind = bench.probe_accelerator(args)
    assert (use_cpu, err, kind) == (True, "", "cpu")
    a = args.probe_report["attempts"][0]
    assert a["ok"] and a["keepalive"] and a["phases_s"]["first_compile"] == 0
    ka = bench._KEEPALIVE
    assert ka is not None and ka.alive()
    try:
        args2 = bench.build_parser().parse_args(
            ["--mode", "ragged", "--probe-keepalive"])
        use_cpu2, err2, kind2 = bench.probe_accelerator(args2)
        assert (use_cpu2, err2, kind2) == (True, "", "cpu")
        assert args2.probe_report["keepalive_reused"] is True
        # reuse = NO new cold attempt, same live child
        assert args2.probe_report["attempts"] == []
        assert bench._KEEPALIVE is ka and ka.alive()
    finally:
        ka.close()
        bench._KEEPALIVE = None
    assert not ka.alive()


def test_probe_keepalive_dead_child_cold_probes(monkeypatch):
    """A died keepalive child doesn't poison later probes: ping fails and
    the next call cold-starts a fresh child."""
    import bench

    monkeypatch.setattr(bench, "_KEEPALIVE_CHILD", _FAKE_PROBE_CHILD)
    monkeypatch.setattr(bench, "_KEEPALIVE", None)
    args = bench.build_parser().parse_args(
        ["--mode", "engine", "--probe-keepalive"])
    bench.probe_accelerator(args)
    bench._KEEPALIVE.proc.kill()
    bench._KEEPALIVE.proc.wait()
    args2 = bench.build_parser().parse_args(
        ["--mode", "engine", "--probe-keepalive"])
    use_cpu, err, kind = bench.probe_accelerator(args2)
    assert (use_cpu, err, kind) == (True, "", "cpu")
    assert "keepalive_reused" not in args2.probe_report
    assert args2.probe_report["attempts"][0]["ok"]
    bench._KEEPALIVE.close()
    bench._KEEPALIVE = None


# --------------------------------------------- engine parity (slow tier)

@pytest.fixture(scope="module")
def loaded(tmp_path_factory):
    ckpt = tiny_checkpoint(tmp_path_factory)
    cfg = load_config(ckpt, dtype="float32")
    params = load_params(ckpt, cfg)
    tok = Tokenizer.from_dir(ckpt)
    return cfg, params, tok


def _ec(loop_steps, **kw):
    return EngineConfig(max_slots=4, max_context=128,
                        prefill_buckets=(16, 64), prefill_chunk=16,
                        kv_pages=10, prompt_cache=False,
                        ragged_token_budget=64,
                        ragged_loop_steps=loop_steps, **kw)


def _mixed_reqs(cfg, n_tok=10):
    rng = np.random.default_rng(0)
    lens = (5, 12, 33, 7, 21, 3)
    sps = [SamplingParams(temperature=0.0),
           SamplingParams(temperature=0.8, seed=11),
           SamplingParams(temperature=0.7, top_p=0.9, seed=3),
           SamplingParams(temperature=0.0),
           SamplingParams(temperature=1.0, top_k=5, seed=7),
           SamplingParams(temperature=0.0)]
    return [GenRequest(rng.integers(5, cfg.vocab_size, n).tolist(), sp,
                       max_tokens=n_tok, ignore_eos=True)
            for n, sp in zip(lens, sps)]


def _run_stream(cfg, params, tok, loop_steps):
    """The test_ragged mixed stream with admissions landing mid-decode —
    exactly the trace where same-tick admission forces the fused loop's
    prefill early exit."""
    eng = Engine(cfg, params, tok, _ec(loop_steps))
    reqs = _mixed_reqs(cfg)
    outs = [eng.submit(r) for r in reqs[:3]]
    for _ in range(3):
        eng.step()
    outs += [eng.submit(r) for r in reqs[3:]]
    for _ in range(500):
        if not eng.step():
            break
    toks = []
    for _, q in outs:
        seq = []
        while not q.empty():
            o = q.get_nowait()
            if o.token_id >= 0:
                seq.append(o.token_id)
        toks.append(seq)
    return toks, dict(eng.metrics)


@pytest.mark.slow
def test_fused_parity_and_early_exit(loaded):
    """Acceptance: the fused multi-step engine emits token streams
    IDENTICAL to single-step ragged (greedy + seeded top-p/top-k, mixed
    lengths, mid-decode admissions), while spending strictly fewer decode
    dispatches — and the mid-loop admissions force prefill early exits."""
    cfg, params, tok = loaded
    single, m1 = _run_stream(cfg, params, tok, loop_steps=0)
    fused, mf = _run_stream(cfg, params, tok, loop_steps=16)
    assert all(len(s) == 10 for s in single)
    assert single == fused
    # the dispatch boundary actually amortized
    assert mf["decode_dispatches"] < m1["decode_dispatches"], (mf, m1)
    assert mf["decode_steps_dispatched"] / mf["decode_dispatches"] > \
        m1["decode_steps_dispatched"] / m1["decode_dispatches"]
    # exit-reason taxonomy populated: finishes always, prefill exits from
    # the mid-decode admissions (the queue was non-empty at dispatch time)
    exits = {k: v for k, v in mf.items()
             if k.startswith("rloop_exit_") and v > 0}
    assert exits.get("rloop_exit_finish", 0) > 0, mf
    assert exits.get("rloop_exit_prefill", 0) > 0, mf
    assert m1.get("rloop_exit_finish", 0) == 0  # single-step never loops


@pytest.mark.slow
def test_fused_grammar_parity(loaded):
    """Grammar-table slots ride the fused loop (device mask gather +
    state advance per iteration) and match single-step ragged exactly,
    greedy and sampled."""
    from localai_tpu.functions.grammars import json_schema_grammar

    cfg, params, tok = loaded
    schema = {"type": "object",
              "properties": {"a": {"type": "integer"},
                             "b": {"type": "string"}},
              "required": ["a", "b"]}

    def reqs():
        g1 = GenRequest(tok.encode("emit json:"),
                        SamplingParams(temperature=0.0, seed=5),
                        max_tokens=24,
                        grammar=json_schema_grammar(schema))
        g2 = GenRequest(tok.encode("emit json:"),
                        SamplingParams(temperature=0.9, seed=9),
                        max_tokens=24,
                        grammar=json_schema_grammar(schema))
        p = GenRequest(tok.encode("the quick brown fox"),
                       SamplingParams(temperature=0.0),
                       max_tokens=10, ignore_eos=True)
        return [g1, p, g2]

    def drain(loop_steps):
        eng = Engine(cfg, params, tok, _ec(loop_steps))
        outs = [eng.submit(r) for r in reqs()]
        for _ in range(500):
            if not eng.step():
                break
        res = []
        for _, q in outs:
            ids, fin = [], None
            while not q.empty():
                o = q.get_nowait()
                if o.token_id >= 0:
                    ids.append(o.token_id)
                if o.finished:
                    fin = o.finish_reason
            res.append((ids, fin))
        return res, dict(eng.metrics)

    a, m1 = drain(0)
    b, mf = drain(16)
    assert a == b, (a, b)
    assert sum(v for k, v in mf.items()
               if k.startswith("rloop_exit_")) > 0, mf


@pytest.mark.slow
def test_fused_zero_recompiles_two_streams(loaded):
    """Compile-count guard over the fused program: after warmup, TWO mixed
    streams with mid-loop admissions add zero XLA compilations and the
    `_ragged_loop_fn` jit cache stays at its warm size."""
    from localai_tpu.testing.tripwires import (
        CompileCounter, decode_cache_sizes, decode_compile_count,
    )

    cfg, params, tok = loaded
    eng = Engine(cfg, params, tok, _ec(16))
    assert eng._ragged_loop_fn is not None
    eng.warmup()

    def stream():
        reqs = _mixed_reqs(cfg, n_tok=8)
        outs = [eng.submit(r) for r in reqs[:3]]
        for _ in range(2):
            eng.step()
        outs += [eng.submit(r) for r in reqs[3:]]
        for _ in range(500):
            if not eng.step():
                break
        return outs

    stream()  # warm stream: host-side admission programs (_install_row
    #           etc.) compile on first use, same as the soup precedent
    warm = decode_compile_count(eng)
    sizes = decode_cache_sizes(eng)
    assert sizes.get("_ragged_loop_fn", 0) >= 1, sizes
    with CompileCounter() as cc:
        stream()
        stream()
    assert cc.total == 0, cc.counts
    assert decode_compile_count(eng) == warm, decode_cache_sizes(eng)
    assert eng.metrics["tokens_by_path__rloop"] + \
        eng.metrics["tokens_by_path__ragged"] > 0
