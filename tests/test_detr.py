"""DETR detection (Detect RPC model family) vs HF torch parity on a
locally-built tiny random checkpoint."""
import json
import os

import numpy as np
import pytest


def _make_ckpt(tmpdir, layer_type="basic"):
    import torch
    from transformers import DetrConfig, DetrForObjectDetection, ResNetConfig

    torch.manual_seed(0)
    cfg = DetrConfig(
        use_timm_backbone=False, use_pretrained_backbone=False,
        backbone_config=ResNetConfig(
            embedding_size=8, hidden_sizes=[8, 16], depths=[1, 2],
            layer_type=layer_type, num_channels=3),
        d_model=32, encoder_layers=2, decoder_layers=2,
        encoder_attention_heads=4, decoder_attention_heads=4,
        encoder_ffn_dim=64, decoder_ffn_dim=64, num_queries=6, num_labels=4,
        id2label={0: "cat", 1: "dog", 2: "bird", 3: "fish"},
        label2id={"cat": 0, "dog": 1, "bird": 2, "fish": 3},
    )
    m = DetrForObjectDetection(cfg)
    m.eval()
    m.save_pretrained(tmpdir, safe_serialization=True)
    return m


@pytest.fixture(scope="module", params=["basic", "bottleneck"])
def detr_pair(request, tmp_path_factory):
    d = str(tmp_path_factory.mktemp(f"detr-{request.param}"))
    m = _make_ckpt(d, request.param)
    return d, m


def test_forward_matches_hf(detr_pair):
    import torch

    import jax.numpy as jnp
    from localai_tpu.models.detr import (
        detr_forward, load_detr_config, load_detr_params,
    )

    d, m = detr_pair
    cfg = load_detr_config(d)
    params = load_detr_params(d, cfg)
    rng = np.random.default_rng(0)
    pix = rng.normal(size=(1, 64, 64, 3)).astype(np.float32)

    logits, boxes = detr_forward(params, cfg, jnp.asarray(pix))
    with torch.no_grad():
        ref = m(pixel_values=torch.tensor(pix.transpose(0, 3, 1, 2)))
    np.testing.assert_allclose(np.asarray(logits), ref.logits.numpy(),
                               rtol=1e-3, atol=1e-3)
    np.testing.assert_allclose(np.asarray(boxes), ref.pred_boxes.numpy(),
                               rtol=1e-3, atol=1e-3)


def test_detector_end_to_end(detr_pair, tmp_path):
    from PIL import Image

    from localai_tpu.models.detr import (
        Detector, load_detr_config, load_detr_params,
    )

    d, _ = detr_pair
    cfg = load_detr_config(d)
    params = load_detr_params(d, cfg)
    det = Detector(cfg, params, sizes=(64,), threshold=0.0)
    img = Image.fromarray(
        (np.random.default_rng(1).uniform(0, 255, (48, 80, 3))).astype(
            np.uint8))
    path = str(tmp_path / "img.png")
    img.save(path)
    dets = det.detect(path)
    assert len(dets) > 0
    for dd in dets:
        assert dd.class_name in ("cat", "dog", "bird", "fish")
        assert 0.0 <= dd.confidence <= 1.0


def test_detect_servicer(detr_pair, tmp_path):
    from PIL import Image

    from localai_tpu.backend import pb
    from localai_tpu.backend.detect import DetectServicer

    d, _ = detr_pair
    s = DetectServicer()
    r = s.LoadModel(pb.ModelOptions(model=d), None)
    assert r.success, r.message
    img = Image.fromarray(np.zeros((32, 32, 3), np.uint8))
    path = str(tmp_path / "z.png")
    img.save(path)
    resp = s.Detect(pb.DetectOptions(src=path), _Ctx())
    assert isinstance(resp.detections, object)


class _Ctx:
    def abort(self, code, details):
        raise AssertionError(f"{code}: {details}")


@pytest.fixture(scope="module")
def detect_stack(tmp_path_factory):
    """API server + real spawned detect backend subprocess."""
    import asyncio
    import socket
    import threading
    import time

    import requests
    import yaml
    from aiohttp import web

    from localai_tpu.config import AppConfig, ModelConfigLoader
    from localai_tpu.core.manager import ModelManager
    from localai_tpu.server.http import API

    ckpt = str(tmp_path_factory.mktemp("detr-http"))
    _make_ckpt(ckpt, "basic")
    models = tmp_path_factory.mktemp("models")
    (models / "det.yaml").write_text(yaml.safe_dump({
        "name": "det", "backend": "detect",
        "parameters": {"model": ckpt},
    }))
    os.environ["LOCALAI_JAX_PLATFORM"] = "cpu"
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    app_cfg = AppConfig(address=f"127.0.0.1:{port}",
                        models_path=str(models))
    manager = ModelManager(app_cfg)
    api = API(app_cfg, ModelConfigLoader(str(models)), manager)
    loop = asyncio.new_event_loop()

    def run():
        asyncio.set_event_loop(loop)
        runner = web.AppRunner(api.app)
        loop.run_until_complete(runner.setup())
        site = web.TCPSite(runner, "127.0.0.1", port)
        loop.run_until_complete(site.start())
        loop.run_forever()

    threading.Thread(target=run, daemon=True).start()
    base = f"http://127.0.0.1:{port}"
    for _ in range(50):
        try:
            requests.get(base + "/healthz", timeout=1)
            break
        except requests.ConnectionError:
            time.sleep(0.1)
    yield base
    manager.stop_all()
    loop.call_soon_threadsafe(loop.stop)


def test_http_detection_endpoint(detect_stack, tmp_path):
    import base64

    import requests
    from PIL import Image

    img = Image.fromarray(
        np.random.default_rng(7).integers(0, 255, (40, 60, 3), np.uint8,
                                          endpoint=False))
    path = tmp_path / "det.png"
    img.save(str(path))
    b64 = base64.b64encode(path.read_bytes()).decode()
    r = requests.post(detect_stack + "/v1/detection", json={
        "model": "det", "image": b64}, timeout=600)
    assert r.status_code == 200, r.text
    dets = r.json()["detections"]
    for d in dets:
        assert set(d) == {"x", "y", "width", "height", "confidence",
                          "class_name"}
