"""Mixtral MoE (expert-routed MLP) vs HF torch parity + engine serving +
expert-parallel sharding on the virtual mesh."""
import numpy as np
import pytest


@pytest.fixture(scope="module")
def mixtral_ckpt(tmp_path_factory):
    import torch
    from transformers import MixtralConfig, MixtralForCausalLM

    d = str(tmp_path_factory.mktemp("mixtral"))
    torch.manual_seed(0)
    cfg = MixtralConfig(
        vocab_size=128, hidden_size=32, intermediate_size=64,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        num_local_experts=4, num_experts_per_tok=2,
        max_position_embeddings=128, rms_norm_eps=1e-5, rope_theta=10000.0,
        tie_word_embeddings=False)
    m = MixtralForCausalLM(cfg)
    m.eval()
    m.save_pretrained(d, safe_serialization=True)
    return d, m


def test_config_loads_moe(mixtral_ckpt):
    from localai_tpu.engine.loader import load_config

    d, _ = mixtral_ckpt
    cfg = load_config(d, dtype="float32")
    assert cfg.num_experts == 4 and cfg.experts_per_tok == 2


def test_forward_matches_hf(mixtral_ckpt):
    import torch

    import jax.numpy as jnp
    from localai_tpu.engine.loader import load_config, load_params
    from localai_tpu.models.llama import forward_train

    d, m = mixtral_ckpt
    cfg = load_config(d, dtype="float32")
    params = load_params(d, cfg, dtype="float32")
    ids = np.array([[1, 5, 9, 13, 17, 21, 25, 29]], np.int64)

    ours = np.asarray(forward_train(params, cfg, jnp.asarray(ids, jnp.int32)))
    with torch.no_grad():
        ref = m(input_ids=torch.tensor(ids)).logits.numpy()
    np.testing.assert_allclose(ours, ref, rtol=2e-3, atol=2e-3)


def test_decode_matches_hf_greedy(mixtral_ckpt):
    import torch

    import jax.numpy as jnp
    from localai_tpu.engine.loader import load_config, load_params
    from localai_tpu.models.llama import (
        decode_step, init_kv_cache, prefill,
    )
    from localai_tpu.ops.rope import rope_table

    d, m = mixtral_ckpt
    cfg = load_config(d, dtype="float32")
    params = load_params(d, cfg, dtype="float32")
    prompt = [1, 7, 14, 21]
    with torch.no_grad():
        ref = m.generate(torch.tensor([prompt]), max_new_tokens=6,
                         do_sample=False).tolist()[0][len(prompt):]

    B, T = 1, 64
    kc, vc = init_kv_cache(cfg, B, T)
    cos, sin = rope_table(cfg.rope, T)
    toks = jnp.asarray([prompt], jnp.int32)
    lengths = jnp.array([len(prompt)], jnp.int32)
    logits, kc, vc = prefill(params, cfg, toks, lengths, cos, sin, kc, vc,
                             jnp.arange(B))
    out = []
    cur = int(np.argmax(np.asarray(logits)[0]))
    for _ in range(6):
        out.append(cur)
        logits, kc, vc = decode_step(params, cfg, jnp.asarray([cur]),
                                     lengths, cos, sin, kc, vc)
        lengths = lengths + 1
        cur = int(np.argmax(np.asarray(logits)[0]))
    assert out == ref


def test_engine_serves_moe(mixtral_ckpt):
    from localai_tpu.engine import Engine, EngineConfig
    from localai_tpu.engine.engine import GenRequest, SamplingParams
    from localai_tpu.engine.loader import load_config, load_params

    d, _ = mixtral_ckpt
    cfg = load_config(d, dtype="float32")
    params = load_params(d, cfg, dtype="float32")
    eng = Engine(cfg, params, None, EngineConfig(
        max_slots=2, max_context=64, prefill_buckets=(16,),
        prefill_chunk=16))
    eng.start()
    try:
        _, q = eng.submit(GenRequest(
            prompt_ids=[3, 6, 9], max_tokens=8, ignore_eos=True,
            params=SamplingParams(temperature=0.0, seed=1)))
        n = 0
        while True:
            o = q.get(timeout=120)
            n += 1
            if o.finished:
                break
        assert n == 8
    finally:
        eng.stop()


def test_expert_parallel_parity(mixtral_ckpt, mesh8):
    """TP+EP sharded forward (experts on the `model` axis) must match the
    unsharded forward on the virtual 8-device mesh."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding
    from localai_tpu.engine.loader import load_config, load_params
    from localai_tpu.models.llama import (
        forward_train, max_model_axis, param_specs,
    )
    from localai_tpu.parallel.mesh import MeshConfig, activate_mesh, build_mesh

    d, _ = mixtral_ckpt
    cfg = load_config(d, dtype="float32")
    params = load_params(d, cfg, dtype="float32")
    ids = jnp.asarray([[2, 4, 8, 16, 32, 64, 3, 1]], jnp.int32)
    ref = np.asarray(forward_train(params, cfg, ids))

    model = max_model_axis(cfg, 4)
    assert model == 2     # experts allow 4, but kv-head sharding caps at 2
    mesh = build_mesh(MeshConfig(data=1, model=model), jax.devices()[:model])
    specs = param_specs(cfg)
    sharded = jax.tree_util.tree_map(
        lambda x, s: jax.device_put(x, NamedSharding(mesh, s)), params, specs)
    with activate_mesh(mesh):
        out = np.asarray(forward_train(sharded, cfg, ids))
    np.testing.assert_allclose(out, ref, rtol=2e-3, atol=2e-3)


def test_moe_int8_quantized_path(mixtral_ckpt):
    import jax.numpy as jnp
    from localai_tpu.engine.loader import load_config, load_params
    from localai_tpu.models.llama import forward_train

    d, _ = mixtral_ckpt
    cfg = load_config(d, dtype="float32")
    dense = load_params(d, cfg, dtype="float32")
    quant = load_params(d, cfg, dtype="int8")
    ids = jnp.asarray([[5, 10, 15, 20]], jnp.int32)
    a = np.asarray(forward_train(dense, cfg, ids))
    b = np.asarray(forward_train(quant, cfg, ids))
    # int8 error is bounded relative to the logit scale
    assert np.max(np.abs(a - b)) < 0.1 * max(np.max(np.abs(a)), 1.0)
