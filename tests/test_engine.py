"""End-to-end engine tests against a real (locally built) HF checkpoint.

This is the round-2 "one real model talks" milestone (VERDICT next-round #1):
checkpoint loading parity with HF, greedy decode parity with HF generate, and
concurrent streaming with per-request sampling params.
"""
import numpy as np
import pytest
import jax.numpy as jnp

from localai_tpu.engine import (
    Engine, EngineConfig, GenRequest, Tokenizer, load_config, load_params,
)
from localai_tpu.functions.grammars import JSON_GRAMMAR
from localai_tpu.models.llama import forward_train
from localai_tpu.ops.sampling import SamplingParams

from fixtures import tiny_checkpoint


@pytest.fixture(scope="session")
def ckpt(tmp_path_factory):
    return tiny_checkpoint(tmp_path_factory)


@pytest.fixture(scope="session")
def loaded(ckpt):
    cfg = load_config(ckpt, dtype="float32")
    params = load_params(ckpt, cfg)
    tok = Tokenizer.from_dir(ckpt)
    return cfg, params, tok


def _hf_model(ckpt):
    import torch
    from transformers import LlamaForCausalLM

    m = LlamaForCausalLM.from_pretrained(ckpt, torch_dtype=torch.float32)
    m.eval()
    return m


def test_config_parsed(loaded):
    cfg, _, tok = loaded
    assert cfg.num_kv_heads == 2 and cfg.num_layers == 2
    assert cfg.vocab_size == tok.vocab_size


def test_logits_parity_with_hf(ckpt, loaded):
    """Our forward on loaded safetensors == HF forward on the same weights."""
    import torch

    cfg, params, tok = loaded
    ids = tok.encode("the quick brown fox jumps over the lazy dog")
    hf = _hf_model(ckpt)
    with torch.no_grad():
        ref = hf(torch.tensor([ids])).logits[0].numpy()
    ours = np.asarray(forward_train(params, cfg, jnp.asarray([ids])))[0]
    np.testing.assert_allclose(ours, ref, rtol=2e-4, atol=2e-4)


def test_greedy_generate_matches_hf(ckpt, loaded):
    import torch

    cfg, params, tok = loaded
    prompt = tok.encode("hello world")
    n_new = 12

    hf = _hf_model(ckpt)
    with torch.no_grad():
        out = hf.generate(
            torch.tensor([prompt]), max_new_tokens=n_new, do_sample=False,
            eos_token_id=None, pad_token_id=0,
            # explicit mask: generate() would otherwise infer one from
            # pad_token_id and mask out our BOS (id 0)
            attention_mask=torch.ones((1, len(prompt)), dtype=torch.long),
        )[0].tolist()
    ref_new = out[len(prompt):]

    eng = Engine(cfg, params, tok, EngineConfig(max_slots=2, max_context=128,
                                                prefill_buckets=(32, 128)))
    req = GenRequest(prompt_ids=prompt, params=SamplingParams(temperature=0.0),
                     max_tokens=n_new, ignore_eos=True)
    toks = [o.token_id for o in eng.generate(req)]
    assert toks == ref_new


def test_concurrent_streams_with_different_sampling(loaded):
    """2+ requests in flight with different sampling params stream to
    completion and produce the prompt-conditioned text deterministically
    for the greedy one."""
    cfg, params, tok = loaded
    eng = Engine(cfg, params, tok, EngineConfig(max_slots=3, max_context=128,
                                                prefill_buckets=(32,)))
    reqs = [
        GenRequest(tok.encode("pack my box"), SamplingParams(temperature=0.0),
                   max_tokens=8, ignore_eos=True),
        GenRequest(tok.encode("sphinx of black"),
                   SamplingParams(temperature=0.9, top_k=20, seed=7),
                   max_tokens=8, ignore_eos=True),
        GenRequest(tok.encode("hello"),
                   SamplingParams(temperature=0.7, top_p=0.9, seed=3),
                   max_tokens=8, ignore_eos=True),
    ]
    outs = [eng.submit(r) for r in reqs]
    # drive the loop manually until all finish
    for _ in range(200):
        if not eng.step():
            break
    results = {}
    for rid, q in outs:
        text, n = "", 0
        while not q.empty():
            o = q.get()
            text += o.text
            n = o.generated_tokens
            if o.finished:
                results[rid] = (text, n, o.finish_reason)
    assert len(results) == 3
    for text, n, reason in results.values():
        assert n == 8 and reason == "length"

    # greedy request must reproduce the single-request greedy output
    solo = Engine(cfg, params, tok, EngineConfig(max_slots=1, max_context=128,
                                                 prefill_buckets=(32,)))
    ref = solo.generate_text(reqs[0])
    assert results[outs[0][0]][0] == ref


def test_burst_admission_matches_sequential(loaded):
    """A burst of simultaneous submissions rides the batched-admission path
    (_flush_admits: one prefill device call per (bucket, heavy) group, padded
    by repetition) and must emit token streams identical to admitting each
    request alone — per-request RNG is keyed on the request, not the path."""
    cfg, params, tok = loaded
    prompts = ["pack my box", "sphinx of black", "hello", "the quick brown",
               "jump over"]
    # mixed groups: 3 light seeded + 1 greedy light + 1 heavy (penalty)
    reqs = [
        GenRequest(tok.encode(p), SamplingParams(temperature=0.8, top_k=20,
                                                 seed=11 + i),
                   max_tokens=6, ignore_eos=True)
        for i, p in enumerate(prompts[:3])
    ] + [
        GenRequest(tok.encode(prompts[3]), SamplingParams(temperature=0.0),
                   max_tokens=6, ignore_eos=True),
        GenRequest(tok.encode(prompts[4]),
                   SamplingParams(temperature=0.0, repeat_penalty=3.0),
                   max_tokens=6, ignore_eos=True),
    ]

    def run_burst():
        eng = Engine(cfg, params, tok,
                     EngineConfig(max_slots=8, max_context=128,
                                  prefill_buckets=(32,)))
        outs = [eng.submit(r) for r in reqs]
        for _ in range(300):
            if not eng.step():
                break
        toks = {}
        for rid, q in outs:
            seq = []
            while not q.empty():
                seq.append(q.get().token_id)
            toks[rid] = seq
        return [toks[rid] for rid, _ in outs]

    def run_sequential():
        res = []
        for r in reqs:
            eng = Engine(cfg, params, tok,
                         EngineConfig(max_slots=1, max_context=128,
                                      prefill_buckets=(32,)))
            res.append([o.token_id for o in eng.generate(r)])
        return res

    burst, seq = run_burst(), run_sequential()
    assert burst == seq


def test_wide_topk_rides_escalated_fast_path(loaded):
    """A top_k above the base sort-free width but under 8x of it samples on
    the escalated window — identical tokens to the full-sort path, and the
    batch never falls back to full [B, V] sorting."""
    cfg, params, tok = loaded
    prompt = tok.encode("pack my box with five")

    def run(width):
        eng = Engine(cfg, params, tok, EngineConfig(
            max_slots=2, max_context=128, prefill_buckets=(32,),
            sampling_topk_width=width))
        # top_k=50 > 8 (base) but <= 64 (8x tier) when width=8
        req = GenRequest(list(prompt),
                         SamplingParams(temperature=0.9, top_k=50, seed=21),
                         max_tokens=10, ignore_eos=True)
        seen = {"w": []}
        orig = eng._dev_decode

        def spy(active, mask_host=None, fast_width=None):
            seen["w"].append(fast_width)
            return orig(active, mask_host, fast_width)

        eng._dev_decode = spy
        toks = [o.token_id for o in eng.generate(req)]
        return toks, seen["w"]

    full_toks, full_w = run(0)        # width 0 disables the fast path
    fast_toks, fast_w = run(8)
    assert full_toks == fast_toks
    assert all(w is None for w in full_w)
    assert all(w == 64 for w in fast_w)   # escalated 8x tier, never full


def test_stop_sequence_truncates(loaded):
    cfg, params, tok = loaded
    eng = Engine(cfg, params, tok, EngineConfig(max_slots=1, max_context=128,
                                                prefill_buckets=(32,)))
    # run greedy once to find a substring the model actually emits
    base = eng.generate_text(GenRequest(
        tok.encode("the quick"), SamplingParams(temperature=0.0),
        max_tokens=10, ignore_eos=True))
    assert len(base) > 3
    stop = base[2:5]
    eng2 = Engine(cfg, params, tok, EngineConfig(max_slots=1, max_context=128,
                                                 prefill_buckets=(32,)))
    outs = list(eng2.generate(GenRequest(
        tok.encode("the quick"), SamplingParams(temperature=0.0),
        max_tokens=10, ignore_eos=True, stop=(stop,))))
    text = "".join(o.text for o in outs)
    assert stop not in text
    assert outs[-1].finish_reason == "stop"
    assert text == base[:base.find(stop)]


def test_penalties_affect_output(loaded):
    """repeat penalty must change sampling behavior (token_counts is live)."""
    cfg, params, tok = loaded
    ec = EngineConfig(max_slots=1, max_context=128, prefill_buckets=(32,))
    prompt = tok.encode("hello world hello world hello")

    def run(rp):
        eng = Engine(cfg, params, tok, ec)
        return [o.token_id for o in eng.generate(GenRequest(
            prompt, SamplingParams(temperature=0.0, repeat_penalty=rp),
            max_tokens=10, ignore_eos=True))]

    assert run(1.0) != run(5.0)


def test_chat_template(loaded):
    _, _, tok = loaded
    text = tok.apply_chat_template(
        [{"role": "user", "content": "hi"}], add_generation_prompt=True
    )
    assert "<|user|>" in text and text.endswith("<|assistant|>\n")
    ids = tok.encode_chat([{"role": "user", "content": "hi"}])
    assert ids[0] == tok.bos_id


def test_incremental_detok_utf8(loaded):
    """Multi-byte characters split across tokens must never emit U+FFFD."""
    _, _, tok = loaded
    s = "café 東京 über"
    ids = tok.encode(s, add_bos=False)
    dec = tok.stream_decoder()
    text = "".join(dec.push(i) for i in ids)
    assert "�" not in text
    assert text == tok.decode(ids)


def test_bad_grammar_fails_request_not_engine(loaded):
    """Client-reachable admission failures must reject that request only and
    leave the engine serving others (advisor finding: an admission exception
    bricked the whole engine). Two layers: malformed GBNF raises ValueError at
    submit() (→ gRPC INVALID_ARGUMENT); anything slipping to admission time is
    converted to a terminal finish_reason=error StepOutput."""
    cfg, params, tok = loaded
    eng = Engine(cfg, params, tok, EngineConfig(max_slots=2, max_context=128,
                                                prefill_buckets=(32,)))
    bad = GenRequest(tok.encode("hello"), SamplingParams(temperature=0.0),
                     max_tokens=4, ignore_eos=True,
                     grammar="root ::= (")
    with pytest.raises(ValueError, match="grammar"):
        eng.submit(bad)

    # admission-time failure (defensive layer): force the matcher compile to
    # blow up only inside _admit_one
    ok = GenRequest(tok.encode("hi"), SamplingParams(temperature=0.0),
                    max_tokens=4, ignore_eos=True, grammar=JSON_GRAMMAR)
    good = GenRequest(tok.encode("hello"), SamplingParams(temperature=0.0),
                      max_tokens=4, ignore_eos=True)
    _, bad_q = eng.submit(ok)
    orig = eng._matcher_for
    eng._matcher_for = lambda g: (_ for _ in ()).throw(ValueError("boom"))
    _, good_q = eng.submit(good)
    for _ in range(50):
        if not eng.step():
            break
    eng._matcher_for = orig
    o = bad_q.get_nowait()
    assert o.finished and o.finish_reason == "error"
    outs = []
    while not good_q.empty():
        outs.append(good_q.get_nowait())
    assert outs and outs[-1].finished and outs[-1].finish_reason == "length"
    assert not eng._dead


def _drain(q):
    text, reason = "", None
    while True:
        o = q.get(timeout=60)
        text += o.text
        if o.finished:
            return text, o.finish_reason


def test_chunked_prefill_matches_single_shot(loaded):
    """A prompt longer than every prefill bucket is admitted via chunked
    extend() ticks; its greedy continuation must be identical to single-shot
    prefill of the same prompt in a large-bucket engine."""
    cfg, params, tok = loaded
    prompt = (tok.encode("the quick brown fox jumps over the lazy dog") * 8)[:70]
    req = lambda: GenRequest(list(prompt), SamplingParams(temperature=0.0),
                             max_tokens=8, ignore_eos=True)
    big = Engine(cfg, params, tok, EngineConfig(
        max_slots=2, max_context=256, prefill_buckets=(128,),
        prefill_chunk=128))
    ref = big.generate_text(req())
    small = Engine(cfg, params, tok, EngineConfig(
        max_slots=2, max_context=256, prefill_buckets=(32,),
        prefill_chunk=32))
    assert len(prompt) > 32  # really exercises the chunked path
    got = small.generate_text(req())
    assert got == ref and len(ref) > 0


def test_chunked_prefill_interleaved_with_decode(loaded):
    """While one stream decodes, a long prompt prefills chunk-by-chunk in the
    gaps; both outputs must equal their solo greedy runs (no KV corruption
    from the concurrent decode writes)."""
    cfg, params, tok = loaded
    long_prompt = (tok.encode("pack my box with five dozen jugs") * 10)[:80]
    short = GenRequest(tok.encode("hello world"),
                       SamplingParams(temperature=0.0),
                       max_tokens=24, ignore_eos=True)
    longr = GenRequest(list(long_prompt), SamplingParams(temperature=0.0),
                       max_tokens=8, ignore_eos=True)
    ec = EngineConfig(max_slots=2, max_context=256, prefill_buckets=(32,),
                      prefill_chunk=32)
    solo = Engine(cfg, params, tok, ec)
    ref_short = solo.generate_text(GenRequest(short.prompt_ids, short.params,
                                              max_tokens=24, ignore_eos=True))
    ref_long = solo.generate_text(GenRequest(longr.prompt_ids, longr.params,
                                             max_tokens=8, ignore_eos=True))
    eng = Engine(cfg, params, tok, ec)
    _, q_short = eng.submit(GenRequest(short.prompt_ids, short.params,
                                       max_tokens=24, ignore_eos=True))
    # let the short stream get going, then admit the long prompt mid-decode
    for _ in range(3):
        eng.step()
    _, q_long = eng.submit(GenRequest(longr.prompt_ids, longr.params,
                                      max_tokens=8, ignore_eos=True))
    for _ in range(200):
        if not eng.step():
            break
    t_short, r_short = _drain(q_short)
    t_long, r_long = _drain(q_long)
    assert (t_short, r_short) == (ref_short, "length")
    assert (t_long, r_long) == (ref_long, "length")


def test_pipeline_matches_sync_mode(loaded):
    """Pipelined dispatch (one step in flight) must not change outputs vs the
    synchronous loop for mixed seeded-sampling concurrent requests."""
    cfg, params, tok = loaded

    def run(pipeline: bool):
        eng = Engine(cfg, params, tok, EngineConfig(
            max_slots=3, max_context=128, prefill_buckets=(32,),
            pipeline=pipeline))
        reqs = [
            GenRequest(tok.encode("pack my box"),
                       SamplingParams(temperature=0.0), max_tokens=8,
                       ignore_eos=True),
            GenRequest(tok.encode("sphinx of black"),
                       SamplingParams(temperature=0.9, top_k=20, seed=7),
                       max_tokens=8, ignore_eos=True),
            GenRequest(tok.encode("hello"),
                       SamplingParams(temperature=0.7, top_p=0.9, seed=3),
                       max_tokens=8, ignore_eos=True),
        ]
        outs = [eng.submit(r) for r in reqs]
        for _ in range(200):
            if not eng.step():
                break
        return [_drain(q) for _, q in outs]

    assert run(True) == run(False)


def test_context_shift_rotation_unit():
    """cache_shift mechanics: a K row written at position p must, after the
    shift, equal the same raw vector roped at position p-discard; V rows move
    verbatim; sink rows stay; lengths drops by discard."""
    import jax

    from localai_tpu.models.llama import LlamaConfig, cache_shift
    from localai_tpu.ops.rope import apply_rope, rope_table

    cfg = LlamaConfig(vocab_size=64, hidden_size=16, intermediate_size=32,
                      num_layers=2, num_heads=2, num_kv_heads=2, head_dim=8,
                      max_position=64, dtype="float32")
    L, B, KVH, T, D = 2, 2, 2, 32, 8
    keep, discard, length = 3, 10, 30
    cos, sin = rope_table(cfg.rope, T)
    raw = jax.random.normal(jax.random.PRNGKey(0), (L, B, KVH, T, D))
    positions = jnp.arange(T)[None, :].repeat(L * B * KVH, 0).reshape(
        L, B, KVH, T)
    # roped[l,b,h,p] = R(p)·raw  (apply_rope wants [..., seq, heads, dim])
    roped = apply_rope(raw.transpose(0, 1, 3, 2, 4).reshape(L * B, T, KVH, D),
                       cos, sin, jnp.arange(T)[None, :].repeat(L * B, 0))
    kc = roped.reshape(L, B, T, KVH, D).transpose(0, 1, 3, 2, 4)
    vc = jax.random.normal(jax.random.PRNGKey(1), (L, B, KVH, T, D))
    lengths = jnp.array([length, 5], jnp.int32)

    kc2, vc2, lengths2 = cache_shift(cfg, kc, vc, lengths, 0,
                                     keep=keep, discard=discard)
    assert int(lengths2[0]) == length - discard
    assert int(lengths2[1]) == 5           # other slot untouched
    np.testing.assert_allclose(np.asarray(kc2[:, 1]), np.asarray(kc[:, 1]))
    # sink rows unchanged
    np.testing.assert_allclose(np.asarray(kc2[:, 0, :, :keep]),
                               np.asarray(kc[:, 0, :, :keep]), rtol=1e-6)
    # moved V rows verbatim
    np.testing.assert_allclose(
        np.asarray(vc2[:, 0, :, keep:length - discard]),
        np.asarray(vc[:, 0, :, keep + discard:length]), rtol=1e-6)
    # moved K rows = raw re-roped at the new position
    expect = apply_rope(
        raw.transpose(0, 1, 3, 2, 4).reshape(L * B, T, KVH, D),
        cos, sin,
        (jnp.arange(T) - discard)[None, :].repeat(L * B, 0) % T,
    ).reshape(L, B, T, KVH, D).transpose(0, 1, 3, 2, 4)
    np.testing.assert_allclose(
        np.asarray(kc2[:, 0, :, keep:length - discard]),
        np.asarray(expect[:, 0, :, keep + discard:length]),
        rtol=1e-4, atol=1e-5)


def test_context_shift_generation_crosses_limit(loaded):
    """A context_shift request keeps generating past the context cap (bounded
    memory) and ends with finish_reason=length from max_tokens — while a
    non-shift request dies at the cap."""
    cfg, params, tok = loaded
    ctx = 48
    prompt = tok.encode("the quick brown fox jumps over")
    n = len(prompt)

    def run(shift):
        eng = Engine(cfg, params, tok, EngineConfig(
            max_slots=2, max_context=ctx, prefill_buckets=(32,)))
        req = GenRequest(list(prompt), SamplingParams(temperature=0.0),
                         max_tokens=3 * ctx, ignore_eos=True,
                         context_shift=shift)
        _, out = eng.submit(req)
        outs = []
        for _ in range(4000):
            if not eng.step():
                break
        while not out.empty():
            outs.append(out.get())
        return outs

    plain = run(False)
    assert plain[-1].finish_reason == "length"
    assert plain[-1].generated_tokens <= ctx - n  # capped by the context

    shifted = run(True)
    assert shifted[-1].finish_reason == "length"
    assert shifted[-1].generated_tokens == 3 * ctx  # sailed past the cap
    assert all(o.token_id >= 0 for o in shifted)


def test_engine_self_restart_after_fatal_step(loaded):
    """A fatal device error in step() fails the in-flight streams, but the
    engine rebuilds its device state (weights are never donated) and keeps
    serving — the in-process analog of the manager reaping + respawning a
    dead backend, without reloading weights."""
    cfg, params, tok = loaded
    eng = Engine(cfg, params, tok, EngineConfig(
        max_slots=2, max_context=64, prefill_buckets=(16,),
        prefill_chunk=16, max_restarts=1))
    fired = {"n": 0}
    orig_admit = eng._admit_many_fn

    def boom(*a, **kw):
        if fired["n"] == 0:
            fired["n"] += 1
            raise RuntimeError("injected device fault")
        return orig_admit(*a, **kw)

    eng._admit_many_fn = boom
    eng.start()
    try:
        _, q = eng.submit(GenRequest([1, 2, 3], SamplingParams(
            temperature=0.0), max_tokens=4, ignore_eos=True))
        o = q.get(timeout=60)
        while not o.finished:
            o = q.get(timeout=60)
        assert o.finish_reason == "error"

        # engine recovered: the next request serves normally
        _, q2 = eng.submit(GenRequest([1, 2, 3], SamplingParams(
            temperature=0.0), max_tokens=4, ignore_eos=True))
        toks = []
        while True:
            o = q2.get(timeout=60)
            toks.append(o.token_id)
            if o.finished:
                break
        assert o.finish_reason == "length" and len(toks) == 4

        # a second fault exceeds max_restarts=1: engine goes dead for good
        fired["n"] = 0
        _, q3 = eng.submit(GenRequest([1, 2, 3], SamplingParams(
            temperature=0.0), max_tokens=4, ignore_eos=True))
        o = q3.get(timeout=60)
        while not o.finished:
            o = q3.get(timeout=60)
        assert o.finish_reason == "error"
        import time as _t

        for _ in range(100):          # loop thread flips _dead shortly after
            if eng._dead:
                break
            _t.sleep(0.05)
        with pytest.raises(RuntimeError, match="terminated"):
            eng.submit(GenRequest([1, 2, 3], SamplingParams(), max_tokens=2))
    finally:
        eng.stop()
