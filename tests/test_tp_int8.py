"""Tensor-parallel int8 decode (ISSUE 3): the flagship quantized recipe on a
mesh.

Covers the PR's acceptance criteria on the virtual CPU mesh:
- `load_params(mesh=..., dtype="int8")` no longer raises; the sharded
  quantized load quantizes per host-read shard and never materializes a full
  stacked bf16 weight (device-put spy + a tripwire on the device-side
  `quantize_params` path),
- `param_specs` completeness: every leaf of `init_params` — bf16 AND the
  quantized {q, s} trees — has a full-rank spec, and a wrong-rank spec
  raises at shard time instead of silently replicating,
- 4-device fused-decode-block parity against the single-device engine at
  8 slots: dense and paged, bf16 and int8-W (incl. int8 KV and the
  shard_map'd Pallas scatter-append tier),
- a compiled-HLO inspection proof that the TP decode step contains no
  full-weight all-gather (weights stay resident-sharded through the layer
  scan; the only gather is the small vocab-parallel logits one).

Everything here runs on 4 devices so the CI job with
XLA_FLAGS=--xla_force_host_platform_device_count=4 can run the `tp` marker
standalone.
"""
from __future__ import annotations

import dataclasses
import json
import os
import re
import sys
import threading
import types

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from localai_tpu.models.llama import (
    LlamaConfig, decode_step, init_kv_cache, init_params, kv_cache_spec,
    param_specs, prefill, replicated_specs,
)
from localai_tpu.ops.quant import quantize_params
from localai_tpu.ops.rope import rope_table
from localai_tpu.parallel.mesh import (
    MeshConfig, activate_mesh, build_mesh, mesh_shape, shard_params,
)

pytestmark = pytest.mark.tp

# every TP'd dim divisible by the 4-wide model axis (incl. the KV-head axis
# the cache/pool shard on)
CFG = LlamaConfig(
    vocab_size=256, hidden_size=64, intermediate_size=128, num_layers=2,
    num_heads=4, num_kv_heads=4, head_dim=16, max_position=512,
    dtype="float32",
)


@pytest.fixture(scope="module")
def mesh4():
    if len(jax.devices()) < 4:
        pytest.skip("needs >= 4 devices")
    return build_mesh(MeshConfig(data=1, model=4), jax.devices()[:4])


# ------------------------------------------------------- spec completeness

def _leaves_with_paths(tree):
    flat, _ = jax.tree_util.tree_flatten_with_path(tree)
    return {jax.tree_util.keystr(path): leaf for path, leaf in flat}


def _cfg_variants():
    return [
        CFG,
        dataclasses.replace(CFG, num_kv_heads=2),
        dataclasses.replace(CFG, qkv_bias=True),
        dataclasses.replace(CFG, tie_embeddings=True),
        dataclasses.replace(CFG, num_experts=4, experts_per_tok=2),
    ]


@pytest.mark.parametrize("qbits", [None, 8])
def test_param_specs_cover_every_leaf(qbits):
    """Acceptance: every leaf of init_params — bf16 and quantized trees —
    has a PartitionSpec of exactly the leaf's rank (full-rank specs are what
    makes the wrong-rank check below meaningful)."""
    for cfg in _cfg_variants():
        params = init_params(cfg, jax.random.PRNGKey(0))
        if qbits:
            params = quantize_params(params, bits=qbits)
        specs = param_specs(cfg, qbits=qbits)
        pleaves = _leaves_with_paths(params)
        sleaves = _leaves_with_paths(specs)
        assert set(pleaves) == set(sleaves), (
            f"spec tree != param tree: only-params="
            f"{set(pleaves) - set(sleaves)} only-specs="
            f"{set(sleaves) - set(pleaves)}")
        for path, spec in sleaves.items():
            assert isinstance(spec, P), f"{path}: not a PartitionSpec"
            assert len(spec) == pleaves[path].ndim, (
                f"{path}: spec rank {len(spec)} != param rank "
                f"{pleaves[path].ndim}")


def test_replicated_specs_cover_quantized_tree():
    qparams = quantize_params(init_params(CFG, jax.random.PRNGKey(0)))
    specs = replicated_specs(CFG, qbits=8)
    # structure must match exactly (tree_map raises otherwise) and every
    # leaf replicates
    jax.tree_util.tree_map(
        lambda _, s: (_ for _ in ()).throw(AssertionError(s))
        if tuple(s) not in ((), None) and any(a is not None for a in s)
        else None,
        qparams, specs)


def test_wrong_rank_spec_raises_at_shard_time(mesh4):
    """A wrong-rank spec must raise naming the leaf — not silently replicate
    (the pre-PR failure mode for the quantized {q, s} leaves)."""
    params = init_params(CFG, jax.random.PRNGKey(0))
    specs = param_specs(CFG)
    specs["layers"]["wq"] = P(None, "model")      # rank 2 vs param rank 3
    with pytest.raises(ValueError, match="wq"):
        shard_params(params, specs, mesh4)


def test_missing_spec_leaf_raises(mesh4):
    params = init_params(CFG, jax.random.PRNGKey(0))
    specs = param_specs(CFG)
    del specs["layers"]["wo"]
    with pytest.raises((ValueError, KeyError)):
        shard_params(params, specs, mesh4)


# ------------------------------------------------------ sharded int8 load

def _spy_device_put(monkeypatch, record):
    real = jax.device_put

    def spy(x, *a, **kw):
        for leaf in jax.tree_util.tree_leaves(x):
            if hasattr(leaf, "dtype"):
                record.append((np.dtype(leaf.dtype),
                               getattr(leaf, "ndim", 0),
                               int(getattr(leaf, "size", 0))))
        return real(x, *a, **kw)

    monkeypatch.setattr(jax, "device_put", spy)


def test_sharded_int8_load_never_materializes_full_bf16(
        tmp_path_factory, mesh4, monkeypatch):
    """Acceptance: load_params(mesh=..., qbits=8) no longer raises, the int8
    payload + per-channel scales land under the quantized param_specs, and no
    full stacked floating-point projection is ever device_put (quantization
    happened per host-read shard). The device-side quantize_params path must
    not run at all under a mesh."""
    from fixtures import tiny_checkpoint
    import localai_tpu.ops.quant as quant_mod
    from localai_tpu.engine.loader import load_config, load_params

    ckpt = tiny_checkpoint(tmp_path_factory)
    cfg = load_config(ckpt, dtype="int8")
    ref = load_params(ckpt, cfg, dtype="int8")       # single-device baseline

    def boom(*a, **kw):
        raise AssertionError(
            "device-side quantize_params ran on the sharded load path")

    monkeypatch.setattr(quant_mod, "quantize_params", boom)
    record = []
    _spy_device_put(monkeypatch, record)
    params = load_params(ckpt, cfg, dtype="int8", mesh=mesh4)

    # smallest stacked projection (wk/wv: [L, h, kvh*hd]) — no float array
    # that large (or larger) with a stacked-layer rank may cross device_put
    stack_elems = cfg.num_layers * cfg.hidden_size \
        * cfg.num_kv_heads * cfg.head_dim
    offenders = [r for r in record
                 if np.issubdtype(r[0], np.floating) and r[1] >= 3
                 and r[2] >= stack_elems]
    assert not offenders, f"full float weight stacks device_put: {offenders}"

    wq = params["layers"]["wq"]
    assert wq["q"].dtype == jnp.int8
    assert wq["q"].sharding.spec == P(None, None, "model")
    assert not wq["q"].sharding.is_fully_replicated
    assert wq["s"].sharding.spec == P(None, None, "model")
    assert params["layers"]["wo"]["q"].sharding.spec == P(None, "model", None)
    assert params["lm_head"]["q"].sharding.spec == P(None, "model")

    # numerics: host-side per-shard quantization == the device-side
    # quantize_params baseline, bit for bit
    for k in ("wq", "wk", "wv", "wo", "w_gate", "w_up", "w_down"):
        np.testing.assert_array_equal(
            np.asarray(params["layers"][k]["q"]),
            np.asarray(ref["layers"][k]["q"]), err_msg=k)
        np.testing.assert_allclose(
            np.asarray(params["layers"][k]["s"]),
            np.asarray(ref["layers"][k]["s"]), rtol=0, atol=0, err_msg=k)
    np.testing.assert_array_equal(np.asarray(params["embed"]),
                                  np.asarray(ref["embed"]))


def test_synthetic_int8_load_shards(tmp_path, mesh4, monkeypatch):
    """The benchmark path: a synthetic checkpoint loaded with mesh + int8
    generates the {q, s} leaves directly and places them sharded."""
    from localai_tpu.engine.loader import load_config, load_params

    monkeypatch.setenv("LOCALAI_ALLOW_SYNTHETIC", "1")
    body = dict(vocab_size=256, hidden_size=64, intermediate_size=128,
                num_hidden_layers=2, num_attention_heads=4,
                num_key_value_heads=4, head_dim=16,
                max_position_embeddings=256, tie_word_embeddings=False,
                architectures=["LlamaForCausalLM"], rms_norm_eps=1e-5,
                localai_synthetic=True)
    with open(tmp_path / "config.json", "w") as fh:
        json.dump(body, fh)
    cfg = load_config(str(tmp_path), dtype="int8")
    params = load_params(str(tmp_path), cfg, dtype="int8", mesh=mesh4)
    assert params["layers"]["wq"]["q"].dtype == jnp.int8
    assert params["layers"]["wq"]["q"].sharding.spec == P(None, None, "model")
    assert not params["layers"]["wq"]["q"].sharding.is_fully_replicated
    assert params["lm_head"]["q"].sharding.spec == P(None, "model")


# ----------------------------------------------- fused decode block parity

def _collect(eng, reqs):
    eng.start()
    outs = {}

    def run(i, req):
        _, q = eng.submit(req)
        ids = []
        while True:
            o = q.get(timeout=300)
            if o.token_id >= 0:
                ids.append(o.token_id)
            if o.finished:
                outs[i] = ids
                return

    ths = [threading.Thread(target=run, args=(i, r))
           for i, r in enumerate(reqs)]
    [t.start() for t in ths]
    [t.join(timeout=600) for t in ths]
    eng.stop()
    return outs


def _reqs(cfg, n, max_tokens=14):
    from localai_tpu.engine import GenRequest
    from localai_tpu.ops.sampling import SamplingParams

    rng = np.random.default_rng(7)
    return [GenRequest(
        rng.integers(5, cfg.vocab_size, 6).tolist(),
        SamplingParams(temperature=0.0),
        max_tokens=max_tokens, ignore_eos=True) for _ in range(n)]


def _run_engine(cfg, params, mesh, *, kv_pages=0, cache_type=""):
    from localai_tpu.engine import Engine, EngineConfig

    ec = EngineConfig(max_slots=8, max_context=256, prefill_buckets=(32,),
                      decode_block=8, prompt_cache=False, mesh=mesh,
                      kv_pages=kv_pages, cache_type=cache_type)
    outs = _collect(Engine(cfg, params, None, ec), _reqs(cfg, 8))
    assert sorted(outs) == list(range(8))
    return outs


def _parity(cfg, params, sharded, mesh4, **kw):
    ref = _run_engine(cfg, params, None, **kw)
    got = _run_engine(cfg, sharded, mesh4, **kw)
    for i in ref:
        assert got[i] == ref[i], f"slot {i} diverged under TP: " \
                                 f"{ref[i]} vs {got[i]}"


# Stream parity uses f32 activations: row-parallel wo/w_down split their
# reduction across shards, and with bf16 activations the psum's reduction-
# order rounding (~1e-2 relative) exceeds the smallest greedy top-2 logit
# margins this model produces (~1e-3, measured over 16 steps for several
# seeds) — bit-exact bf16 token streams vs a single device are a coin flip
# by construction, not a property TP can promise. f32 noise is ~1e-7, three
# orders under the margins, so these streams are deterministically stable;
# the bf16 path is covered by the logits-closeness + full-stream test below.

@pytest.fixture(scope="module")
def f32_params():
    return CFG, init_params(CFG, jax.random.PRNGKey(3))


@pytest.fixture(scope="module")
def int8_params(f32_params):
    cfg, params = f32_params
    return cfg, quantize_params(params, bits=8)


def test_tp_parity_dense(f32_params, mesh4):
    cfg, params = f32_params
    sharded = shard_params(params, param_specs(cfg), mesh4)
    _parity(cfg, params, sharded, mesh4)


def test_tp_parity_dense_int8_w(int8_params, mesh4):
    cfg, qparams = int8_params
    sharded = shard_params(qparams, param_specs(cfg, qbits=8), mesh4)
    _parity(cfg, qparams, sharded, mesh4)


def test_tp_parity_paged(f32_params, mesh4):
    cfg, params = f32_params
    sharded = shard_params(params, param_specs(cfg), mesh4)
    _parity(cfg, params, sharded, mesh4, kv_pages=16)


def test_tp_parity_paged_int8_w_int8_kv(int8_params, mesh4):
    """The full flagship recipe under TP: int8 weights + int8 paged KV."""
    cfg, qparams = int8_params
    sharded = shard_params(qparams, param_specs(cfg, qbits=8), mesh4)
    _parity(cfg, qparams, sharded, mesh4, kv_pages=16, cache_type="int8")


def test_tp_paged_pallas_scatter_via_shard_map(int8_params, mesh4,
                                               monkeypatch):
    """The Pallas scatter-append tier survives TP: with LOCALAI_FORCE_PALLAS
    the paged decode write runs per-shard via shard_map over the pool's
    KV-head axis and still reproduces the single-device stream."""
    monkeypatch.setenv("LOCALAI_FORCE_PALLAS", "1")
    cfg, qparams = int8_params
    sharded = shard_params(qparams, param_specs(cfg, qbits=8), mesh4)
    ref = _run_engine(cfg, qparams, None, kv_pages=16)
    got = _run_engine(cfg, sharded, mesh4, kv_pages=16)
    assert got == ref


def test_tp_bf16_decode_close_and_streams_full(mesh4):
    """The bf16 leg: one fused prefill+decode under TP must track the
    single-device logits within bf16 rounding (the psum reduction-order
    bound — see the parity note above), and the 8-slot TP engine must
    produce complete streams on the bf16+int8-W flagship dtype."""
    from functools import partial

    cfg = dataclasses.replace(CFG, dtype="bfloat16")
    params = init_params(cfg, jax.random.PRNGKey(3))
    qparams = quantize_params(params, bits=8)
    B, T = 8, 64
    cos, sin = rope_table(cfg.rope, T)
    rng = np.random.default_rng(7)
    toks = jnp.asarray(rng.integers(5, cfg.vocab_size, (B, 6)), jnp.int32)
    lengths = jnp.full((B,), 6, jnp.int32)

    def run(ps, mesh):
        kc, vc = init_kv_cache(cfg, B, T)
        with activate_mesh(mesh):
            logits, kc, vc = jax.jit(partial(prefill, cfg=cfg))(
                ps, tokens=toks, lengths=lengths, cos=cos, sin=sin,
                k_cache=kc, v_cache=vc, slot_map=jnp.arange(B))
            nxt = jnp.argmax(logits, -1).astype(jnp.int32)
            dlogits, _, _ = jax.jit(partial(decode_step, cfg=cfg))(
                ps, tokens=nxt, lengths=lengths, cos=cos, sin=sin,
                k_cache=kc, v_cache=vc)
        return np.asarray(logits), np.asarray(dlogits)

    sharded = shard_params(qparams, param_specs(cfg, qbits=8), mesh4)
    for ref, got in zip(run(qparams, None), run(sharded, mesh4)):
        np.testing.assert_allclose(got, ref, rtol=0.05, atol=0.05)
    # and the serving loop end to end: full-length streams at 8 slots
    outs = _run_engine(cfg, sharded, mesh4, kv_pages=16, cache_type="int8")
    assert all(len(v) == 14 for v in outs.values())


# -------------------------------------------- compiled-step HLO inspection

_SHAPE_RE = re.compile(r"\w+\[([\d,]*)\]")


def _allgather_sizes(hlo_text: str) -> list[int]:
    """Element counts of every all-gather result in an HLO dump."""
    sizes = []
    for line in hlo_text.splitlines():
        if "all-gather" not in line:
            continue
        head = line.split("all-gather", 1)[0]
        if "=" not in head:
            continue
        for dims in _SHAPE_RE.findall(head.split("=", 1)[1]):
            n = 1
            for d in filter(None, dims.split(",")):
                n *= int(d)
            sizes.append(n)
    return sizes


def _compiled_decode_step(mesh4, params, cfg):
    from functools import partial

    B, T = 8, 128
    cos, sin = rope_table(cfg.rope, T)
    kc, vc = init_kv_cache(cfg, B, T)
    kv_sh = NamedSharding(mesh4, kv_cache_spec())
    kc, vc = jax.device_put(kc, kv_sh), jax.device_put(vc, kv_sh)
    tokens = jnp.zeros((B,), jnp.int32)
    lengths = jnp.full((B,), 5, jnp.int32)
    with activate_mesh(mesh4):
        lowered = jax.jit(partial(decode_step, cfg=cfg)).lower(
            params, tokens=tokens, lengths=lengths, cos=cos, sin=sin,
            k_cache=kc, v_cache=vc)
        return lowered.compile().as_text()


def test_tp_decode_step_no_full_weight_allgather(mesh4):
    """Acceptance: the compiled TP int8 decode step contains NO all-gather
    at (or above) full-weight size — weights stay sharded through the layer
    scan. The vocab-parallel logits gather ([B, V], small) is the only big
    collective allowed besides the per-layer psum."""
    qparams = quantize_params(init_params(CFG, jax.random.PRNGKey(0)))
    sharded = shard_params(qparams, param_specs(CFG, qbits=8), mesh4)
    txt = _compiled_decode_step(mesh4, sharded, CFG)
    # smallest full projection: wk/wv layer slice [h, kvh*hd]
    weight_elems = CFG.hidden_size * CFG.num_kv_heads * CFG.head_dim
    big = [n for n in _allgather_sizes(txt) if n >= weight_elems]
    assert not big, f"weight-sized all-gather in the TP decode step: {big}"
    # ... and TP is actually active: the row-parallel psum is in there
    assert "all-reduce" in txt, "no all-reduce — decode step not partitioned"


def test_allgather_detector_not_vacuous(mesh4):
    """The HLO parser DOES see a full-weight all-gather when one exists
    (sharded weight forced back to replicated) — the assertion above has
    teeth."""
    # lint: allow(sharding-spec-source) — detector-teeth test: a hand-built
    # sharded weight is forced replicated to PROVE the all-gather shows up
    w = jax.device_put(jnp.zeros((64, 256), jnp.float32),
                       NamedSharding(mesh4, P(None, "model")))
    txt = jax.jit(lambda a: a * 2.0,
                  out_shardings=NamedSharding(mesh4, P(None, None))) \
        .lower(w).compile().as_text()
    assert any(n >= 64 * 256 for n in _allgather_sizes(txt)), \
        f"detector missed the forced all-gather:\n{txt}"


# -------------------------------------------------- plumbing + telemetry

def test_cli_run_parses_tensor_parallel():
    import argparse

    from localai_tpu.cli import _add_run

    parser = argparse.ArgumentParser()
    _add_run(parser.add_subparsers(dest="cmd"))
    args = parser.parse_args(["run", "--tensor-parallel", "4"])
    assert args.tensor_parallel == 4


def test_manager_plumbs_tensor_parallel_to_mesh_model():
    """`--tensor-parallel N` reaches the backend as mesh_model=N unless the
    model YAML pins its own mesh."""
    from localai_tpu.config import AppConfig, ModelConfig
    from localai_tpu.core.manager import ModelManager

    class FakeClient:
        def load_model(self, **kw):
            self.kw = kw
            return types.SimpleNamespace(success=True)

    mgr = ModelManager.__new__(ModelManager)
    mgr.app = AppConfig(tensor_parallel=4)
    h = types.SimpleNamespace(client=FakeClient(),
                              config=ModelConfig(name="m"))
    mgr._load_rpc(h)
    assert h.client.kw["mesh_model"] == 4
    # explicit per-model mesh wins
    h2 = types.SimpleNamespace(client=FakeClient(),
                               config=ModelConfig.from_dict(
                                   {"name": "m2", "mesh": {"model": 2}}))
    mgr._load_rpc(h2)
    assert h2.client.kw["mesh_model"] == 2


def test_bench_parser_has_tp_mode():
    import bench

    p = bench.build_parser()
    args = p.parse_args(["--mode", "tp", "--tensor-parallel", "2", "--cpu"])
    assert args.mode == "tp" and args.tensor_parallel == 2


def test_profiler_records_mesh_and_per_chip_mfu(mesh4):
    """Telemetry acceptance: profiler artifacts carry the mesh shape and the
    MFU denominator scales with the chip count, so a TP profile is never
    silently read as a single-chip one."""
    from localai_tpu.telemetry import StepProfiler

    shape = mesh_shape(mesh4)
    assert shape == {"data": 1, "model": 4}
    prof = StepProfiler(fence=False, n_params=1000, peak=1e9, mesh=shape)
    single = StepProfiler(fence=False, n_params=1000, peak=1e9)
    import time

    t0 = time.perf_counter() - 0.01
    prof.record("decode", t0, tokens=100)
    single.record("decode", t0, tokens=100)
    # same compiled cost on both: the per-chip normalization lives entirely
    # in the cost-backed MFU denominator (the analytic estimate is gone
    # since ISSUE 16)
    prof.set_costs({"decode": {"flops": 1e6, "bytes": 1e6}})
    single.set_costs({"decode": {"flops": 1e6, "bytes": 1e6}})
    rep, srep = prof.report(), single.report()
    assert rep["mesh"] == {"data": 1, "model": 4} and rep["chips"] == 4
    assert srep["mesh"] is None and srep["chips"] == 1
    # same tokens, same wall time: per-chip-normalized MFU is 4x smaller
    ratio = (srep["stages"]["decode"]["mfu"]
             / rep["stages"]["decode"]["mfu"])
    assert abs(ratio - 4.0) < 0.5


def test_engine_profiler_inherits_engine_mesh(mesh4, monkeypatch):
    from localai_tpu import telemetry

    telemetry.set_profile_enabled(True)
    try:
        prof = telemetry.engine_profiler(CFG, mesh=mesh4)
        assert prof is not None
        assert prof.mesh == {"data": 1, "model": 4}
        assert prof.chips == 4
    finally:
        telemetry.set_profile_enabled(None)
