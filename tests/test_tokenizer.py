"""Incremental detokenization across tokenizer decoder families.

The streaming path must reproduce tok.decode(ids) exactly for both ByteLevel
BPE (GPT/Llama-3 style) and Metaspace (SentencePiece/Llama-2 style, whose
decoder strips the leading word-boundary space on every decode call — the
classic dropped-space streaming bug).
"""
import pytest

from localai_tpu.engine.tokenizer import Tokenizer


def _metaspace_tokenizer():
    from tokenizers import Tokenizer as HFTok
    from tokenizers import decoders, models, pre_tokenizers, trainers

    tok = HFTok(models.BPE(unk_token="<unk>"))
    tok.pre_tokenizer = pre_tokenizers.Metaspace()
    tok.decoder = decoders.Metaspace()
    trainer = trainers.BpeTrainer(
        vocab_size=200, special_tokens=["<unk>", "<s>", "</s>"],
        show_progress=False,
    )
    corpus = ["hello world this is a test", "the quick brown fox",
              "pack my box with five dozen jugs"] * 4
    tok.train_from_iterator(corpus, trainer=trainer)
    return Tokenizer(tok, bos_id=1, eos_ids={2})


def test_encode_chat_renders_tools_and_llm_passes_them():
    """Tool schemas must reach the model's prompt: encode_chat threads
    `tools` into the chat template, and the llm backend's prompt builder
    forwards PredictOptions.tools_json to it (VERDICT Missing #1 — the
    grammar constrained the OUTPUT while the model never saw the tools)."""
    import json
    from types import SimpleNamespace

    tok = _metaspace_tokenizer()
    tok.chat_template = (
        "{% for message in messages %}{{ message['content'] }} "
        "{% endfor %}"
        "{% if tools %}tools: {% for t in tools %}"
        "{{ t['function']['name'] }} {% endfor %}{% endif %}")
    tools = [{"type": "function",
              "function": {"name": "box fox", "parameters": {}}}]
    messages = [{"role": "user", "content": "hello world"}]
    with_tools = tok.encode_chat(messages, tools=tools)
    without = tok.encode_chat(messages)
    assert with_tools != without
    assert "box fox" in tok.decode(with_tools)

    # the servicer's prompt builder: tools_json → encode_chat(tools=...)
    from localai_tpu.backend.llm import LLMServicer

    svc = LLMServicer()
    svc.tok = tok
    req = SimpleNamespace(prompt_ids=[], use_tokenizer_template=True,
                          messages_json=json.dumps(messages),
                          tools_json=json.dumps(tools), prompt="")
    assert svc._prompt_ids(req, context=None) == with_tools
    req.tools_json = ""
    assert svc._prompt_ids(req, context=None) == without


def test_metaspace_streaming_keeps_spaces():
    tok = _metaspace_tokenizer()
    s = "hello world this is the quick fox"
    ids = tok.encode(s, add_bos=False)
    ref = tok.decode(ids)
    assert " " in ref  # sanity: multi-word
    dec = tok.stream_decoder()
    streamed = "".join(dec.push(i) for i in ids) + dec.flush()
    assert streamed == ref


def test_flush_emits_heldback_bytes():
    """A generation that ends mid-UTF-8-sequence must still flush the tail."""
    import json
    import os
    import sys
    import tempfile

    sys.path.insert(0, os.path.dirname(__file__))
    from fixtures import build_tiny_checkpoint

    d = tempfile.mkdtemp()
    build_tiny_checkpoint(d)
    tok = Tokenizer.from_dir(d)
    ids = tok.encode("café 東京", add_bos=False)
    # push all but the final token of a multi-byte char: delta held back
    dec = tok.stream_decoder()
    out = "".join(dec.push(i) for i in ids[:-1])
    tail = dec.flush()
    assert out + tail == tok.decode(ids[:-1])
