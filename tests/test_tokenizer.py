"""Incremental detokenization across tokenizer decoder families.

The streaming path must reproduce tok.decode(ids) exactly for both ByteLevel
BPE (GPT/Llama-3 style) and Metaspace (SentencePiece/Llama-2 style, whose
decoder strips the leading word-boundary space on every decode call — the
classic dropped-space streaming bug).
"""
import pytest

from localai_tpu.engine.tokenizer import Tokenizer


def _metaspace_tokenizer():
    from tokenizers import Tokenizer as HFTok
    from tokenizers import decoders, models, pre_tokenizers, trainers

    tok = HFTok(models.BPE(unk_token="<unk>"))
    tok.pre_tokenizer = pre_tokenizers.Metaspace()
    tok.decoder = decoders.Metaspace()
    trainer = trainers.BpeTrainer(
        vocab_size=200, special_tokens=["<unk>", "<s>", "</s>"],
        show_progress=False,
    )
    corpus = ["hello world this is a test", "the quick brown fox",
              "pack my box with five dozen jugs"] * 4
    tok.train_from_iterator(corpus, trainer=trainer)
    return Tokenizer(tok, bos_id=1, eos_ids={2})


def test_metaspace_streaming_keeps_spaces():
    tok = _metaspace_tokenizer()
    s = "hello world this is the quick fox"
    ids = tok.encode(s, add_bos=False)
    ref = tok.decode(ids)
    assert " " in ref  # sanity: multi-word
    dec = tok.stream_decoder()
    streamed = "".join(dec.push(i) for i in ids) + dec.flush()
    assert streamed == ref


def test_flush_emits_heldback_bytes():
    """A generation that ends mid-UTF-8-sequence must still flush the tail."""
    import json
    import os
    import sys
    import tempfile

    sys.path.insert(0, os.path.dirname(__file__))
    from fixtures import build_tiny_checkpoint

    d = tempfile.mkdtemp()
    build_tiny_checkpoint(d)
    tok = Tokenizer.from_dir(d)
    ids = tok.encode("café 東京", add_bos=False)
    # push all but the final token of a multi-byte char: delta held back
    dec = tok.stream_decoder()
    out = "".join(dec.push(i) for i in ids[:-1])
    tail = dec.flush()
    assert out + tail == tok.decode(ids[:-1])
