"""gRPC backend contract tests: in-process server + real client roundtrip,
and a spawned-subprocess health/stream test (the reference's process-boundary
semantics — /root/reference/pkg/model/initializers.go:110-150).
"""
import json
import os
import subprocess
import sys
import time

import numpy as np
import pytest

from fixtures import tiny_checkpoint


@pytest.fixture(scope="module")
def ckpt(tmp_path_factory):
    return tiny_checkpoint(tmp_path_factory)


@pytest.fixture(scope="module")
def served(ckpt):
    from localai_tpu.backend.client import BackendClient
    from localai_tpu.backend.server import serve

    server, servicer, port = serve("127.0.0.1:0", "llm")
    client = BackendClient(f"127.0.0.1:{port}")
    assert client.wait_ready(attempts=20, sleep=0.1)
    r = client.load_model(model=ckpt, dtype="float32", parallel=2,
                          context_size=128, prefill_buckets=[32],
                          embeddings=True)
    assert r.success, r.message
    yield client, servicer
    client.close()
    servicer.shutdown()
    server.stop(grace=1)


def test_health_and_status(served):
    client, _ = served
    assert client.health()
    st = client.status()
    assert st.state == 2  # READY
    assert st.memory.total > 0


def test_predict_roundtrip(served):
    client, _ = served
    r = client.predict(prompt="hello world", tokens=8, temperature=0.0,
                       ignore_eos=True)
    assert r.tokens == 8
    assert len(r.token_ids) == 8
    assert r.finish_reason == "length"
    assert r.timing_prompt_processing > 0


def test_predict_stream(served):
    client, _ = served
    chunks = list(client.predict_stream(prompt="the quick", tokens=6,
                                        temperature=0.0, ignore_eos=True,
                                        logprobs=True))
    assert len(chunks) == 6
    assert chunks[-1].finish_reason == "length"
    assert all(len(c.token_ids) == 1 for c in chunks)
    # deterministic greedy: matches non-streamed predict
    r = client.predict(prompt="the quick", tokens=6, temperature=0.0,
                       ignore_eos=True)
    assert [c.token_ids[0] for c in chunks] == list(r.token_ids)


def test_messages_template_path(served):
    client, _ = served
    r = client.predict(
        messages_json=json.dumps([{"role": "user", "content": "hi"}]),
        use_tokenizer_template=True, tokens=4, temperature=0.0,
        ignore_eos=True)
    assert r.tokens == 4
    assert r.prompt_tokens > 3  # template adds role markers


def test_tokenize(served):
    client, _ = served
    t = client.tokenize("hello world")
    assert t.length == len(t.tokens) > 0


def test_embedding_cosine_sanity(served):
    client, _ = served
    va = np.array(client.embedding(prompt="the quick brown fox").embeddings)
    vb = np.array(client.embedding(prompt="the quick brown foxes").embeddings)
    vc = np.array(client.embedding(prompt="zzz qqq 123").embeddings)
    assert va.shape[0] > 0
    assert abs(np.linalg.norm(va) - 1.0) < 1e-5  # normalized
    sim_ab = float(va @ vb)
    sim_ac = float(va @ vc)
    assert sim_ab > sim_ac  # near-duplicate closer than junk


def test_embedding_batched_single_rpc(served):
    """The whole input list rides ONE Embedding RPC (prompts field) and
    matches the per-item path bitwise."""
    client, _ = served
    texts = ["the quick brown fox", "the quick brown foxes", "zzz qqq 123"]
    r = client.embedding(prompts=texts)
    assert len(r.vectors) == 3
    assert r.prompt_tokens > 0
    singles = [np.array(client.embedding(prompt=t).embeddings) for t in texts]
    for v, s in zip(r.vectors, singles):
        np.testing.assert_allclose(np.array(v.values), s, rtol=1e-5, atol=1e-6)


def test_rerank(served):
    """Cross-encoder rerank: scores are the LM's conditional doc likelihood
    given the query. Random weights carry no semantics, so assert the
    mechanics: full coverage, descending order, top_n, deterministic ties
    for identical documents."""
    client, _ = served
    docs = ["the quick brown foxes", "zzz qqq 123",
            "the quick brown fox", "the quick brown fox"]
    r = client.rerank(query="the quick brown fox", documents=docs)
    assert len(r.results) == 4
    scores = [d.relevance_score for d in r.results]
    assert scores == sorted(scores, reverse=True)
    by_index = {d.index: d.relevance_score for d in r.results}
    assert abs(by_index[2] - by_index[3]) < 1e-5  # identical docs tie
    r2 = client.rerank(query="the quick brown fox", documents=docs, top_n=2)
    assert len(r2.results) == 2
    assert [d.index for d in r2.results] == [d.index for d in r.results][:2]


def test_metrics(served):
    client, _ = served
    m = client.metrics()
    assert m["tokens_generated"] > 0
    assert m["requests_completed"] > 0


def test_unimplemented_capability(served):
    import grpc

    client, _ = served
    with pytest.raises(grpc.RpcError) as e:
        client.generate_image(positive_prompt="a cat", dst="/tmp/x.png")
    assert e.value.code() == grpc.StatusCode.UNIMPLEMENTED


def test_invalid_request_does_not_kill_engine(served):
    import grpc

    client, _ = served
    with pytest.raises(grpc.RpcError) as e:
        client.predict(prompt_ids=[10**6], tokens=4)  # out-of-vocab id
    assert e.value.code() == grpc.StatusCode.INVALID_ARGUMENT
    r = client.predict(prompt="still alive", tokens=4, temperature=0.0,
                      ignore_eos=True)
    assert r.tokens == 4


def test_subprocess_spawn_and_stream(ckpt, tmp_path):
    """Full process boundary: spawn the backend like the control plane would,
    health-poll, load, stream, terminate."""
    from localai_tpu.backend.client import BackendClient

    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = os.path.dirname(os.path.dirname(__file__))
    proc = subprocess.Popen(
        [sys.executable, "-c",
         "import jax; jax.config.update('jax_platforms','cpu');"
         "from localai_tpu.backend.__main__ import main; main()",
         ],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        cwd=str(tmp_path),
    )
    try:
        client = BackendClient("127.0.0.1:50051")
        assert client.wait_ready(attempts=120, sleep=0.5), "backend never healthy"
        r = client.load_model(model=ckpt, dtype="float32", parallel=2,
                              context_size=64, prefill_buckets=[32])
        assert r.success, r.message
        chunks = list(client.predict_stream(prompt="hello", tokens=5,
                                            temperature=0.0, ignore_eos=True))
        assert chunks[-1].finish_reason == "length"
        client.close()
    finally:
        proc.terminate()
        try:
            proc.wait(timeout=15)
        except subprocess.TimeoutExpired:
            proc.kill()


def test_draft_model_load_and_stream(ckpt):
    """LoadModel with draft_model (reference DraftModel role) serves
    speculative decoding over gRPC with acceptance metrics exposed."""
    from localai_tpu.backend.llm import LLMServicer
    from localai_tpu.backend import pb

    s = LLMServicer()
    r = s.LoadModel(pb.ModelOptions(
        model=ckpt, context_size=128, parallel=2, dtype="float32",
        prefill_buckets=[32], draft_model=ckpt, n_draft=3), None)
    assert r.success, r.message
    try:
        replies = list(s.PredictStream(pb.PredictOptions(
            prompt="pack my box", tokens=12, temperature=0.0,
            ignore_eos=True), None))
        ids = [t for rep in replies for t in rep.token_ids]
        assert len(ids) == 12
        m = s.GetMetrics(pb.MetricsRequest(), None).metrics
        assert m["draft_proposed"] > 0
        assert m["draft_accepted"] >= 0
    finally:
        s.shutdown()


def test_load_model_prewarm_path(ckpt, monkeypatch):
    """LoadModel's serving prewarm (backend/llm.py _prewarm) runs when not
    disabled and leaves the engine READY with the hot programs compiled —
    the suite otherwise disables it (conftest LOCALAI_NO_PREWARM=1), so
    this is the one place the path executes under CI."""
    monkeypatch.delenv("LOCALAI_NO_PREWARM", raising=False)
    from localai_tpu.backend.client import BackendClient
    from localai_tpu.backend.server import serve

    server, servicer, port = serve("127.0.0.1:0", "llm")
    client = BackendClient(f"127.0.0.1:{port}")
    try:
        assert client.wait_ready(attempts=20, sleep=0.1)
        r = client.load_model(model=ckpt, dtype="float32", parallel=2,
                              context_size=128, prefill_buckets=[32])
        assert r.success, r.message
        # prewarm generated through the engine: its dispatch counters moved
        m = client.metrics()
        assert m.get("decode_dispatches", 0) > 0
        assert m.get("tokens_generated", 0) > 0
        reply = client.predict(prompt="hello", tokens=4, temperature=0.0,
                               ignore_eos=True)
        assert len(reply.token_ids) == 4
    finally:
        client.close()
        servicer.shutdown()
        server.stop(grace=1)
