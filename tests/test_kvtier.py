"""KV lifecycle tier (engine/kvtier.py + engine compact-ring geometry):
attention-sink + sliding-window retention, quantized cold blocks, eviction
and recompute accounting for long-context serving.

Design per PAPERS.md attention-sink streaming (SnapStream) and sub-channel
KV quantization (Transformer-Lite). Cheap policy/geometry/jaxpr checks run
in tier-1; the engine-driving parity, refcount, and tripwire streams are
slow-marked and run standalone via `-m longctx` (the CI slow lane picks
them up through `-m slow`).
"""
import numpy as np
import pytest

from localai_tpu.engine import kvtier
from localai_tpu.engine.kvtier import (
    KVPolicy, engine_margin_tokens, parse_policy, resident_blocks,
    resolve_policy, ring_blocks,
)
from localai_tpu.ops.paged import (
    BLOCK, blocks_needed, resident_block_positions, ring_block_map,
)

pytestmark = pytest.mark.longctx

TINY = dict(vocab_size=96, hidden_size=32, intermediate_size=64,
            num_layers=2, num_heads=2, num_kv_heads=2, head_dim=16,
            max_position=33280, dtype="float32")


# ------------------------------------------------------------ policy layer


def test_policy_parse():
    assert parse_policy("") == KVPolicy()
    assert parse_policy("full") == KVPolicy()
    p = parse_policy("sink_window(sinks=256, window=1024)")
    assert (p.kind, p.sinks, p.window, p.quantize_cold) == \
        ("sink_window", 256, 1024, False)
    assert p.windowed and p.sink_blocks == 2
    q = parse_policy("sink_window(window=512, quantize_cold=true)")
    assert q.sinks == 0 and q.quantize_cold
    assert "quantize_cold" in q.describe()
    for bad in ("lru", "sink_window", "sink_window()",
                "sink_window(sinks=4)", "sink_window(window=-1)",
                "sink_window(frobnicate=1)"):
        with pytest.raises(ValueError):
            parse_policy(bad)


def test_resolve_policy_narrowing_only():
    eng = parse_policy("sink_window(sinks=256, window=1024)")
    # request may shrink the retention set
    r = resolve_policy("sink_window(sinks=128, window=512)", eng)
    assert (r.sinks, r.window) == (128, 512)
    # full request under a windowed engine is fine (identity geometry)
    assert not resolve_policy("full", eng).windowed
    # widening past the engine geometry is rejected
    with pytest.raises(ValueError):
        resolve_policy("sink_window(sinks=512, window=1024)", eng)
    with pytest.raises(ValueError):
        resolve_policy("sink_window(sinks=256, window=4096)", eng)
    # windowed request needs a windowed engine (no ring to ride)
    with pytest.raises(ValueError):
        resolve_policy("sink_window(sinks=0, window=256)", KVPolicy())
    # quantize_cold is an engine property, inherited not per-request
    c = resolve_policy("sink_window(sinks=128, window=512)",
                       parse_policy("sink_window(sinks=256, window=1024, "
                                    "quantize_cold=true)"))
    assert c.quantize_cold


def test_ring_geometry():
    # ring = window span + write-ahead margin + partial/demote slack
    assert ring_blocks(1024, 512) == 8 + 4 + 2
    pol = parse_policy("sink_window(sinks=256, window=1024)")
    assert resident_blocks(pol, 512) == 2 + 14
    from localai_tpu.engine.engine import EngineConfig

    ec = EngineConfig(prefill_chunk=256, decode_loop=64, decode_block=16)
    assert engine_margin_tokens(ec) == 256


def test_ring_block_map_roundtrip():
    """Ring write map + resident read map agree: after writing raw blocks
    0..n-1 through ring_block_map, resident_block_positions recovers
    exactly the still-resident raw index for every table column."""
    import jax.numpy as jnp

    sb, rw, maxb = 2, 5, 7
    for total in (3, 7, 12, 23):
        # last writer wins per column — emulate the scatter stream
        col_owner = {}
        for raw in range(total):
            vb = int(ring_block_map(jnp.asarray(raw), jnp.asarray(sb),
                                    jnp.asarray(rw)))
            if raw < sb:
                assert vb == raw
            else:
                assert sb <= vb < sb + rw
            col_owner[vb] = raw
        length = total * BLOCK
        raw_pos, ok = resident_block_positions(
            maxb, jnp.asarray([sb]), jnp.asarray([rw]),
            jnp.asarray([length]))
        raw_pos, ok = np.asarray(raw_pos)[0], np.asarray(ok)[0]
        for j in range(maxb):
            if ok[j]:
                assert col_owner.get(j) == raw_pos[j], (total, j)
            else:
                # a masked ring column was never written: any writer with
                # raw in [sb, total) would have made it resident
                assert col_owner.get(j) is None, (total, j)


# ------------------------------------------------ admission / _blocks_for


@pytest.fixture(scope="module")
def tiny_parts():
    import jax

    from localai_tpu.models.llama import LlamaConfig, init_params

    cfg = LlamaConfig(**TINY)
    return cfg, init_params(cfg, jax.random.PRNGKey(0))


def _engine(tiny_parts, **kw):
    from localai_tpu.engine.engine import Engine, EngineConfig

    cfg, params = tiny_parts
    return Engine(cfg, params, None, EngineConfig(**kw))


def test_blocks_for_respects_retention(tiny_parts):
    """A ctx-4k request under sink_window admits against the RESIDENT
    footprint, not the virtual context — the same pool rejects it under
    the full policy, and that rejection names the policy."""
    from localai_tpu.engine.engine import GenRequest
    from localai_tpu.ops.sampling import SamplingParams

    eng = _engine(tiny_parts, max_slots=1, max_context=4096,
                  prefill_buckets=(16,), kv_pages=24,
                  kv_policy="sink_window(sinks=256, window=512)")
    assert eng._maxb == eng._kv_resident <= 23
    # virtual blocks ~32 >> resident: must NOT raise
    rid, out = eng.submit(GenRequest(list(range(1, 40)), SamplingParams(),
                                     max_tokens=3900, ignore_eos=True))
    assert rid >= 0 and out is not None
    full = _engine(tiny_parts, max_slots=1, max_context=4096,
                   prefill_buckets=(16,), kv_pages=24)
    with pytest.raises(ValueError, match="KV blocks.*kv_policy full"):
        full.submit(GenRequest(list(range(1, 40)), SamplingParams(),
                               max_tokens=3900))


def test_tiered_config_validation(tiny_parts):
    with pytest.raises(ValueError, match="kv_pages"):
        _engine(tiny_parts, kv_policy="sink_window(sinks=0, window=256)")
    with pytest.raises(ValueError, match="kv_cold_pages"):
        _engine(tiny_parts, kv_pages=64, kv_cold_pages=8)
    with pytest.raises(ValueError, match="kv_cold_pages"):
        _engine(tiny_parts, kv_pages=64, kv_cold_pages=1,
                kv_policy="sink_window(sinks=0, window=256, "
                          "quantize_cold=true)")
    with pytest.raises(ValueError, match="cache_type"):
        _engine(tiny_parts, kv_pages=64, kv_cold_pages=8, cache_type="int8",
                kv_policy="sink_window(sinks=0, window=256, "
                          "quantize_cold=true)")


# ------------------------------------------------------------ jaxpr proof


def test_tier_map_adds_no_full_pool_gather(tiny_parts):
    """Structural proof for the compact-table contract: the tiered decode
    step's jaxpr materializes the [maxb*BLOCK]-row gathered view and NO
    intermediate sized by the full pool — gather cost is O(sinks+window)
    regardless of kv_pages."""
    import jax
    import jax.numpy as jnp

    from localai_tpu.models.llama import decode_step
    from localai_tpu.ops.paged import init_paged
    from localai_tpu.ops.rope import rope_table

    cfg, params = tiny_parts
    B, NB, MAXB = 2, 64, 6
    kc, vc = init_paged(cfg.num_layers, NB, cfg.num_kv_heads, cfg.head_dim,
                        jnp.float32)
    cos, sin = rope_table(cfg.rope, 1024)
    kvt = {"sb": jnp.ones((B,), jnp.int32),
           "rw": jnp.full((B,), MAXB - 1, jnp.int32),
           "sinks": jnp.full((B,), 128, jnp.int32),
           "window": jnp.full((B,), 256, jnp.int32)}
    jaxpr = jax.make_jaxpr(
        lambda p, t, l, k, v, tab, kvt: decode_step(
            p, cfg, t, l, cos, sin, k, v, table=tab, kvt=kvt)
    )(params, jnp.ones((B,), jnp.int32), jnp.full((B,), 500, jnp.int32),
      kc, vc, jnp.zeros((B, MAXB), jnp.int32), kvt)

    full_rows = NB * BLOCK
    compact_rows = MAXB * BLOCK
    saw_compact = False

    def walk(jx):
        nonlocal saw_compact
        for eqn in jx.eqns:
            for var in eqn.outvars:
                shape = getattr(var.aval, "shape", ())
                assert full_rows not in shape, (
                    f"full-pool-sized intermediate {shape} "
                    f"from {eqn.primitive}")
                if compact_rows in shape:
                    saw_compact = True
            for sub in jax.core.jaxprs_in_params(eqn.params):
                walk(getattr(sub, "jaxpr", sub))

    walk(jaxpr.jaxpr)
    assert saw_compact, "expected a [maxb*BLOCK]-row gathered view"


# ------------------------------------------------- engine-driving streams
# (slow lane: each builds + compiles engines; runs via -m slow / -m longctx)


def _drive(eng, reqs, timeout=120):
    outs = [eng.submit(r)[1] for r in reqs]
    ids, reasons = [], []
    for out in outs:
        toks = []
        while True:
            o = out.get(timeout=timeout)
            if o.token_id >= 0:
                toks.append(o.token_id)
            if o.finished:
                ids.append(toks)
                reasons.append(o.finish_reason)
                break
    return ids, reasons


def _req(prompt, n, *, seed=0, temp=0.8, policy=""):
    from localai_tpu.engine.engine import GenRequest
    from localai_tpu.ops.sampling import SamplingParams

    return GenRequest(list(prompt), SamplingParams(temperature=temp,
                                                   seed=seed),
                      max_tokens=n, ignore_eos=True, kv_policy=policy)


@pytest.mark.slow
def test_tier_parity_exact_when_retention_covers_context(tiny_parts):
    """sinks+window >= context: nothing ever leaves retention, so the
    tiered engine's token streams are EXACTLY the full-KV ones (ring
    write map + masked tiered attention are semantically invisible)."""
    rng = np.random.default_rng(3)
    prompts = [rng.integers(1, 96, n).tolist() for n in (37, 120, 64)]
    reqs = lambda: [_req(p, 24, seed=10 + i, temp=0.8)  # noqa: E731
                    for i, p in enumerate(prompts)]
    ec = dict(max_slots=3, max_context=512, prefill_buckets=(32,),
              decode_block=4)
    full = _engine(tiny_parts, kv_pages=16, **ec)
    full.start()
    try:
        ref, rr = _drive(full, reqs())
    finally:
        full.stop()
    tier = _engine(tiny_parts, kv_pages=32,
                   kv_policy="sink_window(sinks=256, window=256)", **ec)
    tier.start()
    try:
        got, gr = _drive(tier, reqs())
    finally:
        tier.stop()
    assert gr == rr == ["length"] * 3
    assert got == ref


@pytest.mark.slow
def test_eviction_prefix_cache_refcount_interaction(tiny_parts):
    """Windowed admissions may borrow ONLY whole sink blocks from a shared
    prefix (ring columns hold rotated content no other tenant can address);
    the excess shared blocks are unref'd — never corrupted — and the
    recompute metric records the re-prefilled blocks. The full-policy
    tenant's retained prefix survives the windowed tenant's lifecycle."""
    rng = np.random.default_rng(5)
    prefix = rng.integers(1, 96, 4 * BLOCK).tolist()
    ec = dict(max_slots=2, max_context=2048, prefill_buckets=(32,),
              decode_block=4, prompt_cache_min=8)
    eng = _engine(tiny_parts, kv_pages=40,
                  kv_policy="sink_window(sinks=128, window=256)", **ec)
    eng.start()
    try:
        # full-policy tenant seeds the prefix cache (retained at release)
        ref, _ = _drive(eng, [_req(prefix + [7, 8], 8, seed=1, temp=0.0,
                                   policy="full")])
        hits0 = eng.metrics["prompt_cache_hits"]
        # windowed tenant shares the prefix: borrows sink blocks only
        _drive(eng, [_req(prefix + [9, 10], 8, seed=2)])
        assert eng.metrics["prompt_cache_hits"] > hits0
        # 4 shared prefix blocks, sink_blocks=1 -> 3 blocks re-prefilled
        assert eng.metrics["kv_recomputes"] == 3
        # the retained full-policy prefix is intact: same prompt, same
        # greedy tokens as the cold first run
        again, _ = _drive(eng, [_req(prefix + [7, 8], 8, seed=1, temp=0.0,
                                     policy="full")])
        assert again == ref
        # pool accounting closed: a block is in the free list iff its
        # refcount is zero — no leak, no double-free, no corrupted share
        free = set(eng._kv_free)
        assert len(free) == len(eng._kv_free)
        for pb in range(1, eng.ec.kv_pages):
            assert (pb in free) == (eng._block_ref[pb] == 0), pb
    finally:
        eng.stop()


@pytest.mark.slow
@pytest.mark.tripwire
def test_tier_tripwires_mixed_hot_cold_stream(tiny_parts):
    """Compile-once + dispatch-budget on a mixed hot/cold stream: full and
    windowed requests interleaved on a quantize_cold engine, demotions
    firing mid-stream. The per-slot tier map is runtime data — a second
    mixed stream compiles NOTHING new — and demote copies ride their own
    program without spending decode dispatches."""
    from localai_tpu.testing.tripwires import (
        CompileCounter, decode_cache_sizes, decode_compile_count,
        dispatch_budget,
    )

    rng = np.random.default_rng(7)
    ec = dict(max_slots=2, max_context=2048, prefill_buckets=(32,),
              decode_block=4, prompt_cache=False)
    eng = _engine(tiny_parts, kv_pages=40, kv_cold_pages=24,
                  kv_policy="sink_window(sinks=128, window=128, "
                            "quantize_cold=true)", **ec)
    eng.start()
    try:
        def stream(seed):
            r = np.random.default_rng(seed)
            return [
                _req(r.integers(1, 96, 20).tolist(), 400, seed=seed),
                _req(r.integers(1, 96, 33).tolist(), 8, seed=seed + 1,
                     policy="full"),
                _req(r.integers(1, 96, 150).tolist(), 300, seed=seed + 2,
                     policy="sink_window(sinks=128, window=128)"),
            ]

        _, reasons = _drive(eng, stream(11))
        assert reasons == ["length"] * 3
        assert eng.metrics["kv_cold_blocks"] > 0, eng.metrics
        warm = decode_compile_count(eng)
        with CompileCounter() as cc:
            with dispatch_budget(eng, max_per_128_tokens=3.0):
                _, reasons = _drive(eng, stream(23))
        assert reasons == ["length"] * 3
        assert cc.total == 0, cc.counts
        assert decode_compile_count(eng) == warm, decode_cache_sizes(eng)
        # demote copies are not decode dispatches
        assert eng.metrics["kv_cold_blocks"] > 0
    finally:
        eng.stop()


@pytest.mark.slow
def test_longctx_32k_quantize_cold_parity(tiny_parts):
    """ctx-32k decode parity vs full KV within the tier's stated tolerance:
    under quantize_cold every position stays readable (sinks + window at
    full precision, the exited middle at sub-channel int8), so greedy
    token agreement is bounded by int8 quantization error only — the
    documented tolerance (README Long-context tier)."""
    rng = np.random.default_rng(9)
    n = 32 * 1024
    prompt = rng.integers(1, 96, n).tolist()
    decode = 32
    ctx = n + decode + 2 * BLOCK
    ec = dict(max_slots=1, max_context=ctx, prefill_buckets=(128, 512),
              prefill_chunk=512, decode_block=8)
    full = _engine(tiny_parts, kv_pages=blocks_needed(ctx) + 2, **ec)
    full.start()
    try:
        # the 32k full-KV prefill is minutes of CPU work before the first
        # token lands — give the stream a generous first-chunk timeout
        (ref,), _ = _drive(full, [_req(prompt, decode, temp=0.0)],
                           timeout=900)
    finally:
        full.stop()
    cold = _engine(tiny_parts, kv_pages=64,
                   kv_cold_pages=blocks_needed(ctx) + 2,
                   kv_policy="sink_window(sinks=256, window=1024, "
                             "quantize_cold=true)", **ec)
    cold.start()
    try:
        (got,), _ = _drive(cold, [_req(prompt, decode, temp=0.0)],
                           timeout=900)
        m = dict(cold.metrics)
    finally:
        cold.stop()
    assert m["kv_cold_blocks"] > 200, m       # the middle really demoted
    assert m["kv_blocks_peak"] <= 63, m       # pool bounded, not O(ctx)
    agree = sum(a == b for a, b in zip(got, ref)) / max(len(ref), 1)
    assert agree >= 0.75, (agree, got, ref)
