"""Tensor-parallel correctness on the virtual 8-device CPU mesh.

The reference has no automated multi-device tests (SURVEY §4); these run the
REAL sharded path — params placed per param_specs, prefill/decode jitted under
an active mesh so every with_sharding_constraint is a hard constraint — and
assert bit-level agreement with the unsharded single-device run.
"""
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from localai_tpu.models.llama import (
    LlamaConfig, init_params, init_kv_cache, prefill, decode_step,
    forward_train, param_specs, kv_cache_spec,
)
from localai_tpu.ops.rope import rope_table
from localai_tpu.parallel.mesh import (
    MeshConfig, activate_mesh, build_mesh, constrain, shard_params,
)

# head/ffn/vocab dims divisible by the model axis (4); slots by data axis (2)
CFG = LlamaConfig(
    vocab_size=256, hidden_size=64, intermediate_size=128, num_layers=2,
    num_heads=4, num_kv_heads=4, head_dim=16, max_position=128,
    dtype="float32",
)


@pytest.fixture(scope="module")
def params():
    return init_params(CFG, jax.random.PRNGKey(0))


def _reference(params, tokens, lengths, slot_map, T=32, slots=4):
    cos, sin = rope_table(CFG.rope, T)
    kc, vc = init_kv_cache(CFG, slots, T)
    logits, kc, vc = prefill(params, CFG, tokens, lengths, cos, sin, kc, vc,
                             slot_map)
    next_tok = jnp.argmax(logits, -1).astype(jnp.int32)
    slot_tokens = jnp.zeros((slots,), jnp.int32).at[slot_map].set(next_tok)
    slot_lengths = jnp.zeros((slots,), jnp.int32).at[slot_map].set(lengths)
    dlogits, _, _ = decode_step(params, CFG, slot_tokens, slot_lengths,
                                cos, sin, kc, vc)
    return np.asarray(logits), np.asarray(dlogits)


def test_tp_prefill_decode_matches_unsharded(mesh8):
    """Full sharded path (params + kv cache + activation constraints) must
    reproduce the unsharded logits exactly (same CPU arithmetic)."""
    ps = init_params(CFG, jax.random.PRNGKey(0))
    B, S, T, slots = 2, 5, 32, 4
    tokens = jax.random.randint(jax.random.PRNGKey(1), (B, S), 0, CFG.vocab_size)
    lengths = jnp.array([S, 3], jnp.int32)
    slot_map = jnp.array([0, 2], jnp.int32)

    ref_pre, ref_dec = _reference(ps, tokens, lengths, slot_map, T, slots)

    sharded = shard_params(ps, param_specs(CFG), mesh8)
    # every TP'd leaf must actually be distributed, not replicated
    assert sharded["layers"]["wq"].sharding.spec == P(None, None, "model")
    assert not sharded["layers"]["wq"].sharding.is_fully_replicated

    cos, sin = rope_table(CFG.rope, T)
    kv_sh = NamedSharding(mesh8, kv_cache_spec())
    kc0, vc0 = init_kv_cache(CFG, slots, T)
    kc = jax.device_put(kc0, kv_sh)
    vc = jax.device_put(vc0, kv_sh)

    with activate_mesh(mesh8):
        pf = jax.jit(partial(prefill, cfg=CFG))
        logits, kc, vc = pf(sharded, tokens=tokens, lengths=lengths, cos=cos,
                            sin=sin, k_cache=kc, v_cache=vc, slot_map=slot_map)
        next_tok = jnp.argmax(logits, -1).astype(jnp.int32)
        slot_tokens = jnp.zeros((slots,), jnp.int32).at[slot_map].set(next_tok)
        slot_lengths = jnp.zeros((slots,), jnp.int32).at[slot_map].set(lengths)
        dc = jax.jit(partial(decode_step, cfg=CFG))
        dlogits, kc, vc = dc(sharded, tokens=slot_tokens, lengths=slot_lengths,
                             cos=cos, sin=sin, k_cache=kc, v_cache=vc)

    np.testing.assert_allclose(np.asarray(logits), ref_pre, rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(np.asarray(dlogits), ref_dec, rtol=1e-5, atol=1e-5)


def test_tp_forward_train_matches(mesh8, params):
    tokens = jnp.arange(12).reshape(2, 6) % CFG.vocab_size
    ref = np.asarray(forward_train(params, CFG, tokens))
    sharded = shard_params(params, param_specs(CFG), mesh8)
    with activate_mesh(mesh8):
        out = jax.jit(partial(forward_train, cfg=CFG))(sharded, tokens=tokens)
    np.testing.assert_allclose(np.asarray(out), ref, rtol=1e-5, atol=1e-5)


def test_mesh_shapes_and_validation(devices):
    m = build_mesh(MeshConfig(data=4, model=2))
    assert m.shape == {"data": 4, "model": 2}
    with pytest.raises(ValueError):
        build_mesh(MeshConfig(data=3, model=2))


def test_constrain_is_hard_under_mesh(mesh8):
    """A wrong-rank spec must raise at trace time — not degrade to a no-op."""
    x = jnp.zeros((8, 4))
    with activate_mesh(mesh8):
        with pytest.raises(ValueError):
            jax.jit(lambda a: constrain(a, P("data", None, "model")))(x)
    # no mesh → identity
    assert constrain(x, P("data", None, "model")) is x


def test_engine_on_mesh_matches_unmeshed():
    """Engine greedy decode under a 2x4 mesh == no-mesh engine, token for token."""
    from localai_tpu.engine import Engine, EngineConfig, GenRequest
    from localai_tpu.ops.sampling import SamplingParams

    ps = init_params(CFG, jax.random.PRNGKey(3))
    mesh = build_mesh(MeshConfig(data=2, model=4))
    prompt = [5, 9, 2, 7]
    req = lambda: GenRequest(prompt_ids=list(prompt),
                             params=SamplingParams(temperature=0.0),
                             max_tokens=8, ignore_eos=True)

    def run(mesh_arg):
        ec = EngineConfig(max_slots=2, max_context=64, prefill_buckets=(16,),
                          mesh=mesh_arg)
        eng = Engine(CFG, ps if mesh_arg is None else
                     shard_params(ps, param_specs(CFG), mesh_arg), None, ec)
        return [o.token_id for o in eng.generate(req())]

    assert run(None) == run(mesh)


def test_engine_multimodal_inject_on_mesh():
    """Multimodal embedding injection under a TP/DP mesh: injecting
    embed-table rows reproduces the pure-token request on the SAME mesh —
    the sharded flagship config serves images too."""
    import numpy as np

    from localai_tpu.engine import Engine, EngineConfig, GenRequest
    from localai_tpu.ops.sampling import SamplingParams

    ps = init_params(CFG, jax.random.PRNGKey(3))
    mesh = build_mesh(MeshConfig(data=2, model=4))
    prompt = [5, 9, 2, 7, 11, 3]
    embed = np.asarray(ps["embed"], np.float32)

    def run(mm):
        eng = Engine(CFG, shard_params(ps, param_specs(CFG), mesh), None,
                     EngineConfig(max_slots=2, max_context=64,
                                  prefill_buckets=(16,), mesh=mesh))
        req = GenRequest(prompt_ids=list(prompt),
                         params=SamplingParams(temperature=0.0),
                         max_tokens=8, ignore_eos=True)
        if mm:
            req.mm_embeds = embed[[9, 2, 7]]
            req.mm_positions = np.arange(1, 4)
        return [o.token_id for o in eng.generate(req)]

    assert run(False) == run(True)


def test_engine_seq_parallel_matches_unmeshed():
    """Ring-attention serving integration: an engine on a ('data','model',
    'seq') mesh (sequence-parallel prefill over the ppermute ring) must
    reproduce the no-mesh engine token-for-token."""
    from localai_tpu.engine import Engine, EngineConfig, GenRequest
    from localai_tpu.ops.sampling import SamplingParams

    ps = init_params(CFG, jax.random.PRNGKey(5))
    mesh = build_mesh(MeshConfig(data=1, model=2, seq=4))
    assert mesh.axis_names == ("data", "model", "seq")
    prompt = [5, 9, 2, 7, 11, 3]
    req = lambda: GenRequest(prompt_ids=list(prompt),
                             params=SamplingParams(temperature=0.0),
                             max_tokens=8, ignore_eos=True)

    def run(mesh_arg):
        ec = EngineConfig(max_slots=2, max_context=64, prefill_buckets=(16,),
                          mesh=mesh_arg)
        eng = Engine(CFG, ps if mesh_arg is None else
                     shard_params(ps, param_specs(CFG), mesh_arg), None, ec)
        return [o.token_id for o in eng.generate(req())]

    assert run(None) == run(mesh)
