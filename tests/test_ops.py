import jax
import jax.numpy as jnp
import numpy as np
import pytest

from localai_tpu.ops.norms import rms_norm
from localai_tpu.ops.rope import RopeConfig, rope_table, apply_rope
from localai_tpu.ops.attention import mha_prefill, mha_decode
from localai_tpu.ops.sampling import SamplerState, SamplingParams, sample, sampler_row


def test_rms_norm():
    x = jax.random.normal(jax.random.PRNGKey(0), (2, 8), jnp.float32)
    w = jnp.ones((8,))
    y = rms_norm(x, w)
    rms = np.sqrt(np.mean(np.asarray(y) ** 2, -1))
    np.testing.assert_allclose(rms, 1.0, rtol=1e-3)


def test_rope_orthogonal_norm_preserved():
    cfg = RopeConfig(head_dim=16)
    cos, sin = rope_table(cfg, 32)
    x = jax.random.normal(jax.random.PRNGKey(1), (1, 4, 2, 16))
    pos = jnp.arange(4)[None, :]
    y = apply_rope(x, cos, sin, pos)
    np.testing.assert_allclose(
        np.linalg.norm(np.asarray(x), axis=-1),
        np.linalg.norm(np.asarray(y), axis=-1), rtol=1e-4)


def test_rope_scaling_modes():
    for mode in ["linear", "yarn", "llama3"]:
        cfg = RopeConfig(head_dim=16, scaling=mode, scale_factor=4.0,
                         original_max_position=64)
        cos, sin = rope_table(cfg, 128)
        assert np.isfinite(np.asarray(cos)).all()


def test_mha_prefill_against_naive():
    B, S, H, KVH, D = 1, 6, 4, 2, 8
    key = jax.random.PRNGKey(0)
    q = jax.random.normal(key, (B, S, H, D))
    k = jax.random.normal(jax.random.PRNGKey(1), (B, S, KVH, D))
    v = jax.random.normal(jax.random.PRNGKey(2), (B, S, KVH, D))
    out = mha_prefill(q, k, v, jnp.array([S]))
    ref2 = np.zeros((S, H, D))
    qf = np.asarray(q[0], np.float64)
    kf = np.asarray(k[0], np.float64)
    vf = np.asarray(v[0], np.float64)
    for i in range(H):
        j = i // (H // KVH)
        logits = qf[:, i] @ kf[:, j].T / np.sqrt(D)
        for s in range(S):
            row = logits[s].copy()
            row[s + 1:] = -1e30
            e = np.exp(row - row.max())
            ref2[s, i] = (e / e.sum()) @ vf[:, j]
    np.testing.assert_allclose(np.asarray(out[0]), ref2, rtol=1e-4, atol=1e-5)


def test_mha_decode_matches_prefill_last_row():
    B, S, H, KVH, D = 2, 5, 4, 2, 8
    q = jax.random.normal(jax.random.PRNGKey(0), (B, S, H, D))
    k = jax.random.normal(jax.random.PRNGKey(1), (B, S, KVH, D))
    v = jax.random.normal(jax.random.PRNGKey(2), (B, S, KVH, D))
    lengths = jnp.array([S, S])
    pre = mha_prefill(q, k, v, lengths)
    T = 16
    kc = jnp.zeros((B, KVH, T, D)).at[:, :, :S].set(k.transpose(0, 2, 1, 3))
    vc = jnp.zeros((B, KVH, T, D)).at[:, :, :S].set(v.transpose(0, 2, 1, 3))
    dec = mha_decode(q[:, S - 1:S], kc, vc, lengths)
    np.testing.assert_allclose(np.asarray(dec[:, 0]), np.asarray(pre[:, S - 1]),
                               rtol=1e-4, atol=1e-5)


def test_sampling_greedy_and_topk():
    B, V = 2, 50
    st = SamplerState.init(B, V)
    row = sampler_row(SamplingParams(temperature=0.0), V, fallback_seed=7)
    for f, val in row.items():
        setattr(st, f, getattr(st, f).at[0].set(val))
    row1 = sampler_row(SamplingParams(temperature=1.0, top_k=1, seed=3), V, 0)
    for f, val in row1.items():
        setattr(st, f, getattr(st, f).at[1].set(val))
    logits = jnp.zeros((B, V)).at[:, 17].set(10.0)
    toks, keys, lp = sample(logits, st)
    assert int(toks[0]) == 17  # greedy picks max
    assert int(toks[1]) == 17  # top_k=1 also forced


def test_sampling_penalties_suppress_repeats():
    B, V = 1, 16
    st = SamplerState.init(B, V)
    row = sampler_row(SamplingParams(temperature=0.0, repeat_penalty=2.0), V, 0)
    for f, val in row.items():
        setattr(st, f, getattr(st, f).at[0].set(val))
    st.token_counts = st.token_counts.at[0, 5].set(3)
    logits = jnp.zeros((B, V)).at[0, 5].set(2.0).at[0, 9].set(1.5)
    toks, _, _ = sample(logits, st)
    # token 5 logit 2.0/2.0=1.0 < 1.5 → token 9 wins
    assert int(toks[0]) == 9


def test_quantize_stacked_per_layer_scales():
    """Stacked [L, in, out] weights must get PER-LAYER scales [L, 1, out] —
    a collapsed leading axis breaks lax.scan and shares one scale across
    layers (round-4 review finding)."""
    from localai_tpu.ops.quant import dequantize, qmatmul, quantize

    L, fin, fout = 3, 16, 8
    w = jax.random.normal(jax.random.PRNGKey(0), (L, fin, fout))
    w = w.at[1].multiply(100.0)  # wildly different per-layer magnitude
    p = quantize(w)
    assert p["q"].shape == (L, fin, fout)
    assert p["s"].shape == (L, 1, fout)
    assert float(p["s"][1].mean()) > 10 * float(p["s"][0].mean())
    deq = dequantize(p, jnp.float32)
    rel = jnp.abs(deq - w).max() / jnp.abs(w).max()
    assert float(rel) < 0.02
    x = jax.random.normal(jax.random.PRNGKey(1), (2, fin))
    np.testing.assert_allclose(np.asarray(qmatmul(x, {k: v[0] for k, v in p.items()})),
                               np.asarray(x @ deq[0]), rtol=1e-4, atol=1e-4)


@pytest.mark.parametrize("qdtype,min_agree", [("int8", 0.8), ("int4", 0.5)])
def test_quantized_checkpoint_load_and_forward(qdtype, min_agree):
    """dtype=int8/int4 through the REAL loader (quantize_params over the scan
    layout) must forward without shape errors and stay close to f32."""
    import sys
    sys.path.insert(0, "tests")
    from fixtures import build_tiny_checkpoint

    import tempfile

    from localai_tpu.engine import load_config, load_params
    from localai_tpu.models.llama import forward_train

    d = tempfile.mkdtemp(prefix="qckpt-")
    build_tiny_checkpoint(d)
    cfg32 = load_config(d, dtype="float32")
    p32 = load_params(d, cfg32, dtype="float32")
    cfgq = load_config(d, dtype=qdtype)
    pq = load_params(d, cfgq, dtype=qdtype)
    if qdtype == "int4":
        assert pq["layers"]["wq"]["q"].dtype == jnp.int4
    toks = jnp.arange(10)[None, :] % cfg32.vocab_size
    ref = np.asarray(forward_train(p32, cfg32, toks))
    out = np.asarray(forward_train(pq, cfgq, toks).astype(jnp.float32))
    # quantized weights: argmax should mostly survive the rounding
    assert (ref.argmax(-1) == out.argmax(-1)).mean() > min_agree
