"""Int8 KV cache (reference CacheTypeKey/Value, backend.proto:257-258) and
fused decode blocks: parity against the dense/bf16 paths."""
import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from localai_tpu.models.llama import (
    LlamaConfig, cache_shift, decode_step, extend, init_kv_cache, prefill,
)
from localai_tpu.ops.kvcache import (
    QuantKV, dequant, init_quant, is_quant_kind, quantize_tokens,
)
from localai_tpu.ops.rope import rope_table

CFG = LlamaConfig(vocab_size=128, hidden_size=64, intermediate_size=128,
                  num_layers=2, num_heads=4, num_kv_heads=2, head_dim=16,
                  max_position=256, dtype="float32")


def _params(cfg=CFG, seed=0):
    from localai_tpu.models.llama import init_params

    return init_params(cfg, jax.random.PRNGKey(seed), jnp.float32)


def test_quantize_roundtrip():
    x = jax.random.normal(jax.random.PRNGKey(0), (3, 5, 2, 16))
    q, s = quantize_tokens(x)
    assert q.dtype == jnp.int8
    back = q.astype(jnp.float32) * s[..., None]
    np.testing.assert_allclose(np.asarray(back), np.asarray(x),
                               atol=float(np.abs(x).max()) / 100)


def test_is_quant_kind():
    assert is_quant_kind("int8") and is_quant_kind("q8_0")
    assert not is_quant_kind("") and not is_quant_kind("bf16")


def test_init_kv_cache_int8_layout():
    kc, vc = init_kv_cache(CFG, 2, 200, cache_type="int8")
    assert isinstance(kc, QuantKV)
    # token axis padded to the 128 scale tile
    assert kc.shape == (2, 2, 2, 256, 16)
    assert kc.q.dtype == jnp.int8
    assert kc.s.shape == (2, 2, 2, 2, 128)
    # dense bytes would be 4x (f32) the int8 payload
    assert kc.q.nbytes == np.prod(kc.shape)


def _run_decode(cache_type, n_steps=6):
    params = _params()
    B, T = 2, 128
    kc, vc = init_kv_cache(CFG, B, T, cache_type=cache_type)
    cos, sin = rope_table(CFG.rope, T)
    tokens = jnp.array([[1, 2, 3, 4, 0, 0], [5, 6, 7, 0, 0, 0]], jnp.int32)
    lengths = jnp.array([4, 3], jnp.int32)
    logits, kc, vc = prefill(params, CFG, tokens, lengths, cos, sin, kc, vc,
                             jnp.arange(B))
    outs = [logits]
    toks = jnp.argmax(logits, -1)
    for _ in range(n_steps):
        logits, kc, vc = decode_step(params, CFG, toks, lengths, cos, sin,
                                     kc, vc)
        lengths = lengths + 1
        toks = jnp.argmax(logits, -1)
        outs.append(logits)
    return [np.asarray(o) for o in outs]


def test_decode_parity_int8_vs_dense():
    dense = _run_decode("")
    quant = _run_decode("int8")
    for d, q in zip(dense, quant):
        # int8 cache error is small relative to the logit scale
        assert np.max(np.abs(d - q)) < 0.05 * max(np.max(np.abs(d)), 1.0)


def test_extend_parity_int8_vs_dense():
    params = _params()
    B, T, S = 2, 128, 4
    cos, sin = rope_table(CFG.rope, T)
    tokens = jnp.array([[9, 8, 7, 6], [1, 2, 3, 4]], jnp.int32)
    start = jnp.array([0, 0], jnp.int32)
    outs = {}
    for kind in ("", "int8"):
        kc, vc = init_kv_cache(CFG, B, T, cache_type=kind)
        logits, _, _ = extend(params, CFG, tokens, start, cos, sin, kc, vc)
        outs[kind] = np.asarray(logits)
    assert np.max(np.abs(outs[""] - outs["int8"])) < 0.05 * np.max(
        np.abs(outs[""]) + 1.0)


def test_ragged_decode_q8_matches_xla_on_same_values():
    from localai_tpu.ops.attention import mha_decode
    from localai_tpu.ops.pallas import ragged_decode_q8

    B, H, KVH, D, T = 2, 4, 2, 64, 256
    q = jax.random.normal(jax.random.PRNGKey(0), (B, 1, H, D), jnp.float32)
    kd = jax.random.normal(jax.random.PRNGKey(1), (B, KVH, T, D))
    vd = jax.random.normal(jax.random.PRNGKey(2), (B, KVH, T, D))
    kc = init_quant((B, KVH, T, D))
    kq, ks = quantize_tokens(kd)
    vq, vs = quantize_tokens(vd)
    kc = QuantKV(kq, ks.reshape(B, KVH, T // 128, 128))
    vc = QuantKV(vq, vs.reshape(B, KVH, T // 128, 128))
    lengths = jnp.array([200, 77], jnp.int32)
    out = ragged_decode_q8(q, kc.q, kc.s, vc.q, vc.s, lengths)
    ref = mha_decode(q.astype(jnp.float32),
                     dequant(kc, jnp.float32), dequant(vc, jnp.float32),
                     lengths)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-2, atol=2e-2)


def test_cache_shift_quant_parity():
    B, T = 1, 128
    cfg = CFG
    kd = jax.random.normal(jax.random.PRNGKey(3),
                           (cfg.num_layers, B, cfg.num_kv_heads, T,
                            cfg.head_dim))
    vd = jax.random.normal(jax.random.PRNGKey(4), kd.shape)
    lengths = jnp.array([100], jnp.int32)
    kq, ks = quantize_tokens(kd)
    vq, vs = quantize_tokens(vd)
    kcq = QuantKV(kq, ks.reshape(*ks.shape[:-1], T // 128, 128))
    vcq = QuantKV(vq, vs.reshape(*vs.shape[:-1], T // 128, 128))

    kd2, vd2, l2 = cache_shift(cfg, kd, vd, lengths, 0, keep=4, discard=32)
    kq2, vq2, lq2 = cache_shift(cfg, kcq, vcq, lengths, 0, keep=4, discard=32)
    assert int(l2[0]) == int(lq2[0]) == 68
    scale = float(np.max(np.abs(np.asarray(kd2)))) or 1.0
    n = 68
    np.testing.assert_allclose(
        np.asarray(dequant(kq2, jnp.float32))[:, :, :, :n],
        np.asarray(kd2)[:, :, :, :n], atol=0.05 * scale)
    np.testing.assert_allclose(
        np.asarray(dequant(vq2, jnp.float32))[:, :, :, :n],
        np.asarray(vd2)[:, :, :, :n], atol=0.05 * scale)


# --------------------------------------------------------------- engine level

def _collect(out_q):
    texts, toks = [], []
    while True:
        o = out_q.get(timeout=60)
        toks.append(o.token_id)
        if o.finished:
            return toks, o


def _engine(cache_type="", decode_block=1, **kw):
    from localai_tpu.engine import Engine, EngineConfig
    from localai_tpu.engine.engine import GenRequest, SamplingParams

    cfg = dataclasses.replace(CFG, dtype="float32")
    params = _params(cfg)
    eng = Engine(cfg, params, None, EngineConfig(
        max_slots=2, max_context=128, prefill_buckets=(16,),
        prefill_chunk=16, cache_type=cache_type, decode_block=decode_block,
        **kw))
    return eng, GenRequest, SamplingParams


def test_engine_int8_cache_serves():
    eng, GenRequest, SamplingParams = _engine(cache_type="int8")
    eng.start()
    try:
        _, q = eng.submit(GenRequest(
            prompt_ids=[1, 2, 3], max_tokens=8, ignore_eos=True,
            params=SamplingParams(temperature=0.0, seed=7)))
        toks, last = _collect(q)
        assert len(toks) == 8 and last.finish_reason == "length"
    finally:
        eng.stop()


def test_engine_decode_block_parity():
    """Fused-block dispatch must emit the exact same tokens as single steps
    (per-slot RNG streams are independent of dispatch grouping)."""
    results = []
    for block in (1, 4):
        eng, GenRequest, SamplingParams = _engine(decode_block=block)
        eng.start()
        try:
            _, q = eng.submit(GenRequest(
                prompt_ids=[5, 6, 7, 8], max_tokens=12, ignore_eos=True,
                params=SamplingParams(temperature=0.8, top_k=20, seed=3)))
            toks, _ = _collect(q)
            results.append(toks)
        finally:
            eng.stop()
    assert results[0] == results[1]


@pytest.mark.parametrize("block", [1, 4])
def test_engine_int8_block_combined(block):
    eng, GenRequest, SamplingParams = _engine(cache_type="int8",
                                              decode_block=block)
    eng.start()
    try:
        qs = [eng.submit(GenRequest(
            prompt_ids=[i + 1, i + 2], max_tokens=6, ignore_eos=True,
            params=SamplingParams(temperature=0.5, seed=i)))[1]
            for i in range(2)]
        for q in qs:
            toks, last = _collect(q)
            assert len(toks) == 6
    finally:
        eng.stop()


def test_fast_topk_sampler_parity():
    """Sort-free decode sampling (sampling_topk_width): greedy rows match the
    full path exactly, logprobs are full-vocab exact, and stochastic draws
    stay inside the top-k set."""
    import jax
    import jax.numpy as jnp

    from localai_tpu.ops.sampling import (
        SamplerState, SamplingParams, sample, sampler_row,
    )

    B, V = 4, 512
    logits = jax.random.normal(jax.random.PRNGKey(0), (B, V)) * 3.0
    st = SamplerState.init(B, V)
    rows = [sampler_row(SamplingParams(temperature=0.0, seed=1), V, 1),
            sampler_row(SamplingParams(temperature=0.8, top_k=20, seed=2),
                        V, 2),
            sampler_row(SamplingParams(temperature=1.2, top_k=5, top_p=0.9,
                                       seed=3), V, 3),
            sampler_row(SamplingParams(temperature=0.0, seed=4), V, 4)]
    import dataclasses as dc

    fields = {}
    for f in dc.fields(SamplerState):
        cur = getattr(st, f.name)
        if f.name == "token_counts":
            fields[f.name] = cur
        else:
            fields[f.name] = jnp.stack([r[f.name] for r in rows])
    st = SamplerState(**fields)

    t_full, _, lp_full = sample(logits, st)
    t_fast, _, lp_fast = sample(logits, st, topk_width=64)
    # greedy rows (0 and 3) must match exactly, incl. logprob
    for i in (0, 3):
        assert int(t_full[i]) == int(t_fast[i]) == int(jnp.argmax(logits[i]))
        assert abs(float(lp_full[i]) - float(lp_fast[i])) < 1e-4
    # stochastic rows: drawn token must be inside the row's top-k set
    for i, k in ((1, 20), (2, 5)):
        topk = set(np.asarray(jax.lax.top_k(logits[i], k)[1]).tolist())
        assert int(t_fast[i]) in topk
