"""Explorer (federation dashboard + discovery crawler) tests."""
import json
import threading

import pytest


def test_database_roundtrip(tmp_path):
    from localai_tpu.explorer import Database, NetworkData

    db = Database(str(tmp_path / "pool.json"))
    db.set("tok1", NetworkData(name="n1", url="http://a", description="d"))
    db.set("tok2", NetworkData(name="n2", url="http://b"))
    assert db.token_list() == ["tok1", "tok2"]
    assert db.get("tok1").name == "n1"
    db.delete("tok1")
    assert db.token_list() == ["tok2"]
    # second instance sees the same file state (flock + reload semantics)
    db2 = Database(str(tmp_path / "pool.json"))
    assert db2.get("tok2").url == "http://b"


def test_database_concurrent_writers(tmp_path):
    from localai_tpu.explorer import Database, NetworkData

    path = str(tmp_path / "pool.json")
    db = Database(path)

    def writer(i):
        Database(path).set(f"tok{i}", NetworkData(name=f"n{i}", url="u"))

    ts = [threading.Thread(target=writer, args=(i,)) for i in range(8)]
    [t.start() for t in ts]
    [t.join() for t in ts]
    assert len(db.token_list()) == 8


@pytest.fixture()
def fake_lb():
    """Minimal federated-LB lookalike serving /federation/workers."""
    from http.server import BaseHTTPRequestHandler, HTTPServer

    workers = [{"url": "http://w1:8080", "healthy": True},
               {"url": "http://w2:8080", "healthy": True}]

    class H(BaseHTTPRequestHandler):
        def log_message(self, *a):
            pass

        def do_GET(self):
            if self.path == "/federation/workers":
                body = json.dumps(workers).encode()
                self.send_response(200)
                self.end_headers()
                self.wfile.write(body)
            else:
                self.send_response(404)
                self.end_headers()

    srv = HTTPServer(("127.0.0.1", 0), H)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    yield f"http://127.0.0.1:{srv.server_address[1]}"
    srv.shutdown()


def test_discovery_sync_and_eviction(tmp_path, fake_lb):
    from localai_tpu.explorer import Database, DiscoveryServer, NetworkData

    db = Database(str(tmp_path / "pool.json"))
    db.set("good", NetworkData(name="good", url=fake_lb))
    db.set("dead", NetworkData(name="dead", url="http://127.0.0.1:9"))
    ds = DiscoveryServer(db, threshold=2, timeout=1.0)

    ds.sync_once()
    good = db.get("good")
    assert good.clusters[0]["workers"] == ["http://w1:8080", "http://w2:8080"]
    assert good.failures == 0
    assert db.get("dead").failures == 1

    ds.sync_once()   # second failure → evicted
    assert db.get("dead") is None
    assert db.get("good") is not None


def test_explorer_http_routes(tmp_path, fake_lb):
    import asyncio
    import socket
    import time

    import requests
    from aiohttp import web

    from localai_tpu.explorer import Database, build_explorer_app

    db = Database(str(tmp_path / "pool.json"))
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    loop = asyncio.new_event_loop()

    def run():
        asyncio.set_event_loop(loop)
        runner = web.AppRunner(build_explorer_app(db))
        loop.run_until_complete(runner.setup())
        site = web.TCPSite(runner, "127.0.0.1", port)
        loop.run_until_complete(site.start())
        loop.run_forever()

    threading.Thread(target=run, daemon=True).start()
    base = f"http://127.0.0.1:{port}"
    for _ in range(50):
        try:
            requests.get(base + "/", timeout=1)
            break
        except requests.ConnectionError:
            time.sleep(0.1)
    try:
        page = requests.get(base + "/", timeout=5)
        assert "Federated networks" in page.text
        r = requests.post(base + "/network/add", json={
            "name": "mynet", "url": fake_lb, "description": "test"},
            timeout=5)
        assert r.status_code == 200
        # duplicate rejected
        r = requests.post(base + "/network/add", json={"url": fake_lb},
                          timeout=5)
        assert r.status_code == 409
        nets = requests.get(base + "/networks", timeout=5).json()
        assert len(nets) == 1 and nets[0]["name"] == "mynet"
        # missing url rejected
        assert requests.post(base + "/network/add", json={},
                             timeout=5).status_code == 400
    finally:
        loop.call_soon_threadsafe(loop.stop)
