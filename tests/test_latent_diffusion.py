"""Latent diffusion (SD-class) pipeline: diffusers-layout checkpoint loading,
CLIP parity vs transformers (torch), and the end-to-end txt2img path."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from fixtures import build_tiny_sd_checkpoint


@pytest.fixture(scope="module")
def sd_ckpt(tmp_path_factory):
    return build_tiny_sd_checkpoint(str(tmp_path_factory.mktemp("sd")))


def test_clip_text_parity_with_transformers(sd_ckpt):
    """clip_encode over the loaded safetensors must match the torch
    CLIPTextModel's last_hidden_state."""
    import torch
    from transformers import CLIPTextModel

    from localai_tpu.models.latent_diffusion import (
        _component_config, _component_weights, clip_encode,
    )

    tm = CLIPTextModel.from_pretrained(sd_ckpt + "/text_encoder")
    tm.eval()
    ids = [[5, 9, 2, 7, 100, 42, 0, 0]]
    with torch.no_grad():
        ref = tm(torch.tensor(ids)).last_hidden_state.numpy()

    w = {k: jnp.asarray(v) for k, v in
         _component_weights(sd_ckpt, "text_encoder").items()}
    cfg = _component_config(sd_ckpt, "text_encoder")
    out = clip_encode(w, cfg, jnp.asarray(ids, jnp.int32))
    np.testing.assert_allclose(np.asarray(out), ref, rtol=2e-4, atol=2e-4)


def test_txt2img_end_to_end(sd_ckpt):
    """Full pipeline: text → CLIP → UNet DDIM scan → VAE decode → uint8
    image. Deterministic per seed; prompt changes the output (real
    conditioning, not noise)."""
    from localai_tpu.models.latent_diffusion import (
        LatentDiffusion, is_diffusers_checkpoint,
    )

    assert is_diffusers_checkpoint(sd_ckpt)
    pipe = LatentDiffusion(sd_ckpt)
    img1 = pipe.txt2img("a red cat", width=64, height=64, steps=4, seed=3)
    assert img1.shape == (64, 64, 3) and img1.dtype == np.uint8
    img1b = pipe.txt2img("a red cat", width=64, height=64, steps=4, seed=3)
    np.testing.assert_array_equal(img1, img1b)          # deterministic
    img2 = pipe.txt2img("a blue dog", width=64, height=64, steps=4, seed=3)
    assert (img1 != img2).mean() > 0.05                 # prompt conditions
    img3 = pipe.txt2img("a red cat", width=64, height=64, steps=4, seed=3,
                        guidance_scale=1.0)
    assert (img1 != img3).mean() > 0.05                 # guidance has effect


def test_image_backend_serves_sd_checkpoint(sd_ckpt, tmp_path):
    """The image servicer routes a diffusers-layout model dir to the
    LatentDiffusion pipeline and writes a real PNG."""
    from PIL import Image

    from localai_tpu.backend import pb
    from localai_tpu.backend.image import ImageServicer

    s = ImageServicer()
    r = s.LoadModel(pb.ModelOptions(model=sd_ckpt), None)
    assert r.success, r.message
    dst = str(tmp_path / "out.png")
    r = s.GenerateImage(pb.GenerateImageRequest(
        positive_prompt="a tiny test", dst=dst, width=64, height=64,
        step=3, seed=1), None)
    assert r.success
    img = Image.open(dst)
    assert img.size == (64, 64)
