"""Latent diffusion (SD-class) pipeline: diffusers-layout checkpoint loading,
CLIP parity vs transformers (torch), and the end-to-end txt2img path."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from fixtures import build_tiny_sd_checkpoint


@pytest.fixture(scope="module")
def sd_ckpt(tmp_path_factory):
    return build_tiny_sd_checkpoint(str(tmp_path_factory.mktemp("sd")))


def test_clip_text_parity_with_transformers(sd_ckpt):
    """clip_encode over the loaded safetensors must match the torch
    CLIPTextModel's last_hidden_state."""
    import torch
    from transformers import CLIPTextModel

    from localai_tpu.models.latent_diffusion import (
        _component_config, _component_weights, clip_encode,
    )

    tm = CLIPTextModel.from_pretrained(sd_ckpt + "/text_encoder")
    tm.eval()
    ids = [[5, 9, 2, 7, 100, 42, 0, 0]]
    with torch.no_grad():
        ref = tm(torch.tensor(ids)).last_hidden_state.numpy()

    w = {k: jnp.asarray(v) for k, v in
         _component_weights(sd_ckpt, "text_encoder").items()}
    cfg = _component_config(sd_ckpt, "text_encoder")
    out = clip_encode(w, cfg, jnp.asarray(ids, jnp.int32))
    np.testing.assert_allclose(np.asarray(out), ref, rtol=2e-4, atol=2e-4)


def test_txt2img_end_to_end(sd_ckpt):
    """Full pipeline: text → CLIP → UNet DDIM scan → VAE decode → uint8
    image. Deterministic per seed; prompt changes the output (real
    conditioning, not noise)."""
    from localai_tpu.models.latent_diffusion import (
        LatentDiffusion, is_diffusers_checkpoint,
    )

    assert is_diffusers_checkpoint(sd_ckpt)
    pipe = LatentDiffusion(sd_ckpt)
    img1 = pipe.txt2img("a red cat", width=64, height=64, steps=4, seed=3)
    assert img1.shape == (64, 64, 3) and img1.dtype == np.uint8
    img1b = pipe.txt2img("a red cat", width=64, height=64, steps=4, seed=3)
    np.testing.assert_array_equal(img1, img1b)          # deterministic
    img2 = pipe.txt2img("a blue dog", width=64, height=64, steps=4, seed=3)
    assert (img1 != img2).mean() > 0.05                 # prompt conditions
    img3 = pipe.txt2img("a red cat", width=64, height=64, steps=4, seed=3,
                        guidance_scale=1.0)
    assert (img1 != img3).mean() > 0.05                 # guidance has effect


def test_image_backend_serves_sd_checkpoint(sd_ckpt, tmp_path):
    """The image servicer routes a diffusers-layout model dir to the
    LatentDiffusion pipeline and writes a real PNG."""
    from PIL import Image

    from localai_tpu.backend import pb
    from localai_tpu.backend.image import ImageServicer

    s = ImageServicer()
    r = s.LoadModel(pb.ModelOptions(model=sd_ckpt), None)
    assert r.success, r.message
    dst = str(tmp_path / "out.png")
    r = s.GenerateImage(pb.GenerateImageRequest(
        positive_prompt="a tiny test", dst=dst, width=64, height=64,
        step=3, seed=1), None)
    assert r.success
    img = Image.open(dst)
    assert img.size == (64, 64)


@pytest.fixture(scope="module")
def sdxl_ckpt(tmp_path_factory):
    from fixtures import build_tiny_sdxl_checkpoint

    return build_tiny_sdxl_checkpoint(str(tmp_path_factory.mktemp("sdxl")))


def test_sdxl_second_encoder_parity_with_transformers(sdxl_ckpt):
    """Penultimate hidden state + projected pooled embedding vs the torch
    CLIPTextModelWithProjection — the exact tensors SDXL conditions on."""
    import torch
    from transformers import CLIPTextModelWithProjection

    from localai_tpu.models.latent_diffusion import (
        _component_config, _component_weights, clip_encode,
    )

    tm = CLIPTextModelWithProjection.from_pretrained(
        sdxl_ckpt + "/text_encoder_2")
    tm.eval()
    ids = [[7, 3, 99, 255, 12, 0, 0, 0]]   # 255 = EOS → pooled position 3
    with torch.no_grad():
        out = tm(torch.tensor(ids), output_hidden_states=True)
        ref_h = out.hidden_states[-2].numpy()
        ref_pooled = out.text_embeds.numpy()

    w = {k: jnp.asarray(v) for k, v in
         _component_weights(sdxl_ckpt, "text_encoder_2").items()}
    cfg = _component_config(sdxl_ckpt, "text_encoder_2")
    h, pooled = clip_encode(w, cfg, jnp.asarray(ids, jnp.int32),
                            penultimate=True, with_pooled=True)
    np.testing.assert_allclose(np.asarray(h), ref_h, rtol=2e-4, atol=2e-4)
    np.testing.assert_allclose(np.asarray(pooled), ref_pooled,
                               rtol=2e-4, atol=2e-4)


def test_sdxl_txt2img_end_to_end(sdxl_ckpt):
    """SDXL-geometry pipeline: dual encoders → depth-2 transformer UNet with
    text_time addition embedding → VAE. Deterministic, prompt-conditioned."""
    from localai_tpu.models.latent_diffusion import (
        LatentDiffusion, is_diffusers_checkpoint,
    )

    assert is_diffusers_checkpoint(sdxl_ckpt)
    pipe = LatentDiffusion(sdxl_ckpt)
    assert pipe.is_xl
    img1 = pipe.txt2img("a red cat", width=64, height=64, steps=3, seed=5)
    assert img1.shape == (64, 64, 3) and img1.dtype == np.uint8
    np.testing.assert_array_equal(
        img1, pipe.txt2img("a red cat", width=64, height=64, steps=3,
                           seed=5))
    img2 = pipe.txt2img("a blue dog", width=64, height=64, steps=3, seed=5)
    assert (img1 != img2).mean() > 0.05
