"""Telemetry subsystem tests (ISSUE 2): tracer/profiler units, engine-stage
instrumentation, trace integrity under concurrent multi-slot serving through
the full HTTP→gRPC→engine stack, and the disabled-path overhead guard.
"""
import json
import os
import threading
import time

import pytest
import requests
import yaml

from fixtures import tiny_checkpoint


# ------------------------------------------------------------------ units


def test_tracer_spans_parents_and_reparse():
    from localai_tpu.telemetry import Tracer, chrome_trace

    tr = Tracer(capacity=128)
    with tr.span("outer", kind="test") as outer:
        with tr.span("inner"):
            pass
    tr.add_complete("standalone", time.perf_counter() - 0.001)
    events = tr.events()
    assert {e["name"] for e in events} == {"inner", "outer", "standalone"}
    by_id = {e["args"]["span_id"]: e for e in events}
    inner = next(e for e in events if e["name"] == "inner")
    # parent resolves to the outer span
    assert by_id[inner["args"]["parent_id"]]["name"] == "outer"
    assert outer.sid == inner["args"]["parent_id"]
    # chrome-trace export re-parses and keeps every event well-formed
    dump = json.dumps(chrome_trace(events, {os.getpid(): "test"}))
    back = json.loads(dump)
    assert back["displayTimeUnit"] == "ms"
    for e in back["traceEvents"]:
        if e["ph"] == "X":
            assert e["dur"] >= 0 and e["ts"] > 0 and e["pid"] and e["tid"]


def test_tracer_ring_wraps_without_growing():
    from localai_tpu.telemetry import Tracer

    tr = Tracer(capacity=64)
    t0 = time.perf_counter()
    for i in range(500):
        tr.add_complete(f"s{i}", t0, dur_s=0.0)
    events = tr.events()
    assert len(events) == 64
    names = {e["name"] for e in events}
    # exactly the newest 64 survive the wrap
    assert names == {f"s{i}" for i in range(436, 500)}


def test_tracer_concurrent_writers():
    from localai_tpu.telemetry import Tracer

    tr = Tracer(capacity=4096)

    def writer(k):
        t0 = time.perf_counter()
        for i in range(200):
            tr.add_complete(f"w{k}-{i}", t0, dur_s=0.0)

    threads = [threading.Thread(target=writer, args=(k,)) for k in range(8)]
    [t.start() for t in threads]
    [t.join() for t in threads]
    events = tr.events()
    assert len(events) == 1600
    # span ids stay unique across racing writers (the count() atomicity)
    ids = [e["args"]["span_id"] for e in events]
    assert len(set(ids)) == len(ids)


def test_profiler_histogram_and_flat():
    from localai_tpu.telemetry import StepProfiler

    p = StepProfiler(fence=False, n_params=1_000_000, peak=1e12)
    for _ in range(10):
        p.record("decode_block", time.perf_counter() - 0.004, tokens=64)
    p.record("admit", time.perf_counter() - 0.001, tokens=8)
    r = p.report()
    st = r["stages"]["decode_block"]
    assert st["count"] == 10 and st["tokens"] == 640
    assert 0 < st["p50_ms"] <= 20
    assert sum(st["hist"]) == 10
    # mfu is COST-BACKED (ISSUE 13): None until set_costs supplies the
    # compiled variant's FLOPs; the 2·N·tokens analytic estimate is gone
    # (removed in ISSUE 16 after its one-release grace period)
    assert st["mfu"] is None
    assert "mfu_analytic_legacy" not in st
    p.set_costs({"decode_block": {"flops": 2e6, "bytes": 1e6}})
    st = p.report()["stages"]["decode_block"]
    assert st["mfu"] is not None and st["mfu"] > 0
    assert st["cost_flops"] == 2e6 and st["cost_bytes"] == 1e6
    assert abs(sum(s["share"] for s in r["stages"].values()) - 1.0) < 1e-6
    assert r["coverage"] > 0
    flat = p.flat()
    assert flat["prof_decode_block_count"] == 10.0
    assert flat["prof_admit_total_ms"] > 0
    assert flat["prof_decode_block_mfu"] > 0
    assert not any(k.endswith("mfu_analytic_legacy") for k in flat)


# ------------------------------------------------- engine instrumentation


@pytest.fixture(scope="module")
def ckpt(tmp_path_factory):
    return tiny_checkpoint(tmp_path_factory)


def _engine(ckpt, **ec_kw):
    from localai_tpu.engine import (
        Engine, EngineConfig, Tokenizer, load_config, load_params,
    )

    cfg = load_config(ckpt, dtype="float32")
    params = load_params(ckpt, cfg)
    tok = Tokenizer.from_dir(ckpt)
    return Engine(cfg, params, tok, EngineConfig(
        max_slots=4, max_context=128, prefill_buckets=(32, 64),
        prefill_chunk=64, **ec_kw)), tok


def _run(eng, tok, n_req=4, max_tokens=8):
    from localai_tpu.engine import GenRequest

    outs = [eng.submit(GenRequest(
        prompt_ids=tok.encode(f"request number {i} says"),
        max_tokens=max_tokens, ignore_eos=True))[1] for i in range(n_req)]
    while eng.step():
        pass
    finished = 0
    for q in outs:
        while not q.empty():
            if q.get_nowait().finished:
                finished += 1
    return finished


def test_engine_stage_spans_and_profile(ckpt):
    from localai_tpu import telemetry

    telemetry.set_trace_enabled(True)
    telemetry.set_profile_enabled(True)
    tracer = telemetry.tracer()
    tracer.clear()
    try:
        eng, tok = _engine(ckpt)
        assert eng._prof is not None and eng._tracer is not None
        finished = _run(eng, tok, n_req=4)
        assert finished == 4
        names = {e["name"] for e in tracer.events()}
        # the device-step stages the ISSUE names: admit, prefill-or-decode
        # fused dispatches, and the sample (sync+commit) stage
        assert "engine.admit" in names
        assert "engine.sample" in names
        assert names & {"engine.decode_loop", "engine.decode_block",
                        "engine.decode"}
        # one engine.request span per request, all closed, with ttft args
        reqs = [e for e in tracer.events() if e["name"] == "engine.request"]
        assert len(reqs) == 4
        for r in reqs:
            assert r["args"]["generated"] > 0
            assert r["args"]["ttft_ms"] is not None
            assert r["args"]["request_id"].startswith("rid-")
        prof = eng._prof.report()
        assert prof["stages"]["admit"]["count"] >= 1
        decode_stages = [s for s in prof["stages"]
                         if s in ("decode", "decode_block", "decode_loop")]
        assert decode_stages
        # fenced stage totals cover most of the busy window (the >=90%
        # wall-coverage acceptance, measured on the in-process engine)
        assert prof["coverage"] > 0.5
        assert prof["fenced"] is True
    finally:
        telemetry.set_trace_enabled(None)
        telemetry.set_profile_enabled(None)
        tracer.clear()


def test_tracing_disabled_is_inert_and_cheap(ckpt):
    """The overhead guard: with telemetry off the engine must hold no tracer
    or profiler, record nothing, and its step loop must stay within noise of
    itself — the instrumentation left on the hot path is one perf_counter
    read and a None-check per device dispatch."""
    from localai_tpu import telemetry

    telemetry.set_trace_enabled(False)
    telemetry.set_profile_enabled(False)
    try:
        eng, tok = _engine(ckpt)
        assert eng._prof is None and eng._tracer is None
        before = len(telemetry.chrome_events())
        _run(eng, tok, n_req=2, max_tokens=16)
        assert len(telemetry.chrome_events()) == before   # nothing recorded

        def timed():
            t0 = time.perf_counter()
            _run(eng, tok, n_req=2, max_tokens=32)
            return time.perf_counter() - t0

        timed()                      # warm
        disabled = min(timed() for _ in range(3))
        # enable spans (no fences) on the SAME engine: the recording path
        # itself must be cheap relative to a device dispatch
        eng._tracer = telemetry.tracer()
        eng._tracer.clear()
        enabled = min(timed() for _ in range(3))
        eng._tracer.clear()
        assert enabled < disabled * 2.0, (
            f"span recording too expensive: {enabled:.3f}s vs "
            f"{disabled:.3f}s disabled")
    finally:
        telemetry.set_trace_enabled(None)
        telemetry.set_profile_enabled(None)


# ------------------------------------- full-stack concurrent trace integrity


@pytest.fixture(scope="module")
def traced_stack(tmp_path_factory):
    """HTTP server + real backend subprocess with LOCALAI_TRACE/PROFILE on:
    the end-to-end path the /debug endpoints and request-id propagation
    need. Mirrors test_http_api's stack fixture."""
    import asyncio

    from aiohttp import web

    from localai_tpu.config import AppConfig, ModelConfigLoader
    from localai_tpu.core.manager import ModelManager
    from localai_tpu.server.http import API

    ckpt = tiny_checkpoint(tmp_path_factory)
    models = tmp_path_factory.mktemp("models-traced")
    (models / "tiny.yaml").write_text(yaml.safe_dump({
        "name": "tiny",
        "backend": "llm",
        "context_size": 128,
        "parallel": 4,
        "dtype": "float32",
        "prefill_buckets": [32, 64],
        "parameters": {"model": ckpt, "temperature": 0.0, "max_tokens": 8},
    }))

    import socket

    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()

    os.environ["LOCALAI_JAX_PLATFORM"] = "cpu"
    old_trace = os.environ.get("LOCALAI_TRACE")
    old_prof = os.environ.get("LOCALAI_PROFILE")
    os.environ["LOCALAI_TRACE"] = "1"    # backend subprocess inherits
    os.environ["LOCALAI_PROFILE"] = "1"
    app_cfg = AppConfig(address=f"127.0.0.1:{port}", models_path=str(models),
                        parallel_requests=4)
    configs = ModelConfigLoader(str(models))
    manager = ModelManager(app_cfg)
    api = API(app_cfg, configs, manager)

    loop = asyncio.new_event_loop()

    def run():
        asyncio.set_event_loop(loop)
        runner = web.AppRunner(api.app)
        loop.run_until_complete(runner.setup())
        site = web.TCPSite(runner, "127.0.0.1", port)
        loop.run_until_complete(site.start())
        loop.run_forever()

    t = threading.Thread(target=run, daemon=True)
    t.start()
    base = f"http://127.0.0.1:{port}"
    for _ in range(50):
        try:
            requests.get(base + "/healthz", timeout=1)
            break
        except requests.ConnectionError:
            time.sleep(0.1)
    yield base, manager
    manager.stop_all()
    loop.call_soon_threadsafe(loop.stop)
    for key, old in (("LOCALAI_TRACE", old_trace),
                     ("LOCALAI_PROFILE", old_prof)):
        if old is None:
            os.environ.pop(key, None)
        else:
            os.environ[key] = old


def _warm(base):
    """Ensure the backend is loaded and has served at least one request
    (tests in this module must not depend on execution order)."""
    r = requests.post(base + "/v1/chat/completions", json={
        "model": "tiny",
        "messages": [{"role": "user", "content": "warm up"}],
        "max_tokens": 4,
    }, timeout=300)
    assert r.status_code == 200, r.text


def test_concurrent_trace_integrity_http_grpc_engine(traced_stack):
    """N parallel chat requests: request ids round-trip HTTP→gRPC→engine,
    every exported span is closed (complete events only), parents resolve
    within their process, and the merged Chrome trace re-parses."""
    base, _ = traced_stack
    n = 4
    rids = [f"it-req-{i}" for i in range(n)]
    results = {}

    def fire(rid):
        r = requests.post(base + "/v1/chat/completions", json={
            "model": "tiny",
            "messages": [{"role": "user", "content": f"hello from {rid}"}],
            "max_tokens": 6,
        }, headers={"X-Request-Id": rid}, timeout=300)
        results[rid] = r

    threads = [threading.Thread(target=fire, args=(rid,)) for rid in rids]
    [t.start() for t in threads]
    [t.join() for t in threads]
    for rid, r in results.items():
        assert r.status_code == 200, r.text
        # the middleware echoes the propagated id back
        assert r.headers.get("X-Request-Id") == rid

    # the engine loop closes a request's span just after the final chunk is
    # streamed — give it a beat before snapshotting
    time.sleep(0.5)
    trace = requests.get(base + "/debug/trace", timeout=60).json()
    events = trace["traceEvents"]
    spans = [e for e in events if e.get("ph") == "X"]
    assert spans, "no spans exported"
    assert json.loads(json.dumps(trace))   # re-parses

    # request ids round-tripped into every layer's spans
    for layer in ("http /v1/chat/completions", "rpc.Predict",
                  "grpc.Predict", "engine.request"):
        seen = {e["args"].get("request_id") for e in spans
                if e["name"] == layer}
        assert set(rids) <= seen, f"{layer}: {seen}"

    # every span closed with a sane interval
    for e in spans:
        assert e["dur"] >= 0 and e["ts"] > 0

    # parents resolve within their process
    by_proc = {}
    for e in spans:
        by_proc.setdefault(e["pid"], set()).add(e["args"]["span_id"])
    for e in spans:
        parent = e["args"].get("parent_id")
        if parent:
            assert parent in by_proc[e["pid"]], e

    # engine.request nests under its grpc.Predict span (trace_parent link)
    grpc_ids = {e["args"]["span_id"] for e in spans
                if e["name"] == "grpc.Predict"}
    engine_reqs = [e for e in spans if e["name"] == "engine.request"
                   and e["args"].get("request_id") in rids]
    assert engine_reqs
    assert all(e["args"].get("parent_id") in grpc_ids for e in engine_reqs)

    # device stages made it across the process boundary
    names = {e["name"] for e in spans}
    assert "engine.admit" in names and "engine.sample" in names


def test_debug_profile_and_prometheus_stage_series(traced_stack):
    base, _ = traced_stack
    _warm(base)
    prof = requests.get(base + "/debug/profile", timeout=60).json()
    assert prof["profiling_enabled"] is True
    stages = prof["models"]["tiny"]["stages"]
    assert "admit" in stages and "sample" in stages
    assert any(s in stages for s in ("decode", "decode_block",
                                     "decode_loop"))
    assert stages["admit"]["count"] >= 1
    assert prof["models"]["tiny"]["coverage"] > 0

    # stage breakdown sums to ~100% of the busy window's stage time
    assert abs(sum(s["share"] for s in stages.values()) - 1.0) < 1e-6

    # Prometheus series appear after a scrape
    m = requests.get(base + "/metrics", timeout=60).text
    assert "localai_engine_stage_seconds_total" in m
    assert 'stage="admit"' in m


def test_util_trace_cli(traced_stack, tmp_path, capsys):
    """`local-ai util trace <addr>` writes a Chrome-trace file and prints
    the stage table."""
    from localai_tpu.cli import main as cli_main

    base, _ = traced_stack
    _warm(base)
    out = tmp_path / "trace.json"
    rc = cli_main(["util", "trace", base, "--out", str(out)])
    assert rc == 0
    dump = json.loads(out.read_text())
    assert any(e.get("ph") == "X" for e in dump["traceEvents"])
    printed = capsys.readouterr().out
    assert "events" in printed
    assert "admit" in printed   # the stage table rendered
