"""Real-TPU lowering tests (the round-3 gap: kernels that pass in interpreter
mode but die in Mosaic lowering on hardware).

Skipped on the CPU harness; run with `LOCALAI_TPU_TESTS=1 python -m pytest
tests/test_tpu_real.py` on a machine with a TPU attached. The driver's bench
exercises the same compile path, but these give targeted failures.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

pytestmark = pytest.mark.skipif(
    jax.default_backend() != "tpu",
    reason="requires a real TPU (LOCALAI_TPU_TESTS=1)",
)


def _bf16(key, shape):
    return jax.random.normal(jax.random.PRNGKey(key), shape, jnp.bfloat16)


@pytest.mark.parametrize("H,KVH,D", [(8, 4, 64), (8, 8, 128), (32, 8, 128)])
def test_flash_prefill_lowers_and_matches(H, KVH, D):
    from localai_tpu.ops.attention import mha_prefill
    from localai_tpu.ops.pallas import flash_prefill

    B, S = 2, 256
    q, k, v = _bf16(0, (B, S, H, D)), _bf16(1, (B, S, KVH, D)), _bf16(2, (B, S, KVH, D))
    lengths = jnp.array([S, 100], jnp.int32)
    out = flash_prefill(q, k, v, lengths)
    ref = mha_prefill(q, k, v, lengths)
    for b in range(B):
        n = int(lengths[b])
        np.testing.assert_allclose(np.asarray(out[b, :n], np.float32),
                                   np.asarray(ref[b, :n], np.float32),
                                   rtol=3e-2, atol=3e-2)


@pytest.mark.parametrize("H,KVH,D", [(8, 4, 64), (8, 8, 128), (32, 8, 128)])
def test_ragged_decode_lowers_and_matches(H, KVH, D):
    from localai_tpu.ops.attention import mha_decode
    from localai_tpu.ops.pallas import ragged_decode

    B, T = 4, 1024
    q = _bf16(3, (B, 1, H, D))
    kc, vc = _bf16(4, (B, KVH, T, D)), _bf16(5, (B, KVH, T, D))
    lengths = jnp.array([1, 100, 777, T], jnp.int32)
    out = ragged_decode(q, kc, vc, lengths)
    ref = mha_decode(q, kc, vc, lengths)
    np.testing.assert_allclose(np.asarray(out, np.float32),
                               np.asarray(ref, np.float32),
                               rtol=3e-2, atol=3e-2)


def test_pallas_probe_reports_ok():
    from localai_tpu.ops.pallas import pallas_works

    assert pallas_works()


def test_model_decode_step_compiles_on_tpu():
    """The engine's hot path — decode_step through the Pallas selector — must
    compile and run on the chip (this is exactly where BENCH_r03 died)."""
    from localai_tpu.models.llama import (
        LlamaConfig, decode_step, init_kv_cache, init_params, prefill,
    )
    from localai_tpu.ops.rope import rope_table

    cfg = LlamaConfig(vocab_size=256, hidden_size=256, intermediate_size=512,
                      num_layers=2, num_heads=4, num_kv_heads=2, head_dim=64,
                      max_position=256)
    params = init_params(cfg, jax.random.PRNGKey(0))
    cos, sin = rope_table(cfg.rope, 256)
    kc, vc = init_kv_cache(cfg, 2, 256)
    tokens = jnp.array([[1, 2, 3, 4]], jnp.int32)
    logits, kc, vc = prefill(params, cfg, tokens, jnp.array([4], jnp.int32),
                             cos, sin, kc, vc, jnp.array([0], jnp.int32))
    assert np.isfinite(np.asarray(logits)).all()
    step_tokens = jnp.array([5, 0], jnp.int32)
    step_lengths = jnp.array([4, 0], jnp.int32)
    dlogits, _, _ = decode_step(params, cfg, step_tokens, step_lengths,
                                cos, sin, kc, vc)
    assert np.isfinite(np.asarray(dlogits[0])).all()


@pytest.mark.parametrize("H,KVH,D", [(8, 4, 64), (32, 8, 128)])
def test_ragged_decode_q8_lowers_and_matches(H, KVH, D):
    from localai_tpu.ops.attention import mha_decode
    from localai_tpu.ops.kvcache import QuantKV, dequant, quantize_tokens
    from localai_tpu.ops.pallas import ragged_decode_q8

    B, T = 4, 1024
    q = _bf16(6, (B, 1, H, D))
    kd = jax.random.normal(jax.random.PRNGKey(7), (B, KVH, T, D))
    vd = jax.random.normal(jax.random.PRNGKey(8), (B, KVH, T, D))
    kq, ks = quantize_tokens(kd)
    vq, vs = quantize_tokens(vd)
    kc = QuantKV(kq, ks.reshape(B, KVH, T // 128, 128))
    vc = QuantKV(vq, vs.reshape(B, KVH, T // 128, 128))
    lengths = jnp.array([1, 100, 777, T], jnp.int32)
    out = ragged_decode_q8(q, kc.q, kc.s, vc.q, vc.s, lengths)
    ref = mha_decode(q, dequant(kc), dequant(vc), lengths)
    np.testing.assert_allclose(np.asarray(out, np.float32),
                               np.asarray(ref, np.float32),
                               rtol=3e-2, atol=3e-2)


def test_decode_block_runs_on_tpu():
    """The fused multi-step decode program (EngineConfig.decode_block) must
    compile and run on the chip — it is the serving hot loop."""
    from localai_tpu.engine import Engine, EngineConfig
    from localai_tpu.engine.engine import GenRequest, SamplingParams
    from localai_tpu.models.llama import LlamaConfig, init_params

    cfg = LlamaConfig(vocab_size=256, hidden_size=256, intermediate_size=512,
                      num_layers=2, num_heads=4, num_kv_heads=2, head_dim=64,
                      max_position=256)
    params = init_params(cfg, jax.random.PRNGKey(0))
    eng = Engine(cfg, params, None, EngineConfig(
        max_slots=2, max_context=128, prefill_buckets=(16,),
        prefill_chunk=16, decode_block=8))
    eng.start()
    try:
        _, q = eng.submit(GenRequest(
            prompt_ids=[1, 2, 3], max_tokens=24, ignore_eos=True,
            params=SamplingParams(temperature=0.0, seed=1)))
        n = 0
        while True:
            o = q.get(timeout=120)
            n += 1
            if o.finished:
                break
        assert n == 24
    finally:
        eng.stop()
