"""Vector store tests: native-lib correctness vs numpy cosine (reference
tests/integration/stores_test.go:34-60) + gRPC servicer roundtrip."""
import numpy as np
import pytest


@pytest.fixture()
def store():
    from localai_tpu.stores import LocalStore

    return LocalStore(dim=32)


def test_set_get_delete(store):
    rng = np.random.default_rng(0)
    keys = rng.normal(size=(5, 32)).astype(np.float32)
    vals = [f"value-{i}".encode() for i in range(5)]
    store.set(keys, vals)
    assert len(store) == 5
    got = store.get(keys[1:3])
    assert got == [b"value-1", b"value-2"]
    assert store.get(rng.normal(size=(1, 32)).astype(np.float32)) == [None]
    assert store.delete(keys[:2]) == 2
    assert len(store) == 3
    assert store.get(keys[:1]) == [None]


def test_upsert_overwrites(store):
    k = np.ones((1, 32), np.float32)
    store.set(k, [b"a"])
    store.set(k, [b"b"])
    assert len(store) == 1
    assert store.get(k) == [b"b"]


def test_find_matches_numpy_cosine(store):
    rng = np.random.default_rng(1)
    keys = rng.normal(size=(200, 32)).astype(np.float32)
    vals = [str(i).encode() for i in range(200)]
    store.set(keys, vals)
    q = rng.normal(size=(32,)).astype(np.float32)

    norm = keys / np.linalg.norm(keys, axis=1, keepdims=True)
    ref_sims = norm @ (q / np.linalg.norm(q))
    ref_order = np.argsort(-ref_sims)[:10]

    found_keys, found_vals, sims = store.find(q, 10)
    got = [int(v) for v in found_vals]
    assert got == ref_order.tolist()
    np.testing.assert_allclose(sims, ref_sims[ref_order], rtol=1e-5, atol=1e-5)
    # returned keys are the original (unnormalized) vectors
    np.testing.assert_allclose(found_keys, keys[ref_order], rtol=1e-6, atol=0)


def test_find_after_delete(store):
    keys = np.eye(32, dtype=np.float32)[:4]
    store.set(keys, [b"0", b"1", b"2", b"3"])
    store.delete(keys[:1])
    _, vals, sims = store.find(keys[0], 4)
    assert b"0" not in vals and len(vals) == 3


def test_store_grpc_roundtrip():
    from localai_tpu.backend.client import BackendClient
    from localai_tpu.backend.server import serve

    server, servicer, port = serve("127.0.0.1:0", "store")
    try:
        c = BackendClient(f"127.0.0.1:{port}")
        assert c.wait_ready(attempts=20, sleep=0.1)
        keys = [[1.0, 0.0], [0.0, 1.0], [0.7, 0.7]]
        c.stores_set(keys, [b"x", b"y", b"diag"])
        got = c.stores_get([[1.0, 0.0]])
        assert got.values[0].bytes == b"x"
        found = c.stores_find([1.0, 0.1], 2)
        assert found.values[0].bytes == b"x"
        assert found.similarities[0] > found.similarities[1]
        c.stores_delete([[1.0, 0.0]])
        assert len(c.stores_get([[1.0, 0.0]]).values) == 0
        c.close()
    finally:
        server.stop(grace=1)
