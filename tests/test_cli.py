"""CLI one-shot inference subcommands (reference core/cli/tts.go +
transcript.go): real backend subprocesses, real files."""
import json
import os
import wave

import pytest

from localai_tpu.cli import main


@pytest.fixture(scope="module")
def whisper_models_dir(tmp_path_factory):
    """models dir with a tiny whisper checkpoint named default-whisper."""
    import torch
    from transformers import WhisperConfig, WhisperForConditionalGeneration

    root = tmp_path_factory.mktemp("cli-models")
    d = root / "default-whisper"
    torch.manual_seed(0)
    cfg = WhisperConfig(
        vocab_size=51865, d_model=64, encoder_layers=2, decoder_layers=2,
        encoder_attention_heads=4, decoder_attention_heads=4,
        encoder_ffn_dim=128, decoder_ffn_dim=128, num_mel_bins=80,
        max_source_positions=1500, max_target_positions=64)
    m = WhisperForConditionalGeneration(cfg)
    m.generation_config.forced_decoder_ids = None
    m.generation_config.suppress_tokens = None
    m.generation_config.begin_suppress_tokens = None
    m.save_pretrained(str(d), safe_serialization=True)
    return str(root)


def test_cli_version(capsys):
    assert main(["version"]) == 0
    assert capsys.readouterr().out.strip()


def test_cli_tts_writes_wav(tmp_path, monkeypatch):
    monkeypatch.setenv("LOCALAI_JAX_PLATFORM", "cpu")
    out = tmp_path / "speech.wav"
    rc = main(["tts", "hello from the cli", "--output-file", str(out),
               "--models-path", str(tmp_path)])
    assert rc == 0
    with wave.open(str(out)) as w:
        assert w.getframerate() == 16000
        assert w.getnframes() > 1000


def test_cli_soundgeneration_writes_wav(tmp_path, monkeypatch):
    """`soundgeneration` wraps the existing SoundGeneration RPC (reference
    core/cli/soundgeneration.go; VERDICT Missing #7)."""
    monkeypatch.setenv("LOCALAI_JAX_PLATFORM", "cpu")
    out = tmp_path / "rain.wav"
    rc = main(["soundgeneration", "rain on a tin roof", "--duration", "1.0",
               "--output-file", str(out), "--models-path", str(tmp_path)])
    assert rc == 0
    with wave.open(str(out)) as w:
        assert w.getframerate() == 16000
        assert w.getnframes() >= 16000  # >= the requested 1 s


def test_cli_transcript_formats(tmp_path, monkeypatch, whisper_models_dir,
                                capsys):
    monkeypatch.setenv("LOCALAI_JAX_PLATFORM", "cpu")
    wav = tmp_path / "in.wav"
    rc = main(["tts", "testing one two three", "--output-file", str(wav),
               "--models-path", str(tmp_path)])
    assert rc == 0
    capsys.readouterr()
    rc = main(["transcript", str(wav), "--model", "default-whisper",
               "--models-path", whisper_models_dir,
               "--output-format", "json"])
    assert rc == 0
    out = capsys.readouterr().out
    payload = json.loads(out[out.index("{"):])
    assert "text" in payload and "segments" in payload


def test_util_hf_info_and_fits(tmp_path):
    import json
    import subprocess
    import sys

    cfg = {"architectures": ["LlamaForCausalLM"], "vocab_size": 1000,
           "hidden_size": 64, "intermediate_size": 128,
           "num_hidden_layers": 2, "num_attention_heads": 4,
           "num_key_value_heads": 2, "head_dim": 16,
           "max_position_embeddings": 256, "rms_norm_eps": 1e-5}
    (tmp_path / "config.json").write_text(json.dumps(cfg))
    env = dict(__import__("os").environ)
    repo = __import__("os").path.dirname(
        __import__("os").path.dirname(__import__("os").path.abspath(__file__)))
    env["PYTHONPATH"] = repo

    out = subprocess.run(
        [sys.executable, "-m", "localai_tpu.cli", "util", "hf-info",
         str(tmp_path)], capture_output=True, text=True, timeout=120, env=env)
    assert out.returncode == 0, out.stderr
    info = json.loads(out.stdout)
    assert info["layers"] == 2 and info["parameters"] > 0

    out = subprocess.run(
        [sys.executable, "-m", "localai_tpu.cli", "util", "fits",
         str(tmp_path), "--hbm-gb", "16"],
        capture_output=True, text=True, timeout=120, env=env)
    assert out.returncode == 0, out.stderr
    fit = json.loads(out.stdout)
    assert fit["fits"] is True
