"""Flux-geometry rectified-flow pipeline (models/flux.py): T5 encoder parity
vs transformers, and the end-to-end txt2img path over the FluxPipeline
checkpoint layout (reference: diffusers backend FluxPipeline branch +
stablediffusion-ggml's flux support)."""
import jax.numpy as jnp
import numpy as np
import pytest

from fixtures import build_tiny_flux_checkpoint


@pytest.fixture(scope="module")
def flux_ckpt(tmp_path_factory):
    return build_tiny_flux_checkpoint(str(tmp_path_factory.mktemp("flux")))


def test_t5_encoder_parity_with_transformers(flux_ckpt):
    """t5_encode (gated-gelu v1.1 geometry, relative-position bias) must
    match the torch T5EncoderModel last_hidden_state."""
    import torch
    from transformers import T5EncoderModel

    from localai_tpu.models.flux import t5_encode
    from localai_tpu.models.latent_diffusion import (
        _component_config, _component_weights,
    )

    tm = T5EncoderModel.from_pretrained(flux_ckpt + "/text_encoder_2")
    tm.eval()
    ids = [[5, 9, 2, 44, 100, 1, 0, 0]]
    with torch.no_grad():
        ref = tm(torch.tensor(ids)).last_hidden_state.numpy()

    w = {k: jnp.asarray(v) for k, v in
         _component_weights(flux_ckpt, "text_encoder_2").items()}
    cfg = _component_config(flux_ckpt, "text_encoder_2")
    out = t5_encode(w, cfg, jnp.asarray(ids, jnp.int32))
    np.testing.assert_allclose(np.asarray(out), ref, rtol=3e-4, atol=3e-4)


def test_flux_txt2img_end_to_end(flux_ckpt):
    """CLIP pooled + T5 ctx → MMDiT euler flow → VAE decode → uint8 image;
    deterministic per seed, conditioned on the prompt."""
    from localai_tpu.models.flux import FluxPipeline, is_flux_checkpoint

    assert is_flux_checkpoint(flux_ckpt)
    pipe = FluxPipeline(flux_ckpt)
    img1 = pipe.txt2img("a red cat", width=32, height=32, steps=3, seed=7)
    assert img1.shape == (32, 32, 3) and img1.dtype == np.uint8
    np.testing.assert_array_equal(
        img1, pipe.txt2img("a red cat", width=32, height=32, steps=3,
                           seed=7))
    img2 = pipe.txt2img("a blue dog", width=32, height=32, steps=3, seed=7)
    assert (img1 != img2).mean() > 0.05


def test_image_backend_serves_flux(flux_ckpt, tmp_path):
    """The image servicer routes FluxPipeline checkpoints automatically."""
    from localai_tpu.backend.client import BackendClient
    from localai_tpu.backend.server import serve

    server, servicer, port = serve("127.0.0.1:0", "image")
    try:
        client = BackendClient(f"127.0.0.1:{port}")
        assert client.wait_ready(attempts=20, sleep=0.1)
        r = client.load_model(model=flux_ckpt)
        assert r.success, r.message
        dst = str(tmp_path / "flux.png")
        res = client.generate_image(
            positive_prompt="a tiny test", dst=dst, width=32, height=32,
            step=2, seed=1)
        assert res.success, res.message
        from PIL import Image

        with Image.open(dst) as im:
            assert im.size == (32, 32)
        client.close()
    finally:
        server.stop(grace=1)
