"""HBM fit estimator (gguf-parser VRAM role) + audio transcode helper."""
import numpy as np
import pytest

from localai_tpu.models.llama import LlamaConfig
from localai_tpu.system.memory import estimate, param_count


def test_param_count_8b_geometry():
    cfg = LlamaConfig(vocab_size=128256, hidden_size=4096,
                      intermediate_size=14336, num_layers=32, num_heads=32,
                      num_kv_heads=8, head_dim=128, tie_embeddings=False)
    n = param_count(cfg)
    assert 7.9e9 < n < 8.1e9        # Llama-3.1-8B ≈ 8.03B


def test_param_count_moe():
    dense = LlamaConfig(vocab_size=1000, hidden_size=64,
                        intermediate_size=128, num_layers=2, num_heads=4,
                        num_kv_heads=4, head_dim=16)
    moe = LlamaConfig(vocab_size=1000, hidden_size=64, intermediate_size=128,
                      num_layers=2, num_heads=4, num_kv_heads=4, head_dim=16,
                      num_experts=4)
    # the extra (E-1) expert MLPs per layer, plus the router
    expected_delta = 2 * ((4 - 1) * 3 * 64 * 128 + 64 * 4)
    assert param_count(moe) - param_count(dense) == expected_delta


def test_estimate_fits_and_not():
    cfg = LlamaConfig(vocab_size=128256, hidden_size=4096,
                      intermediate_size=14336, num_layers=32, num_heads=32,
                      num_kv_heads=8, head_dim=128, tie_embeddings=False)
    # 8B bf16 on a 16GB chip: does not fit
    e = estimate(cfg, slots=8, context=1024, dtype="bfloat16",
                 hbm_bytes=16 << 30)
    assert e.fits is False
    # 8B int8 + int8 KV: fits
    e2 = estimate(cfg, slots=16, context=1024, dtype="int8",
                  cache_type="int8", hbm_bytes=16 << 30)
    assert e2.fits is True
    # same slot count: int8 KV ≈ half the dense bytes (+ scale overhead)
    e3 = estimate(cfg, slots=8, context=1024, dtype="int8",
                  cache_type="int8", hbm_bytes=16 << 30)
    assert e3.kv_cache_bytes < 0.6 * e.kv_cache_bytes
    d = e2.to_dict()
    assert d["fits"] is True and d["total_bytes"] > 8 << 30


def test_estimate_unknown_hbm():
    cfg = LlamaConfig(vocab_size=100, hidden_size=32, intermediate_size=64,
                      num_layers=1, num_heads=2, num_kv_heads=2, head_dim=16)
    e = estimate(cfg, slots=1, context=64, hbm_bytes=None)
    # on the CPU test harness there is no accelerator: fits is unknown
    assert e.fits is None or isinstance(e.fits, bool)


def test_transcode_wav_roundtrip(tmp_path):
    from localai_tpu.audio.pcm import write_wav
    from localai_tpu.audio.transcode import to_pcm16k

    t = np.arange(8000) / 8000.0
    audio = (0.3 * np.sin(2 * np.pi * 220 * t)).astype(np.float32)
    p = str(tmp_path / "a.wav")
    write_wav(p, audio, 8000)           # 8 kHz source → resampled to 16 kHz
    out = to_pcm16k(p)
    assert abs(len(out) - 16000) < 50
    assert np.isfinite(out).all()


def test_transcode_non_wav_requires_ffmpeg(tmp_path):
    from localai_tpu.audio.transcode import ffmpeg_available, to_pcm16k

    p = tmp_path / "x.mp3"
    p.write_bytes(b"\xff\xfbnot really an mp3")
    if ffmpeg_available():
        with pytest.raises(RuntimeError, match="ffmpeg failed"):
            to_pcm16k(str(p))
    else:
        with pytest.raises(RuntimeError, match="ffmpeg"):
            to_pcm16k(str(p))
