"""Audio backends over the gRPC contract: whisper transcription (real tiny
checkpoint), VAD RPC, TTS + sound generation WAV output."""
import numpy as np
import pytest

from localai_tpu.audio.pcm import read_wav, write_wav


@pytest.fixture(scope="module")
def tone_wav(tmp_path_factory):
    d = tmp_path_factory.mktemp("audio")
    rate = 16000
    rng = np.random.default_rng(0)
    silence = 0.001 * rng.normal(size=rate // 2)
    tone = 0.4 * np.sin(2 * np.pi * 440 * np.arange(rate) / rate)
    audio = np.concatenate([silence, tone, silence]).astype(np.float32)
    p = str(d / "tone.wav")
    write_wav(p, audio, rate)
    return p


@pytest.fixture(scope="module")
def whisper_served(tmp_path_factory):
    import torch
    from transformers import WhisperConfig, WhisperForConditionalGeneration

    d = str(tmp_path_factory.mktemp("whisper-srv"))
    torch.manual_seed(0)
    cfg = WhisperConfig(
        vocab_size=51865, d_model=64, encoder_layers=2, decoder_layers=2,
        encoder_attention_heads=4, decoder_attention_heads=4,
        encoder_ffn_dim=128, decoder_ffn_dim=128, num_mel_bins=80,
        max_source_positions=1500, max_target_positions=64)
    m = WhisperForConditionalGeneration(cfg)
    m.generation_config.forced_decoder_ids = None
    m.generation_config.suppress_tokens = None
    m.generation_config.begin_suppress_tokens = None
    m.save_pretrained(d, safe_serialization=True)

    from localai_tpu.backend.client import BackendClient
    from localai_tpu.backend.server import serve

    server, servicer, port = serve("127.0.0.1:0", "whisper")
    client = BackendClient(f"127.0.0.1:{port}")
    assert client.wait_ready(attempts=20, sleep=0.1)
    r = client.load_model(model=d)
    assert r.success, r.message
    yield client
    client.close()
    server.stop(grace=1)


def test_transcription_rpc(whisper_served, tone_wav):
    r = whisper_served.transcribe(dst=tone_wav)
    assert len(r.segments) == 1            # one VAD speech span
    seg = r.segments[0]
    assert 0.3 < seg.start / 1e9 < 0.8
    assert len(seg.tokens) > 0             # random model → some tokens


def test_vad_rpc(whisper_served):
    rate = 16000
    rng = np.random.default_rng(2)
    audio = np.concatenate([
        0.001 * rng.normal(size=rate),
        0.5 * np.sin(2 * np.pi * 300 * np.arange(rate) / rate),
        0.001 * rng.normal(size=rate),
    ]).astype(np.float32)
    r = whisper_served.vad(audio.tolist())
    assert len(r.segments) == 1
    assert 0.8 < r.segments[0].start < 1.3


def test_tts_rpc(tmp_path):
    from localai_tpu.backend.client import BackendClient
    from localai_tpu.backend.server import serve

    server, _, port = serve("127.0.0.1:0", "tts")
    try:
        c = BackendClient(f"127.0.0.1:{port}")
        assert c.wait_ready(attempts=20, sleep=0.1)
        assert c.load_model(model="dsp").success
        dst = str(tmp_path / "out.wav")
        r = c.tts(text="hello world", dst=dst)
        assert r.success
        audio, rate = read_wav(dst)
        assert rate == 16000 and len(audio) > 16000 * 0.5
        assert np.abs(audio).max() > 0.1
        # sound generation
        dst2 = str(tmp_path / "sound.wav")
        assert c.sound_generation(text="rain", duration=1.0, dst=dst2).success
        a2, _ = read_wav(dst2)
        assert abs(len(a2) - 16000) < 100
        c.close()
    finally:
        server.stop(grace=1)
