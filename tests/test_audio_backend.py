"""Audio backends over the gRPC contract: whisper transcription (real tiny
checkpoint), VAD RPC, TTS + sound generation WAV output."""
import numpy as np
import pytest

from localai_tpu.audio.pcm import read_wav, write_wav


@pytest.fixture(scope="module")
def speech_wav(tmp_path_factory):
    """0.5s silence + ~1s synthesized speech + 0.5s silence (the VAD is
    model-based now — tones no longer count as speech)."""
    from localai_tpu.audio.tts import synthesize

    d = tmp_path_factory.mktemp("audio")
    rate = 16000
    rng = np.random.default_rng(0)
    silence = (0.001 * rng.normal(size=rate // 2)).astype(np.float32)
    speech = synthesize("hello there how are you today", voice="default",
                        language="en").astype(np.float32)[: rate]
    audio = np.concatenate([silence, speech, silence]).astype(np.float32)
    p = str(d / "speech.wav")
    write_wav(p, audio, rate)
    return p


@pytest.fixture(scope="module")
def whisper_served(tmp_path_factory):
    import torch
    from transformers import WhisperConfig, WhisperForConditionalGeneration

    d = str(tmp_path_factory.mktemp("whisper-srv"))
    torch.manual_seed(0)
    cfg = WhisperConfig(
        vocab_size=51865, d_model=64, encoder_layers=2, decoder_layers=2,
        encoder_attention_heads=4, decoder_attention_heads=4,
        encoder_ffn_dim=128, decoder_ffn_dim=128, num_mel_bins=80,
        max_source_positions=1500, max_target_positions=64)
    m = WhisperForConditionalGeneration(cfg)
    m.generation_config.forced_decoder_ids = None
    m.generation_config.suppress_tokens = None
    m.generation_config.begin_suppress_tokens = None
    m.save_pretrained(d, safe_serialization=True)

    from localai_tpu.backend.client import BackendClient
    from localai_tpu.backend.server import serve

    server, servicer, port = serve("127.0.0.1:0", "whisper")
    client = BackendClient(f"127.0.0.1:{port}")
    assert client.wait_ready(attempts=20, sleep=0.1)
    r = client.load_model(model=d)
    assert r.success, r.message
    yield client
    client.close()
    server.stop(grace=1)


def test_transcription_rpc(whisper_served, speech_wav):
    r = whisper_served.transcribe(dst=speech_wav)
    assert len(r.segments) >= 1            # VAD speech span(s)
    seg = r.segments[0]
    assert 0.0 <= seg.start / 1e9 < 0.9
    assert len(seg.tokens) > 0             # random model → some tokens


def test_vad_rpc(whisper_served):
    from localai_tpu.audio.tts import synthesize

    rate = 16000
    rng = np.random.default_rng(2)
    speech = synthesize("good morning to you", voice="default",
                        language="en").astype(np.float32)[: rate]
    audio = np.concatenate([
        0.001 * rng.normal(size=rate).astype(np.float32),
        speech,
        0.001 * rng.normal(size=rate).astype(np.float32),
    ]).astype(np.float32)
    r = whisper_served.vad(audio.tolist())
    assert len(r.segments) >= 1
    assert 0.6 < r.segments[0].start < 1.4


def test_tts_rpc(tmp_path):
    from localai_tpu.backend.client import BackendClient
    from localai_tpu.backend.server import serve

    server, _, port = serve("127.0.0.1:0", "tts")
    try:
        c = BackendClient(f"127.0.0.1:{port}")
        assert c.wait_ready(attempts=20, sleep=0.1)
        assert c.load_model(model="dsp").success
        dst = str(tmp_path / "out.wav")
        r = c.tts(text="hello world", dst=dst)
        assert r.success
        audio, rate = read_wav(dst)
        assert rate == 16000 and len(audio) > 16000 * 0.5
        assert np.abs(audio).max() > 0.1
        # sound generation
        dst2 = str(tmp_path / "sound.wav")
        assert c.sound_generation(text="rain", duration=1.0, dst=dst2).success
        a2, _ = read_wav(dst2)
        assert abs(len(a2) - 16000) < 100
        c.close()
    finally:
        server.stop(grace=1)


def test_neural_vad_beats_energy_on_tones():
    """The learned VAD (silero role) must reject a loud pure tone that the
    adaptive-energy fallback flags as speech — the exact failure mode a
    model-based detector exists to fix."""
    import numpy as np

    from localai_tpu.audio.nvad import detect_segments_model, load_params
    from localai_tpu.audio.tts import synthesize

    params = load_params()
    assert params is not None, "vad_model.npz missing from the package"

    t = np.arange(int(1.5 * 16000)) / 16000.0
    tone = (0.4 * np.sin(2 * np.pi * 440 * t)).astype(np.float32)
    assert detect_segments_model(tone, params=params) == []

    speech = synthesize("hello there how are you", voice="default",
                        language="en").astype(np.float32)
    segs = detect_segments_model(speech, params=params)
    assert len(segs) >= 1
    total = sum(e - s for s, e in segs)
    assert total > 0.3 * len(speech) / 16000.0


def test_vad_auto_prefers_model():
    import numpy as np

    from localai_tpu.audio.vad import detect_segments, detect_segments_auto

    # bursty tone: quiet floor + loud tone bursts — the adaptive-energy
    # fallback fires on it, the learned model must not
    rate = 16000
    rng = np.random.default_rng(3)
    quiet = (0.001 * rng.normal(size=rate)).astype(np.float32)
    t = np.arange(rate) / rate
    burst = (0.5 * np.sin(2 * np.pi * 300 * t)).astype(np.float32)
    audio = np.concatenate([quiet, burst, quiet]).astype(np.float32)
    assert len(detect_segments(audio)) >= 1
    assert detect_segments_auto(audio) == []
