"""HTTP integration tests — the reference's app_test.go tier (SURVEY §4):
a REAL server (aiohttp in a thread), REAL backend subprocesses via the
ModelManager, driven over the wire with `requests`.
"""
import asyncio
import json
import os
import signal
import threading
import time

import numpy as np
import pytest
import requests
import yaml

from fixtures import tiny_checkpoint


def _free_port():
    import socket

    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    p = s.getsockname()[1]
    s.close()
    return p


@pytest.fixture(scope="module")
def stack(tmp_path_factory):
    """models dir + config loader + manager + API server on a real port."""
    from aiohttp import web

    from localai_tpu.config import AppConfig, ModelConfigLoader
    from localai_tpu.core.manager import ModelManager
    from localai_tpu.server.http import API

    ckpt = tiny_checkpoint(tmp_path_factory)
    models = tmp_path_factory.mktemp("models")

    # tiny whisper for the realtime transcription pipeline
    import torch
    from transformers import WhisperConfig, WhisperForConditionalGeneration

    wdir = str(tmp_path_factory.mktemp("whisper-ckpt"))
    torch.manual_seed(0)
    wcfg = WhisperConfig(
        vocab_size=51865, d_model=64, encoder_layers=2, decoder_layers=2,
        encoder_attention_heads=4, decoder_attention_heads=4,
        encoder_ffn_dim=128, decoder_ffn_dim=128, num_mel_bins=80,
        max_source_positions=1500, max_target_positions=64)
    wm = WhisperForConditionalGeneration(wcfg)
    wm.generation_config.forced_decoder_ids = None
    wm.generation_config.suppress_tokens = None
    wm.generation_config.begin_suppress_tokens = None
    wm.save_pretrained(wdir, safe_serialization=True)
    (models / "whisper-tiny.yaml").write_text(yaml.safe_dump({
        "name": "whisper-tiny",
        "backend": "whisper",
        "parameters": {"model": wdir},
    }))

    (models / "tiny.yaml").write_text(yaml.safe_dump({
        "name": "tiny",
        "backend": "llm",
        "context_size": 128,
        "parallel": 2,
        "dtype": "float32",
        "embeddings": True,
        "prefill_buckets": [32, 64],
        "parameters": {
            "model": ckpt,
            "temperature": 0.0,
            "max_tokens": 8,
        },
        "pipeline": {
            "llm": "tiny",
            "tts": "default-tts",
            "transcription": "whisper-tiny",
        },
    }))

    os.environ["LOCALAI_JAX_PLATFORM"] = "cpu"
    port = _free_port()
    app_cfg = AppConfig(address=f"127.0.0.1:{port}",
                        models_path=str(models), parallel_requests=2)
    configs = ModelConfigLoader(str(models))
    manager = ModelManager(app_cfg)
    api = API(app_cfg, configs, manager)

    loop = asyncio.new_event_loop()

    def run():
        asyncio.set_event_loop(loop)
        runner = web.AppRunner(api.app)
        loop.run_until_complete(runner.setup())
        site = web.TCPSite(runner, "127.0.0.1", port)
        loop.run_until_complete(site.start())
        loop.run_forever()

    t = threading.Thread(target=run, daemon=True)
    t.start()
    base = f"http://127.0.0.1:{port}"
    for _ in range(50):
        try:
            requests.get(base + "/healthz", timeout=1)
            break
        except requests.ConnectionError:
            time.sleep(0.1)
    yield base, manager
    manager.stop_all()
    loop.call_soon_threadsafe(loop.stop)


def test_models_list(stack):
    base, _ = stack
    r = requests.get(base + "/v1/models", timeout=10)
    assert r.status_code == 200
    assert sorted(m["id"] for m in r.json()["data"]) == ["tiny",
                                                         "whisper-tiny"]


def test_chat_nonstream(stack):
    base, _ = stack
    r = requests.post(base + "/v1/chat/completions", json={
        "model": "tiny",
        "messages": [{"role": "user", "content": "hello"}],
        "max_tokens": 6,
    }, timeout=300)
    assert r.status_code == 200, r.text
    body = r.json()
    assert body["object"] == "chat.completion"
    assert body["choices"][0]["message"]["role"] == "assistant"
    assert body["usage"]["completion_tokens"] == 6
    assert body["choices"][0]["finish_reason"] in ("length", "stop", "eos")


def test_chat_extra_usage_header(stack):
    """Extra-Usage request header (reference chat.go:47-50,191) merges the
    in-band timings into `usage`, llama.cpp field names in ms."""
    base, _ = stack
    r = requests.post(base + "/v1/chat/completions", json={
        "model": "tiny",
        "messages": [{"role": "user", "content": "hello"}],
        "max_tokens": 4,
    }, headers={"Extra-Usage": "1"}, timeout=300)
    assert r.status_code == 200, r.text
    u = r.json()["usage"]
    assert u["timing_token_generation"] > 0
    assert "timing_prompt_processing" in u
    # completions endpoint honors it too (reference completion.go:74)
    rc = requests.post(base + "/v1/completions", json={
        "model": "tiny", "prompt": "hello", "max_tokens": 4,
    }, headers={"Extra-Usage": "1"}, timeout=300)
    assert "timing_token_generation" in rc.json()["usage"]
    # empty header value = disabled, matching the reference predicate
    r0 = requests.post(base + "/v1/chat/completions", json={
        "model": "tiny",
        "messages": [{"role": "user", "content": "hello"}],
        "max_tokens": 4,
    }, headers={"Extra-Usage": ""}, timeout=300)
    assert "timing_token_generation" not in r0.json()["usage"]
    # absent header → plain OpenAI usage
    r2 = requests.post(base + "/v1/chat/completions", json={
        "model": "tiny",
        "messages": [{"role": "user", "content": "hello"}],
        "max_tokens": 4,
    }, timeout=300)
    assert "timing_token_generation" not in r2.json()["usage"]


def test_chat_stream_sse(stack):
    base, _ = stack
    r = requests.post(base + "/v1/chat/completions", json={
        "model": "tiny",
        "messages": [{"role": "user", "content": "the quick"}],
        "max_tokens": 5,
        "stream": True,
    }, stream=True, timeout=300)
    assert r.status_code == 200
    assert r.headers["Content-Type"].startswith("text/event-stream")
    events = []
    for line in r.iter_lines():
        if line.startswith(b"data: "):
            payload = line[6:]
            if payload == b"[DONE]":
                events.append("DONE")
            else:
                events.append(json.loads(payload))
    assert events[-1] == "DONE"
    chunks = [e for e in events if e != "DONE"]
    assert chunks[0]["choices"][0]["delta"].get("role") == "assistant"
    assert any(c["choices"] and c["choices"][0]["delta"].get("content")
               for c in chunks)
    finals = [c for c in chunks
              if c["choices"] and c["choices"][0]["finish_reason"]]
    assert finals, "missing finish_reason chunk"
    assert chunks[-1].get("usage", {}).get("completion_tokens") == 5


def test_completions(stack):
    base, _ = stack
    r = requests.post(base + "/v1/completions", json={
        "model": "tiny", "prompt": "pack my box", "max_tokens": 4,
    }, timeout=300)
    assert r.status_code == 200, r.text
    body = r.json()
    assert body["object"] == "text_completion"
    assert body["usage"]["completion_tokens"] == 4


def test_embeddings_endpoint(stack):
    base, _ = stack
    r = requests.post(base + "/v1/embeddings", json={
        "model": "tiny",
        "input": ["the quick brown fox", "the quick brown foxes", "zzz 123"],
    }, timeout=300)
    assert r.status_code == 200, r.text
    data = r.json()["data"]
    v = [np.array(d["embedding"]) for d in data]
    assert all(abs(np.linalg.norm(x) - 1.0) < 1e-5 for x in v)
    assert float(v[0] @ v[1]) > float(v[0] @ v[2])


def test_tokenize_endpoint(stack):
    base, _ = stack
    r = requests.post(base + "/v1/tokenize", json={
        "model": "tiny", "content": "hello world"}, timeout=60)
    assert r.status_code == 200
    assert len(r.json()["tokens"]) > 0


def test_unknown_model_404(stack):
    base, _ = stack
    r = requests.post(base + "/v1/chat/completions", json={
        "model": "nope", "messages": [{"role": "user", "content": "x"}],
    }, timeout=30)
    assert r.status_code == 404


def test_backend_monitor(stack):
    base, _ = stack
    r = requests.get(base + "/backend/monitor", timeout=60)
    assert r.status_code == 200
    assert r.json()["tiny"]["state"] == 2  # READY


def test_metrics_endpoint(stack):
    base, _ = stack
    r = requests.get(base + "/metrics", timeout=10)
    assert r.status_code == 200
    assert b"localai_api_calls_total" in r.content


def test_tts_and_vad_http(stack):
    """/v1/audio/speech (implicit tts backend) returns WAV; /vad segments."""
    import io
    import wave

    base, _ = stack
    r = requests.post(base + "/v1/audio/speech", json={
        "input": "hello", "voice": "default"}, timeout=120)
    assert r.status_code == 200, r.text
    assert r.headers["Content-Type"].startswith("audio/wav")
    with wave.open(io.BytesIO(r.content)) as w:
        assert w.getframerate() == 16000
        assert w.getnframes() > 1000

    from localai_tpu.audio.tts import synthesize

    rate = 16000
    speech = synthesize("good morning everyone", voice="default",
                        language="en").astype(np.float32)[: rate]
    silence = 0.001 * np.random.default_rng(0).normal(size=rate)
    audio = np.concatenate([silence, speech, silence]).astype(np.float32)
    r = requests.post(base + "/vad", json={"audio": audio.tolist()},
                      timeout=120)
    assert r.status_code == 200
    segs = r.json()["segments"]
    assert len(segs) >= 1 and 0.6 < segs[0]["start"] < 1.4


def test_webui_served(stack):
    """GET / serves the built-in chat UI (reference routes/ui.go role)."""
    base, _ = stack
    r = requests.get(base + "/", timeout=30)
    assert r.status_code == 200
    assert r.headers["Content-Type"].startswith("text/html")
    assert "/v1/chat/completions" in r.text
    assert "/v1/models" in r.text


def test_elevenlabs_tts_route(stack):
    """elevenlabs-shaped /v1/text-to-speech/{voice_id} returns WAV
    (reference routes/elevenlabs.go)."""
    import io
    import wave

    base, _ = stack
    r = requests.post(base + "/v1/text-to-speech/premade-voice", json={
        "text": "hello there"}, timeout=120)
    assert r.status_code == 200, r.text
    assert r.headers["Content-Type"].startswith("audio/wav")
    with wave.open(io.BytesIO(r.content)) as w:
        assert w.getnframes() > 1000


def test_stores_http_roundtrip(stack):
    """/stores/* endpoints spawn an implicit store backend on demand."""
    base, _ = stack
    keys = [[1.0, 0.0, 0.0], [0.0, 1.0, 0.0]]
    r = requests.post(base + "/stores/set", json={
        "keys": keys, "values": ["alpha", "beta"]}, timeout=120)
    assert r.status_code == 200, r.text
    r = requests.post(base + "/stores/find", json={
        "key": [0.9, 0.1, 0.0], "topk": 2}, timeout=60)
    body = r.json()
    assert body["values"][0] == "alpha"
    assert body["similarities"][0] > body["similarities"][1]
    r = requests.post(base + "/stores/get", json={"keys": keys[:1]},
                      timeout=60)
    assert r.json()["values"] == ["alpha"]
    requests.post(base + "/stores/delete", json={"keys": keys[:1]},
                  timeout=60)
    r = requests.post(base + "/stores/get", json={"keys": keys[:1]},
                      timeout=60)
    assert r.json()["values"] == []


def test_response_format_json_object(stack):
    """response_format=json_object → grammar-enforced valid JSON output even
    from random weights (chat.go:224-258 semantics, enforced on-device)."""
    base, _ = stack
    r = requests.post(base + "/v1/chat/completions", json={
        "model": "tiny",
        "messages": [{"role": "user", "content": "emit json"}],
        "max_tokens": 50,
        "temperature": 0.9,
        "seed": 11,
        "response_format": {"type": "json_object"},
    }, timeout=300)
    assert r.status_code == 200, r.text
    content = r.json()["choices"][0]["message"]["content"]
    assert content.startswith("{")
    if r.json()["choices"][0]["finish_reason"] in ("stop", "eos"):
        json.loads(content)


_WEATHER_TOOL = {
    "type": "function",
    "function": {
        "name": "get_weather",
        "description": "Get the weather for a city",
        "parameters": {
            "type": "object",
            "properties": {"city": {"type": "string"}},
            "required": ["city"],
        },
    },
}


def test_tools_returns_tool_calls(stack):
    """OpenAI tools request → grammar-constrained output parsed back into
    message.tool_calls with finish_reason "tool_calls"
    (reference: chat.go:266-312 + pkg/functions/parse.go)."""
    base, _ = stack
    r = requests.post(base + "/v1/chat/completions", json={
        "model": "tiny",
        "messages": [{"role": "user", "content": "weather in Paris?"}],
        "max_tokens": 60,
        "temperature": 0.0,
        "tools": [_WEATHER_TOOL],
    }, timeout=300)
    assert r.status_code == 200, r.text
    choice = r.json()["choices"][0]
    # grammar forces {"name": <tool|answer>, "arguments": {...}}; the
    # no-action "answer" alternative (tool_choice auto) unwraps to prose
    # content, and hitting max_tokens mid-object legitimately yields the
    # raw partial text
    if choice["finish_reason"] == "tool_calls":
        msg = choice["message"]
        assert msg["content"] is None
        calls = msg["tool_calls"]
        assert calls and calls[0]["type"] == "function"
        assert calls[0]["function"]["name"] == "get_weather"
        args = json.loads(calls[0]["function"]["arguments"])
        assert isinstance(args, dict)
        assert calls[0]["id"].startswith("call_")
    else:
        assert isinstance(choice["message"]["content"], str)


def test_tools_streaming_tool_call_delta(stack):
    """Streaming tools request buffers the grammar output and emits ONE
    tool_calls delta + finish_reason tool_calls (chat.go:334-449 role)."""
    base, _ = stack
    r = requests.post(base + "/v1/chat/completions", json={
        "model": "tiny",
        "messages": [{"role": "user", "content": "weather in Oslo?"}],
        "max_tokens": 60,
        "temperature": 0.0,
        "stream": True,
        "tools": [_WEATHER_TOOL],
    }, stream=True, timeout=300)
    assert r.status_code == 200
    deltas, finishes = [], []
    for line in r.iter_lines():
        if not line or not line.startswith(b"data: "):
            continue
        payload = line[6:]
        if payload == b"[DONE]":
            break
        obj = json.loads(payload)
        for ch in obj.get("choices", []):
            deltas.append(ch.get("delta", {}))
            if ch.get("finish_reason"):
                finishes.append(ch["finish_reason"])
    tool_deltas = [d for d in deltas if d.get("tool_calls")]
    if "tool_calls" in finishes:
        assert len(tool_deltas) == 1
        tc = tool_deltas[0]["tool_calls"][0]
        assert tc["index"] == 0
        assert tc["function"]["name"] == "get_weather"
    else:
        # no-action "answer" (possibly with an empty message) or truncated
        # JSON — either way the stream must have terminated cleanly with a
        # finish chunk, and any buffered text arrives as content deltas
        assert finishes, "stream ended without a finish_reason chunk"


def test_realtime_websocket_text_session(stack):
    """WS session: item.create + response.create → text delta + TTS audio
    delta + done (the reference's realtime pipeline composition)."""
    import base64
    import io
    import wave

    pytest.importorskip("websockets")
    from websockets.sync.client import connect

    base, _ = stack
    url = base.replace("http://", "ws://") + "/v1/realtime?model=tiny"
    with connect(url, open_timeout=30) as ws:
        first = json.loads(ws.recv(timeout=30))
        assert first["type"] == "session.created"
        assert first["session"]["model"] == "tiny"

        ws.send(json.dumps({"type": "conversation.item.create",
                            "item": {"role": "user", "content": "hello"}}))
        assert json.loads(ws.recv(timeout=30))["type"] == \
            "conversation.item.created"

        ws.send(json.dumps({"type": "response.create"}))
        events = {}
        for _ in range(64):
            ev = json.loads(ws.recv(timeout=600))
            events[ev["type"]] = ev
            if ev["type"] == "response.done":
                break
        assert "response.created" in events
        assert "response.text.delta" in events
        assert "response.audio.delta" in events
        assert "response.done" in events
        assert events["response.done"]["status"] == "completed"
        wav_bytes = base64.b64decode(events["response.audio.delta"]["delta"])
        with wave.open(io.BytesIO(wav_bytes)) as w:
            assert w.getnframes() > 0

        # unknown event type surfaces an error event, session stays alive
        ws.send(json.dumps({"type": "bogus.event"}))
        assert json.loads(ws.recv(timeout=30))["type"] == "error"


def test_realtime_response_cancel(stack):
    """response.cancel interrupts an in-flight response: the terminal event
    is response.done with status cancelled (the reference stubs this,
    realtime.go:522 — we implement it)."""
    pytest.importorskip("websockets")
    from websockets.sync.client import connect

    base, _ = stack
    url = base.replace("http://", "ws://") + "/v1/realtime?model=tiny"
    with connect(url, open_timeout=30) as ws:
        assert json.loads(ws.recv(timeout=30))["type"] == "session.created"
        ws.send(json.dumps({"type": "conversation.item.create",
                            "item": {"role": "user", "content": "hi"}}))
        assert json.loads(ws.recv(timeout=30))["type"] == \
            "conversation.item.created"
        ws.send(json.dumps({"type": "response.create"}))
        ws.send(json.dumps({"type": "response.cancel"}))
        status = None
        for _ in range(64):
            ev = json.loads(ws.recv(timeout=600))
            if ev["type"] == "response.done":
                status = ev["status"]
                break
            assert ev["type"] in ("response.created", "response.text.delta",
                                  "response.audio.delta", "error")
        # cancelled when the cancel landed mid-flight; completed only if the
        # tiny model outran the cancel — either way done is terminal
        assert status in ("cancelled", "completed")

        # cancel with nothing active is an error event
        ws.send(json.dumps({"type": "response.cancel"}))
        assert json.loads(ws.recv(timeout=30))["type"] == "error"


def test_realtime_transcription_session(stack):
    """intent=transcription sessions (reference routes/openai.go:21-22,
    realtime.go:67): audio commit yields transcription delta + completed and
    NO response events; response.create is rejected; buffer.clear works."""
    import base64

    pytest.importorskip("websockets")
    from websockets.sync.client import connect

    from localai_tpu.audio.tts import synthesize

    base, _ = stack
    url = (base.replace("http://", "ws://")
           + "/v1/realtime?model=tiny&intent=transcription")
    with connect(url, open_timeout=120) as ws:
        first = json.loads(ws.recv(timeout=120))
        assert first["type"] == "transcription_session.created"
        assert first["session"]["object"] == "realtime.transcription_session"

        # clear path
        ws.send(json.dumps({"type": "input_audio_buffer.append",
                            "audio": base64.b64encode(b"\0\0" * 160).decode()}))
        ws.send(json.dumps({"type": "input_audio_buffer.clear"}))
        assert json.loads(ws.recv(timeout=120))["type"] == \
            "input_audio_buffer.cleared"

        # commit synthesized speech → transcription events only
        pcm = synthesize("hello there how are you", voice="default",
                         language="en")
        i16 = (np.clip(pcm, -1, 1) * 32767).astype(np.int16).tobytes()
        ws.send(json.dumps({"type": "input_audio_buffer.append",
                            "audio": base64.b64encode(i16).decode()}))
        ws.send(json.dumps({"type": "input_audio_buffer.commit"}))
        got = []
        for _ in range(64):
            ev = json.loads(ws.recv(timeout=600))
            got.append(ev["type"])
            if ev["type"] == \
                    "conversation.item.input_audio_transcription.completed":
                break
        assert "input_audio_buffer.committed" in got
        assert not any(t.startswith("response.") for t in got)

        # responses are a conversation-session concept
        ws.send(json.dumps({"type": "response.create"}))
        assert json.loads(ws.recv(timeout=120))["type"] == "error"


def test_realtime_session_factory_routes(stack):
    """POST /v1/realtime/sessions + /v1/realtime/transcription_session mint
    ephemeral session descriptors (reference routes/openai.go:21-22)."""
    base, _ = stack
    r = requests.post(base + "/v1/realtime/sessions",
                      json={"model": "tiny", "voice": "alto"}, timeout=30)
    assert r.status_code == 200
    s = r.json()
    assert s["object"] == "realtime.session"
    assert s["model"] == "tiny" and s["voice"] == "alto"
    assert s["client_secret"]["value"].startswith("ek_")

    r = requests.post(base + "/v1/realtime/transcription_session",
                      json={}, timeout=30)
    assert r.status_code == 200
    assert r.json()["object"] == "realtime.transcription_session"


def test_kill9_backend_recovers(stack):
    """Reference loader.go:191-225 semantics: dead backend is reaped on the
    next request and respawned transparently."""
    base, manager = stack
    h = manager.get("tiny")
    assert h is not None
    os.kill(h.proc.pid, signal.SIGKILL)
    h.proc.wait(timeout=10)
    r = requests.post(base + "/v1/chat/completions", json={
        "model": "tiny",
        "messages": [{"role": "user", "content": "alive again"}],
        "max_tokens": 3,
    }, timeout=600)
    assert r.status_code == 200, r.text
    assert r.json()["usage"]["completion_tokens"] == 3
    h2 = manager.get("tiny")
    assert h2 is not None and h2.proc.pid != h.proc.pid
