"""OCI client (localai_tpu/oci) against a local in-process registry —
zero-egress verification of the pull/unpack paths the backend gallery and
`oci://`/`ollama://` downloader schemes use."""
import gzip
import hashlib
import io
import json
import os
import tarfile
import threading

import pytest


def _tar_layer(files: dict[str, bytes], gz=True) -> bytes:
    buf = io.BytesIO()
    with tarfile.open(fileobj=buf, mode="w") as tf:
        for name, data in files.items():
            info = tarfile.TarInfo(name)
            info.size = len(data)
            tf.addfile(info, io.BytesIO(data))
    raw = buf.getvalue()
    return gzip.compress(raw) if gz else raw


def _digest(b: bytes) -> str:
    return "sha256:" + hashlib.sha256(b).hexdigest()


class _FakeRegistry:
    """Tiny distribution-spec server: manifests + blobs, optional token auth."""

    def __init__(self, auth=False):
        self.blobs: dict[str, bytes] = {}
        self.manifests: dict[tuple[str, str], bytes] = {}
        self.auth = auth
        self.requests = []

    def add_image(self, repo: str, tag: str, layers: list[tuple[bytes, str]]):
        entries = []
        for data, mt in layers:
            d = _digest(data)
            self.blobs[d] = data
            entries.append({"digest": d, "mediaType": mt, "size": len(data)})
        manifest = json.dumps({
            "schemaVersion": 2,
            "mediaType": "application/vnd.oci.image.manifest.v1+json",
            "layers": entries,
        }).encode()
        self.manifests[(repo, tag)] = manifest
        return _digest(manifest)

    def serve(self):
        from http.server import BaseHTTPRequestHandler, HTTPServer

        reg = self

        class H(BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def do_GET(self):
                reg.requests.append(self.path)
                if reg.auth and self.path.startswith("/v2/") and \
                        "token" not in self.headers.get("Authorization", ""):
                    self.send_response(401)
                    self.send_header(
                        "WWW-Authenticate",
                        f'Bearer realm="http://{self.server.server_address[0]}'
                        f':{self.server.server_address[1]}/token",'
                        f'service="fake",scope="pull"')
                    self.end_headers()
                    return
                if self.path.startswith("/token"):
                    body = json.dumps({"token": "token-abc"}).encode()
                    self.send_response(200)
                    self.end_headers()
                    self.wfile.write(body)
                    return
                parts = self.path.split("/")
                if "manifests" in parts:
                    i = parts.index("manifests")
                    repo, ref = "/".join(parts[2:i]), parts[i + 1]
                    m = reg.manifests.get((repo, ref))
                    if m is None and ref.startswith("sha256:"):
                        m = next((v for v in reg.manifests.values()
                                  if _digest(v) == ref), None)
                    if m is None:
                        self.send_response(404)
                        self.end_headers()
                        return
                    self.send_response(200)
                    self.send_header(
                        "Content-Type",
                        "application/vnd.oci.image.manifest.v1+json")
                    self.end_headers()
                    self.wfile.write(m)
                    return
                if "blobs" in parts:
                    i = parts.index("blobs")
                    blob = reg.blobs.get(parts[i + 1])
                    if blob is None:
                        self.send_response(404)
                        self.end_headers()
                        return
                    self.send_response(200)
                    self.send_header("Content-Length", str(len(blob)))
                    self.end_headers()
                    self.wfile.write(blob)
                    return
                self.send_response(404)
                self.end_headers()

        srv = HTTPServer(("127.0.0.1", 0), H)
        threading.Thread(target=srv.serve_forever, daemon=True).start()
        return srv


@pytest.fixture()
def registry():
    reg = _FakeRegistry()
    srv = reg.serve()
    host = f"127.0.0.1:{srv.server_address[1]}"
    yield reg, host
    srv.shutdown()


def test_parse_refs():
    from localai_tpu.oci import parse_ollama_ref, parse_ref

    assert parse_ref("oci://quay.io/org/img:v1") == ("quay.io", "org/img", "v1")
    assert parse_ref("oci://host/repo") == ("host", "repo", "latest")
    assert parse_ollama_ref("ollama://gemma:2b") == (
        "registry.ollama.ai", "library/gemma", "2b")
    assert parse_ollama_ref("ollama://org/m") == (
        "registry.ollama.ai", "org/m", "latest")


def test_pull_image(registry, tmp_path):
    from localai_tpu.oci import pull_image

    reg, host = registry
    layer1 = _tar_layer({"run.sh": b"#!/bin/sh\necho hi\n"})
    layer2 = _tar_layer({"sub/data.txt": b"payload"})
    reg.add_image("org/backend", "v1", [
        (layer1, "application/vnd.oci.image.layer.v1.tar+gzip"),
        (layer2, "application/vnd.oci.image.layer.v1.tar+gzip")])
    dest = str(tmp_path / "img")
    pull_image(f"oci://{host}/org/backend:v1", dest)
    assert (tmp_path / "img" / "run.sh").read_bytes().startswith(b"#!/bin/sh")
    assert (tmp_path / "img" / "sub" / "data.txt").read_text() == "payload"


def test_pull_image_with_token_auth(tmp_path):
    from localai_tpu.oci import pull_image

    reg = _FakeRegistry(auth=True)
    srv = reg.serve()
    host = f"127.0.0.1:{srv.server_address[1]}"
    try:
        layer = _tar_layer({"f": b"x"})
        reg.add_image("r/i", "t", [
            (layer, "application/vnd.oci.image.layer.v1.tar+gzip")])
        pull_image(f"oci://{host}/r/i:t", str(tmp_path / "o"))
        assert (tmp_path / "o" / "f").read_text() == "x"
    finally:
        srv.shutdown()


def test_pull_rejects_corrupt_blob(registry, tmp_path):
    from localai_tpu.oci import OCIError, pull_image

    reg, host = registry
    layer = _tar_layer({"f": b"x"})
    reg.add_image("r/i", "t", [
        (layer, "application/vnd.oci.image.layer.v1.tar+gzip")])
    # corrupt the stored blob after the manifest recorded its digest
    (d,) = list(reg.blobs)
    reg.blobs[d] = reg.blobs[d] + b"tamper"
    with pytest.raises(OCIError, match="digest mismatch"):
        pull_image(f"oci://{host}/r/i:t", str(tmp_path / "o"))


def test_extract_rejects_traversal(registry, tmp_path):
    from localai_tpu.oci import OCIError, pull_image

    reg, host = registry
    evil = _tar_layer({"../../evil.txt": b"boom"})
    reg.add_image("r/evil", "t", [
        (evil, "application/vnd.oci.image.layer.v1.tar+gzip")])
    with pytest.raises(OCIError, match="escapes"):
        pull_image(f"oci://{host}/r/evil:t", str(tmp_path / "o"))
    assert not (tmp_path / "evil.txt").exists()


def test_whiteout_removes_file(registry, tmp_path):
    from localai_tpu.oci import pull_image

    reg, host = registry
    l1 = _tar_layer({"old.txt": b"stale", "keep.txt": b"ok"})
    l2 = _tar_layer({".wh.old.txt": b""})
    reg.add_image("r/w", "t", [
        (l1, "application/vnd.oci.image.layer.v1.tar+gzip"),
        (l2, "application/vnd.oci.image.layer.v1.tar+gzip")])
    pull_image(f"oci://{host}/r/w:t", str(tmp_path / "o"))
    assert not (tmp_path / "o" / "old.txt").exists()
    assert (tmp_path / "o" / "keep.txt").read_text() == "ok"


def test_pull_ollama_model(registry, tmp_path):
    from localai_tpu.oci import Registry, parse_ollama_ref  # noqa: F401
    from localai_tpu.oci import pull_ollama_model

    reg, host = registry
    gguf = b"GGUF" + b"\x00" * 64
    cfg = json.dumps({"config": True}).encode()
    entries = []
    for data, mt in ((cfg, "application/vnd.docker.container.image.v1+json"),
                     (gguf, "application/vnd.ollama.image.model")):
        d = _digest(data)
        reg.blobs[d] = data
        entries.append({"digest": d, "mediaType": mt, "size": len(data)})
    reg.manifests[("library/fake", "1b")] = json.dumps({
        "schemaVersion": 2,
        "mediaType": "application/vnd.oci.image.manifest.v1+json",
        "layers": entries}).encode()

    # patch the registry host: pull_ollama_model hardwires registry.ollama.ai
    import localai_tpu.oci as oci

    orig = oci.OLLAMA_REGISTRY
    oci.OLLAMA_REGISTRY = host
    try:
        dest = str(tmp_path / "model.gguf")
        pull_ollama_model("ollama://fake:1b", dest)
        assert open(dest, "rb").read(4) == b"GGUF"
    finally:
        oci.OLLAMA_REGISTRY = orig


def test_unpack_oci_file(tmp_path):
    from localai_tpu.oci import unpack_oci_file

    layer = _tar_layer({"bin/tool": b"TOOL"})
    manifest = json.dumps({
        "schemaVersion": 2,
        "layers": [{"digest": _digest(layer),
                    "mediaType": "application/vnd.oci.image.layer.v1.tar+gzip",
                    "size": len(layer)}]}).encode()
    index = json.dumps({"manifests": [{"digest": _digest(manifest)}]}).encode()
    tar_path = str(tmp_path / "img.tar")
    with tarfile.open(tar_path, "w") as tf:
        for name, data in (("index.json", index),
                           ("blobs/" + _digest(manifest).replace(":", "/"),
                            manifest),
                           ("blobs/" + _digest(layer).replace(":", "/"),
                            layer)):
            info = tarfile.TarInfo(name)
            info.size = len(data)
            tf.addfile(info, io.BytesIO(data))
    out = str(tmp_path / "out")
    unpack_oci_file(tar_path, out)
    assert (tmp_path / "out" / "bin" / "tool").read_text() == "TOOL"
