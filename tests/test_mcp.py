"""MCP client + /mcp/v1/chat/completions agent loop + /v1/edits."""
import json
import os
import sys
import threading

import pytest

SERVER = os.path.join(os.path.dirname(__file__), "mcp_test_server.py")


def test_stdio_session_tools_and_call(tmp_path):
    from localai_tpu.mcp import MCPSession, _StdioTransport

    log = str(tmp_path / "calls.jsonl")
    s = MCPSession("calc", _StdioTransport(f"{sys.executable} {SERVER} {log}"))
    try:
        assert [t["name"] for t in s.tools] == ["add"]
        out = s.call_tool("add", {"a": 2, "b": 40})
        assert out == "42"
        rec = json.loads(open(log).read().strip())
        assert rec["name"] == "add" and rec["arguments"] == {"a": 2, "b": 40}
    finally:
        s.close()


def test_http_session(tmp_path):
    """HTTP transport against an in-process JSON-RPC endpoint."""
    from http.server import BaseHTTPRequestHandler, HTTPServer

    from localai_tpu.mcp import MCPSession, _HttpTransport

    class H(BaseHTTPRequestHandler):
        def log_message(self, *a):
            pass

        def do_POST(self):
            body = json.loads(self.rfile.read(
                int(self.headers["Content-Length"])))
            if "id" not in body:
                self.send_response(202)
                self.end_headers()
                return
            method = body["method"]
            if method == "initialize":
                result = {"protocolVersion": "2024-11-05"}
            elif method == "tools/list":
                result = {"tools": [{"name": "echo",
                                     "inputSchema": {"type": "object"}}]}
            else:
                result = {"content": [{
                    "type": "text",
                    "text": body["params"]["arguments"].get("msg", "")}]}
            out = json.dumps({"jsonrpc": "2.0", "id": body["id"],
                              "result": result}).encode()
            self.send_response(200)
            self.send_header("Content-Type", "application/json")
            self.end_headers()
            self.wfile.write(out)

    srv = HTTPServer(("127.0.0.1", 0), H)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    try:
        s = MCPSession("remote", _HttpTransport(
            f"http://127.0.0.1:{srv.server_address[1]}/mcp"))
        assert s.tools[0]["name"] == "echo"
        assert s.call_tool("echo", {"msg": "hi"}) == "hi"
    finally:
        srv.shutdown()


def test_tools_as_openai(tmp_path):
    from localai_tpu.mcp import (
        MCPSession, _StdioTransport, tools_as_openai,
    )

    s = MCPSession("calc", _StdioTransport(f"{sys.executable} {SERVER}"))
    try:
        tools, owner = tools_as_openai([s])
        assert tools[0]["function"]["name"] == "add"
        assert owner["add"] is s
    finally:
        s.close()


@pytest.fixture(scope="module")
def mcp_stack(tmp_path_factory):
    """Full API stack: tiny llm model configured with a stdio MCP server."""
    import asyncio
    import socket
    import time

    import requests
    import yaml
    from aiohttp import web

    sys.path.insert(0, os.path.dirname(__file__))
    from fixtures import tiny_checkpoint

    from localai_tpu.config import AppConfig, ModelConfigLoader
    from localai_tpu.core.manager import ModelManager
    from localai_tpu.server.http import API

    ckpt = tiny_checkpoint(tmp_path_factory)
    models = tmp_path_factory.mktemp("models")
    call_log = str(tmp_path_factory.mktemp("mcp") / "calls.jsonl")
    (models / "tiny.yaml").write_text(yaml.safe_dump({
        "name": "tiny", "backend": "llm", "context_size": 128,
        "parallel": 2, "dtype": "float32", "prefill_buckets": [32, 64],
        "parameters": {"model": ckpt, "temperature": 0.0, "max_tokens": 16},
        "mcp": {"stdio": [{
            "name": "calc",
            "command": f"{sys.executable} {SERVER} {call_log}"}]},
        "agent": {"max_iterations": 2},
    }))
    os.environ["LOCALAI_JAX_PLATFORM"] = "cpu"
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    app_cfg = AppConfig(address=f"127.0.0.1:{port}",
                        models_path=str(models), parallel_requests=2)
    manager = ModelManager(app_cfg)
    api = API(app_cfg, ModelConfigLoader(str(models)), manager)
    loop = asyncio.new_event_loop()

    def run():
        asyncio.set_event_loop(loop)
        runner = web.AppRunner(api.app)
        loop.run_until_complete(runner.setup())
        site = web.TCPSite(runner, "127.0.0.1", port)
        loop.run_until_complete(site.start())
        loop.run_forever()

    threading.Thread(target=run, daemon=True).start()
    base = f"http://127.0.0.1:{port}"
    for _ in range(50):
        try:
            requests.get(base + "/healthz", timeout=1)
            break
        except requests.ConnectionError:
            time.sleep(0.1)
    yield base, call_log
    manager.stop_all()
    loop.call_soon_threadsafe(loop.stop)


def test_mcp_chat_executes_tools(mcp_stack):
    """The agent loop must produce at least one real MCP tools/call (the
    grammar forces the random model into a valid call on round 1) and return
    a normal chat completion."""
    import requests

    base, call_log = mcp_stack
    r = requests.post(base + "/mcp/v1/chat/completions", json={
        "model": "tiny",
        "messages": [{"role": "user", "content": "add 2 and 3"}],
        "max_tokens": 24,
    }, timeout=600)
    assert r.status_code == 200, r.text
    body = r.json()
    assert body["choices"][0]["message"]["role"] == "assistant"
    assert os.path.exists(call_log)
    calls = [json.loads(l) for l in open(call_log) if l.strip()]
    assert len(calls) >= 1
    assert calls[0]["name"] == "add"


def test_mcp_chat_requires_config(mcp_stack):
    import requests

    base, _ = mcp_stack
    r = requests.post(base + "/mcp/v1/chat/completions", json={
        "model": "definitely-not-there",
        "messages": [{"role": "user", "content": "x"}]}, timeout=30)
    assert r.status_code == 404


def test_edits_endpoint(mcp_stack):
    import requests

    base, _ = mcp_stack
    r = requests.post(base + "/v1/edits", json={
        "model": "tiny",
        "instruction": "capitalize everything",
        "input": "hello",
        "max_tokens": 8,
    }, timeout=600)
    assert r.status_code == 200, r.text
    body = r.json()
    assert body["object"] == "edit"
    assert len(body["choices"]) == 1
    assert "text" in body["choices"][0]
