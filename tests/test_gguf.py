"""GGUF ingestion: binary parsing, block dequantization, llama.cpp q/k
permutation inversion, config/tokenizer synthesis, and end-to-end serving of
an imported checkpoint.

The fixture WRITES a real GGUF v3 file from the tiny HF checkpoint —
including the q/k row permutation and Q8_0 quantization the llama.cpp
converter applies — so the import path is validated as a true round trip:
HF → GGUF → import → logits match the original HF weights.
"""
import json
import struct

import numpy as np
import pytest

from fixtures import tiny_checkpoint
from localai_tpu.services import gguf as G


# ------------------------------------------------------------ GGUF writer

def _w_str(out, s):
    b = s.encode()
    out += struct.pack("<Q", len(b)) + b


def _w_kv(out, key, vtype, value):
    _w_str(out, key)
    out += struct.pack("<I", vtype)
    if vtype == 8:
        _w_str(out, value)
    elif vtype == 4:
        out += struct.pack("<I", value)
    elif vtype == 6:
        out += struct.pack("<f", value)
    elif vtype == 9:
        et, vals = value
        out += struct.pack("<IQ", et, len(vals))
        for v in vals:
            if et == 8:
                _w_str(out, v)
            elif et == 6:
                out += struct.pack("<f", v)
            elif et == 5:
                out += struct.pack("<i", v)


def _permute(w, n_head):
    return (w.reshape(n_head, 2, w.shape[0] // n_head // 2, *w.shape[1:])
             .swapaxes(1, 2).reshape(w.shape))


def _q8_0(w):
    """f32 → GGML Q8_0 blocks (f16 scale + 32 int8)."""
    flat = w.astype(np.float32).reshape(-1, 32)
    d = np.abs(flat).max(axis=1) / 127.0
    d = np.where(d == 0, 1e-12, d)
    q = np.clip(np.round(flat / d[:, None]), -127, 127).astype(np.int8)
    out = bytearray()
    for i in range(flat.shape[0]):
        out += np.float16(d[i]).tobytes() + q[i].tobytes()
    return bytes(out)


def write_gguf(path, meta_kv, tensors):
    """tensors: {name: (np_array, 'f32'|'q8_0')} — dims written GGUF-order."""
    out = bytearray()
    out += b"GGUF" + struct.pack("<IQQ", 3, len(tensors), len(meta_kv))
    for key, (vt, val) in meta_kv.items():
        _w_kv(out, key, vt, val)
    blobs = []
    offset = 0
    for name, (arr, kind) in tensors.items():
        _w_str(out, name)
        dims = list(reversed(arr.shape))
        out += struct.pack("<I", len(dims))
        for dim in dims:
            out += struct.pack("<Q", dim)
        if kind == "q8_0":
            blob, ttype = _q8_0(arr), G.GGML_Q8_0
        elif kind == "f16":
            blob, ttype = arr.astype(np.float16).tobytes(), G.GGML_F16
        else:
            blob, ttype = arr.astype(np.float32).tobytes(), G.GGML_F32
        out += struct.pack("<IQ", ttype, offset)
        pad = (-len(blob)) % 32
        blobs.append(blob + b"\0" * pad)
        offset += len(blob) + pad
    start = (len(out) + 31) // 32 * 32
    out += b"\0" * (start - len(out))
    for blob in blobs:
        out += blob
    with open(path, "wb") as f:
        f.write(out)


@pytest.fixture(scope="module")
def gguf_file(tmp_path_factory):
    """Tiny HF checkpoint → GGUF v3 with the llama.cpp-converter layout."""
    from safetensors.numpy import load_file

    ckpt = tiny_checkpoint(tmp_path_factory)
    cfg = json.load(open(f"{ckpt}/config.json"))
    st = load_file(f"{ckpt}/model.safetensors")
    tok = json.load(open(f"{ckpt}/tokenizer.json"))
    vocab = tok["model"]["vocab"]
    tokens = [None] * len(vocab)
    for t, i in vocab.items():
        tokens[i] = t
    nh, nkv = cfg["num_attention_heads"], cfg["num_key_value_heads"]
    L = cfg["num_hidden_layers"]

    meta = {
        "general.architecture": (8, "llama"),
        "llama.embedding_length": (4, cfg["hidden_size"]),
        "llama.feed_forward_length": (4, cfg["intermediate_size"]),
        "llama.block_count": (4, L),
        "llama.attention.head_count": (4, nh),
        "llama.attention.head_count_kv": (4, nkv),
        "llama.attention.layer_norm_rms_epsilon": (6, cfg["rms_norm_eps"]),
        "llama.context_length": (4, cfg["max_position_embeddings"]),
        "llama.rope.freq_base": (6, cfg.get("rope_theta", 10000.0)),
        "tokenizer.ggml.model": (8, "gpt2"),
        "tokenizer.ggml.tokens": (9, (8, tokens)),
        # tokenizer.json may store merges as ["a", "b"] pairs; GGUF stores
        # "a b" strings (what HF tokenizers also accepts back)
        "tokenizer.ggml.merges": (9, (8, [
            m if isinstance(m, str) else " ".join(m)
            for m in tok["model"]["merges"]])),
        "tokenizer.ggml.eos_token_id": (4, cfg.get("eos_token_id", 1)),
        "tokenizer.ggml.bos_token_id": (4, cfg.get("bos_token_id", 0)),
    }
    tensors = {"token_embd.weight": (st["model.embed_tokens.weight"], "f32"),
               "output_norm.weight": (st["model.norm.weight"], "f32")}
    if "lm_head.weight" in st:
        tensors["output.weight"] = (st["lm_head.weight"], "q8_0")
    for i in range(L):
        hf, b = f"model.layers.{i}.", f"blk.{i}."
        tensors[b + "attn_norm.weight"] = (st[hf + "input_layernorm.weight"],
                                           "f32")
        tensors[b + "attn_q.weight"] = (
            _permute(st[hf + "self_attn.q_proj.weight"], nh), "q8_0")
        tensors[b + "attn_k.weight"] = (
            _permute(st[hf + "self_attn.k_proj.weight"], nkv), "q8_0")
        tensors[b + "attn_v.weight"] = (st[hf + "self_attn.v_proj.weight"],
                                        "q8_0")
        tensors[b + "attn_output.weight"] = (
            st[hf + "self_attn.o_proj.weight"], "q8_0")
        tensors[b + "ffn_norm.weight"] = (
            st[hf + "post_attention_layernorm.weight"], "f32")
        tensors[b + "ffn_gate.weight"] = (st[hf + "mlp.gate_proj.weight"],
                                          "q8_0")
        tensors[b + "ffn_up.weight"] = (st[hf + "mlp.up_proj.weight"], "q8_0")
        tensors[b + "ffn_down.weight"] = (st[hf + "mlp.down_proj.weight"],
                                          "q8_0")
    path = str(tmp_path_factory.mktemp("gguf") / "tiny.Q8_0.gguf")
    write_gguf(path, meta, tensors)
    return path, ckpt


def test_parse_roundtrip(gguf_file):
    path, _ = gguf_file
    meta, tensors, _ = G.parse_gguf(path)
    assert meta["general.architecture"] == "llama"
    assert meta["llama.block_count"] == 2
    assert "blk.0.attn_q.weight" in tensors
    shape, ttype, off = tensors["blk.0.attn_q.weight"]
    assert ttype == G.GGML_Q8_0 and len(shape) == 2


def test_dequant_kinds():
    rng = np.random.default_rng(0)
    w = rng.normal(size=(4, 64)).astype(np.float32)
    # q8_0 round trip ~1% error
    raw = np.frombuffer(_q8_0(w), np.uint8)
    got = G.dequantize(raw, G.GGML_Q8_0, w.shape)
    assert np.abs(got - w).max() < np.abs(w).max() * 0.02
    # f16 exact-ish
    raw16 = np.frombuffer(w.astype(np.float16).tobytes(), np.uint8)
    got16 = G.dequantize(raw16, G.GGML_F16, w.shape)
    np.testing.assert_allclose(got16.astype(np.float32), w, atol=1e-2)


def test_q6k_dequant_reference():
    """Q6_K decode against a scalar reference implementation."""
    rng = np.random.default_rng(1)
    nb = 2
    ql = rng.integers(0, 256, (nb, 128), dtype=np.uint8)
    qh = rng.integers(0, 256, (nb, 64), dtype=np.uint8)
    sc = rng.integers(-30, 30, (nb, 16), dtype=np.int8)
    d = rng.normal(size=(nb,)).astype(np.float16)
    raw = b""
    for i in range(nb):
        raw += ql[i].tobytes() + qh[i].tobytes() + sc[i].tobytes() + d[i].tobytes()
    got = G.dequantize(np.frombuffer(raw, np.uint8), G.GGML_Q6_K, (nb * 256,))
    ref = np.zeros((nb, 256), np.float32)
    for i in range(nb):
        df = float(np.float32(d[i]))
        for half in range(2):
            for l in range(32):
                is_ = l // 16
                base = half * 128
                qlh = ql[i, half * 64:(half + 1) * 64]
                qhh = qh[i, half * 32:(half + 1) * 32]
                scs = sc[i, half * 8:(half + 1) * 8]
                lo, lo32 = int(qlh[l]), int(qlh[l + 32])
                hi = int(qhh[l])
                q1 = ((lo & 0xF) | (((hi >> 0) & 3) << 4)) - 32
                q2 = ((lo32 & 0xF) | (((hi >> 2) & 3) << 4)) - 32
                q3 = ((lo >> 4) | (((hi >> 4) & 3) << 4)) - 32
                q4 = ((lo32 >> 4) | (((hi >> 6) & 3) << 4)) - 32
                ref[i, base + l] = df * scs[is_] * q1
                ref[i, base + l + 32] = df * scs[is_ + 2] * q2
                ref[i, base + l + 64] = df * scs[is_ + 4] * q3
                ref[i, base + l + 96] = df * scs[is_ + 6] * q4
    np.testing.assert_allclose(got.reshape(nb, 256), ref, rtol=1e-5, atol=1e-5)


def test_convert_and_serve(gguf_file, tmp_path):
    """Full import: GGUF → HF dir → engine serves; greedy tokens match the
    ORIGINAL HF checkpoint (q8_0 noise must not change argmax on this tiny
    geometry — and the q/k unpermute is load-bearing for that)."""
    import jax.numpy as jnp

    from localai_tpu.engine import (
        Engine, EngineConfig, GenRequest, Tokenizer, load_config, load_params,
    )
    from localai_tpu.models.llama import forward_train
    from localai_tpu.ops.sampling import SamplingParams

    path, ckpt = gguf_file
    out = G.convert_gguf(path, str(tmp_path / "hf"))

    cfg = load_config(out, dtype="float32")
    params = load_params(out, cfg)
    ref_cfg = load_config(ckpt, dtype="float32")
    ref_params = load_params(ckpt, cfg)
    tok = Tokenizer.from_dir(out)
    ids = tok.encode("the quick brown fox")
    ours = np.asarray(forward_train(params, cfg, jnp.asarray([ids])))[0]
    ref = np.asarray(forward_train(ref_params, ref_cfg, jnp.asarray([ids])))[0]
    # q8_0 quantization noise only — correlation must be near-perfect (the
    # permutation bug would destroy it)
    cc = np.corrcoef(ours.ravel(), ref.ravel())[0, 1]
    assert cc > 0.999, f"logits decorrelated (cc={cc:.4f})"

    eng = Engine(cfg, params, tok, EngineConfig(
        max_slots=1, max_context=128, prefill_buckets=(32,)))
    text = eng.generate_text(GenRequest(
        ids, SamplingParams(temperature=0.0), max_tokens=8, ignore_eos=True))
    assert isinstance(text, str) and len(text) > 0


def test_resolve_gguf_caches(gguf_file, tmp_path, monkeypatch):
    import shutil

    path, _ = gguf_file
    p2 = str(tmp_path / "m.gguf")
    shutil.copy(path, p2)
    out1 = G.resolve_gguf(p2)
    mtime = __import__("os").path.getmtime(out1 + "/config.json")
    out2 = G.resolve_gguf(p2)
    assert out1 == out2
    assert __import__("os").path.getmtime(out2 + "/config.json") == mtime
