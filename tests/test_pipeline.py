"""Pipeline parallelism (parallel/pipeline.py) on the virtual CPU mesh.

Parity oracle: the pipelined forward/loss must match the plain
single-program forward_train / causal_lm_loss bit-for-tolerance — the GPipe
schedule is a pure re-scheduling of the same math.
"""
import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from localai_tpu.models.llama import (
    LlamaConfig, forward_train, init_params,
)
from localai_tpu.parallel.mesh import (
    MeshConfig, activate_mesh, build_mesh, shard_params,
)
from localai_tpu.parallel.pipeline import (
    make_pipeline_train_step, pipeline_forward_train, pipeline_loss,
    pipeline_specs,
)
from localai_tpu.train import causal_lm_loss, make_train_step

CFG = LlamaConfig(
    vocab_size=128, hidden_size=32, intermediate_size=64, num_layers=4,
    num_heads=4, num_kv_heads=2, head_dim=8, max_position=64,
    dtype="float32",
)


def _setup(data=1, pipe=4, batch=4, seqlen=12, seed=0):
    n = data * pipe
    mesh = build_mesh(MeshConfig(data=data, model=1, pipe=pipe),
                      jax.devices()[:n])
    params = init_params(CFG, jax.random.PRNGKey(seed))
    sharded = shard_params(params, pipeline_specs(CFG), mesh)
    tokens = jnp.asarray(
        np.random.default_rng(seed).integers(0, CFG.vocab_size,
                                             (batch, seqlen)), jnp.int32)
    return mesh, params, sharded, tokens


@pytest.mark.parametrize("data,pipe,n_micro", [(1, 4, 2), (1, 2, 4),
                                               (2, 4, 1), (2, 2, 2)])
def test_pipeline_forward_parity(data, pipe, n_micro):
    mesh, params, sharded, tokens = _setup(data, pipe)
    ref = forward_train(params, CFG, tokens)
    with activate_mesh(mesh):
        got = jax.jit(
            lambda p, t: pipeline_forward_train(p, CFG, t, mesh=mesh,
                                                n_micro=n_micro)
        )(sharded, tokens)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=2e-4, atol=2e-4)


def test_pipeline_loss_matches_reference_loss():
    mesh, params, sharded, tokens = _setup(1, 4)
    ref = float(causal_lm_loss(params, CFG, tokens))
    with activate_mesh(mesh):
        got = float(jax.jit(
            lambda p, t: pipeline_loss(p, CFG, t, mesh=mesh, n_micro=2)
        )(sharded, tokens))
    assert abs(got - ref) < 1e-4, (got, ref)


def test_pipeline_train_step_matches_dense_step():
    """One SGD step through the pipelined backward == one through the plain
    backward: same loss, same updated params (spot-checked leaves)."""
    mesh, params, sharded, tokens = _setup(1, 4, batch=4, seqlen=10)
    opt = optax.sgd(1e-2)

    dense_step = jax.jit(make_train_step(CFG, opt))
    d_params, _, d_loss = dense_step(params, opt.init(params), tokens)

    with activate_mesh(mesh):
        pipe_step = jax.jit(make_pipeline_train_step(CFG, opt, mesh, 2))
        p_params, _, p_loss = pipe_step(sharded, opt.init(sharded), tokens)

    assert abs(float(p_loss) - float(d_loss)) < 1e-4
    for key in ("wq", "w_down"):
        np.testing.assert_allclose(
            np.asarray(p_params["layers"][key]),
            np.asarray(d_params["layers"][key]), rtol=2e-3, atol=2e-3)
    np.testing.assert_allclose(np.asarray(p_params["embed"]),
                               np.asarray(d_params["embed"]),
                               rtol=2e-3, atol=2e-3)


def test_pipeline_rejects_bad_geometry():
    mesh, _, sharded, tokens = _setup(1, 4)
    with pytest.raises(ValueError, match="n_micro"):
        pipeline_loss(sharded, CFG, tokens, mesh=mesh, n_micro=3)
    cfg6 = LlamaConfig(
        vocab_size=128, hidden_size=32, intermediate_size=64, num_layers=6,
        num_heads=4, num_kv_heads=2, head_dim=8, max_position=64,
        dtype="float32")
    with pytest.raises(ValueError, match="stages"):
        pipeline_loss(sharded, cfg6, tokens, mesh=mesh, n_micro=1)
    nopipe = build_mesh(MeshConfig(data=1, model=4), jax.devices()[:4])
    with pytest.raises(ValueError, match="pipe"):
        pipeline_loss(sharded, CFG, tokens, mesh=nopipe, n_micro=1)
