"""Gallery / downloader / importer tests — all offline via file:// URIs
(reference tiers: core/gallery tests + pkg/downloader/uri_test.go)."""
import hashlib
import json
import os

import pytest
import yaml

from localai_tpu.downloader import download_file, resolve_uri
from localai_tpu.services import Gallery, GalleryService, install_model
from localai_tpu.services.importers import guess_model_config


def _sha(path):
    return hashlib.sha256(open(path, "rb").read()).hexdigest()


def test_resolve_uri_schemes():
    assert resolve_uri("huggingface://org/repo/model.safetensors") == \
        "https://huggingface.co/org/repo/resolve/main/model.safetensors"
    assert resolve_uri("github:owner/repo/path/file.yaml@dev") == \
        "https://raw.githubusercontent.com/owner/repo/dev/path/file.yaml"
    assert resolve_uri("https://x/y") == "https://x/y"


def test_download_file_sha256(tmp_path):
    src = tmp_path / "src.bin"
    src.write_bytes(b"hello artifact")
    dest = tmp_path / "out" / "dst.bin"
    download_file(f"file://{src}", str(dest), sha256=_sha(str(src)))
    assert dest.read_bytes() == b"hello artifact"
    with pytest.raises(ValueError, match="sha256 mismatch"):
        download_file(f"file://{src}", str(tmp_path / "bad.bin"),
                      sha256="0" * 64)


@pytest.fixture()
def gallery_fixture(tmp_path):
    """A gallery index + artifacts laid out on disk."""
    art = tmp_path / "artifacts"
    art.mkdir()
    (art / "config.json").write_text(json.dumps(
        {"architectures": ["LlamaForCausalLM"], "hidden_size": 64}))
    (art / "weights.safetensors").write_bytes(b"\x00" * 16)
    index = tmp_path / "index.yaml"
    index.write_text(yaml.safe_dump([{
        "name": "demo-model",
        "description": "test entry",
        "tags": ["llm"],
        "files": [
            {"filename": "demo-model/config.json",
             "uri": f"file://{art}/config.json",
             "sha256": _sha(str(art / "config.json"))},
            {"filename": "demo-model/weights.safetensors",
             "uri": f"file://{art}/weights.safetensors"},
        ],
        "config": {
            "backend": "llm",
            "context_size": 512,
            "parameters": {"model": "demo-model"},
        },
    }]))
    return index


def test_gallery_install(gallery_fixture, tmp_path):
    models = tmp_path / "models"
    g = Gallery([str(gallery_fixture)])
    assert "demo-model" in g.models()
    ypath = install_model(g, "demo-model", str(models))
    cfg = yaml.safe_load(open(ypath))
    assert cfg["name"] == "demo-model"
    assert cfg["context_size"] == 512
    assert (models / "demo-model" / "config.json").exists()
    # installed model is visible to the config loader
    from localai_tpu.config import ModelConfigLoader

    loader = ModelConfigLoader(str(models))
    assert loader.get("demo-model").context_size == 512


def test_gallery_service_job_queue(gallery_fixture, tmp_path):
    import time

    svc = GalleryService(Gallery([str(gallery_fixture)]),
                         str(tmp_path / "models"))
    svc.start()
    try:
        job = svc.submit("demo-model")
        deadline = time.monotonic() + 10
        while (svc.status[job]["state"] in ("queued", "processing")
               and time.monotonic() < deadline):
            time.sleep(0.05)
        assert svc.status[job]["state"] == "done", svc.status[job]
        bad = svc.submit("nonexistent")
        deadline = time.monotonic() + 10
        while (svc.status[bad]["state"] in ("queued", "processing")
               and time.monotonic() < deadline):
            time.sleep(0.05)
        assert svc.status[bad]["state"] == "error"
    finally:
        svc.stop()


def test_importer_guesses_llm(tmp_path):
    d = tmp_path / "ck"
    d.mkdir()
    (d / "config.json").write_text(json.dumps({
        "architectures": ["MistralForCausalLM"],
        "hidden_size": 4096, "max_position_embeddings": 32768,
    }))
    cfg = guess_model_config(str(d))
    assert cfg["backend"] == "llm"
    assert cfg["context_size"] == 8192  # capped
    assert cfg["template"]["use_tokenizer_template"] is True


def test_importer_small_model_embeddings(tmp_path):
    d = tmp_path / "ck"
    d.mkdir()
    (d / "config.json").write_text(json.dumps({
        "architectures": ["LlamaForCausalLM"], "hidden_size": 512,
    }))
    assert guess_model_config(str(d))["embeddings"] is True


def test_capability_detection_forced(monkeypatch):
    from localai_tpu.system import capabilities

    monkeypatch.setenv("LOCALAI_FORCE_CAPABILITY", "tpu-v5e")
    capabilities.detect_capability.cache_clear()
    assert capabilities.detect_capability() == "tpu-v5e"
    monkeypatch.delenv("LOCALAI_FORCE_CAPABILITY")
    capabilities.detect_capability.cache_clear()
    assert capabilities.detect_capability() == "cpu"  # tests force CPU


def test_gallery_path_traversal_rejected(gallery_fixture, tmp_path):
    """Untrusted index filenames must stay confined to the models dir
    (reference verifyPath; an upstream CVE class)."""
    models = tmp_path / "models"
    g = Gallery([str(gallery_fixture)])
    gm = g.get("demo-model")
    for evil in ("../escape.yaml", "/etc/cron.d/x", "a/../../b"):
        gm.files = [{"filename": evil, "uri": "file:///dev/null"}]
        with pytest.raises(ValueError, match="path traversal"):
            install_model(g, "demo-model", str(models))
    # a malicious model NAME must not escape either (YAML path)
    gm.files = []
    gm.name = "../../evil"
    g._models["../../evil"] = gm
    with pytest.raises(ValueError, match="path traversal"):
        install_model(g, "../../evil", str(models))
