"""LLaVA multimodal chat path: CLIP tower parity, embedding injection, and
end-to-end greedy parity with HF LlavaForConditionalGeneration."""
import os

import numpy as np
import pytest
import torch

from localai_tpu.engine import Engine, EngineConfig, GenRequest
from localai_tpu.engine.loader import load_config, load_params, load_tokenizer
from localai_tpu.ops.sampling import SamplingParams

from fixtures import tiny_checkpoint

IMG_TOK = 100


@pytest.fixture(scope="session")
def llava_ckpt(tmp_path_factory):
    from transformers import (
        CLIPVisionConfig, LlamaConfig as HFLlama, LlavaConfig,
        LlavaForConditionalGeneration,
    )

    vc = CLIPVisionConfig(
        hidden_size=32, intermediate_size=64, num_hidden_layers=3,
        num_attention_heads=4, image_size=28, patch_size=14,
        projection_dim=32)
    tc = HFLlama(
        vocab_size=128, hidden_size=48, intermediate_size=96,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=128)
    cfg = LlavaConfig(
        vision_config=vc, text_config=tc, image_token_index=IMG_TOK,
        vision_feature_layer=-2, vision_feature_select_strategy="default")
    torch.manual_seed(0)
    m = LlavaForConditionalGeneration(cfg).eval()
    d = str(tmp_path_factory.mktemp("llava"))
    m.save_pretrained(d, safe_serialization=True)
    # backend LoadModel needs a tokenizer; the gRPC test drives prompt_ids,
    # so any tokenizer file works — borrow the tiny fixture's
    import shutil

    src = tiny_checkpoint(tmp_path_factory)
    for f in ("tokenizer.json", "tokenizer_config.json"):
        shutil.copy(os.path.join(src, f), os.path.join(d, f))
    return d


def _hf(llava_ckpt):
    from transformers import LlavaForConditionalGeneration

    return LlavaForConditionalGeneration.from_pretrained(
        llava_ckpt, torch_dtype=torch.float32).eval()


def test_vision_tower_projector_parity(llava_ckpt):
    """encode_images == HF get_image_features on the same checkpoint."""
    from localai_tpu.models.llava import encode_images, load_vision

    vcfg, vparams, meta = load_vision(llava_ckpt)
    px = np.random.default_rng(0).standard_normal((2, 3, 28, 28)).astype(
        np.float32)
    ours = np.asarray(encode_images(vparams, vcfg, meta, px))
    m = _hf(llava_ckpt)
    with torch.no_grad():
        ref = m.get_image_features(pixel_values=torch.tensor(px))
    ref = np.stack([r.numpy() for r in ref])
    np.testing.assert_allclose(ours, ref, rtol=2e-4, atol=2e-4)


def test_expand_image_tokens():
    from localai_tpu.models.llava import expand_image_tokens

    ids, pos = expand_image_tokens([1, IMG_TOK, 2, IMG_TOK, 3], 2, 4,
                                   IMG_TOK)
    assert ids == [1] + [IMG_TOK] * 4 + [2] + [IMG_TOK] * 4 + [3]
    assert pos.tolist() == [1, 2, 3, 4, 6, 7, 8, 9]
    with pytest.raises(ValueError, match="placeholder"):
        expand_image_tokens([1, 2], 1, 4, IMG_TOK)


def test_inject_identity_matches_token_prompt(tmp_path_factory):
    """Injecting embed-table rows at prompt positions must reproduce the pure
    token request bit-for-tolerance — the engine-side invariant the image
    path relies on (image features are just rows the embed table never
    had)."""
    ckpt = tiny_checkpoint(tmp_path_factory)
    cfg = load_config(ckpt, dtype="float32")
    params = load_params(ckpt, cfg)
    tok = load_tokenizer(ckpt)
    embed = np.asarray(params["embed"], np.float32)

    prompt = tok.encode("the quick brown fox jumps over")
    sub = prompt[2:5]

    def run(mm):
        eng = Engine(cfg, params, tok, EngineConfig(
            max_slots=2, max_context=128, prefill_buckets=(32,)))
        req = GenRequest(list(prompt), SamplingParams(temperature=0.0),
                         max_tokens=8, ignore_eos=True)
        if mm:
            req.mm_embeds = embed[sub]
            req.mm_positions = np.arange(2, 5)
        return [o.token_id for o in eng.generate(req)]

    assert run(False) == run(True)


def test_inject_identity_paged_kv(tmp_path_factory):
    """Inject invariant on a PAGED engine: image-feature injection and the
    block-table cache compose."""
    ckpt = tiny_checkpoint(tmp_path_factory)
    cfg = load_config(ckpt, dtype="float32")
    params = load_params(ckpt, cfg)
    tok = load_tokenizer(ckpt)
    embed = np.asarray(params["embed"], np.float32)
    prompt = tok.encode("the quick brown fox jumps over")

    def run(mm):
        eng = Engine(cfg, params, tok, EngineConfig(
            max_slots=2, max_context=128, prefill_buckets=(32,),
            kv_pages=6))
        req = GenRequest(list(prompt), SamplingParams(temperature=0.0),
                         max_tokens=8, ignore_eos=True)
        if mm:
            req.mm_embeds = embed[prompt[2:5]]
            req.mm_positions = np.arange(2, 5)
        return [o.token_id for o in eng.generate(req)]

    assert run(False) == run(True)


def test_inject_identity_chunked_prefill(tmp_path_factory):
    """Same invariant through the chunked-extend path (prompt > bucket)."""
    ckpt = tiny_checkpoint(tmp_path_factory)
    cfg = load_config(ckpt, dtype="float32")
    params = load_params(ckpt, cfg)
    tok = load_tokenizer(ckpt)
    embed = np.asarray(params["embed"], np.float32)

    prompt = (tok.encode("pack my box with five dozen liquor jugs") * 4)[:40]
    positions = np.asarray([3, 14, 15, 16, 30], np.int64)

    def run(mm):
        eng = Engine(cfg, params, tok, EngineConfig(
            max_slots=2, max_context=128, prefill_buckets=(16,),
            prefill_chunk=16))
        req = GenRequest(list(prompt), SamplingParams(temperature=0.0),
                         max_tokens=6, ignore_eos=True)
        if mm:
            req.mm_embeds = embed[[prompt[i] for i in positions]]
            req.mm_positions = positions
        return [o.token_id for o in eng.generate(req)]

    assert run(False) == run(True)


def test_images_through_grpc_backend(llava_ckpt):
    """Process-boundary path: ModelOptions(model=llava dir) loads the vision
    tower; PredictOptions.images (base64 PNG) + a placeholder prompt stream
    real tokens back — the reference's mmproj/vLLM-multimodal serving shape
    (PredictOptions.images, backend.proto:131)."""
    import base64
    import io

    from PIL import Image

    from localai_tpu.backend.client import BackendClient
    from localai_tpu.backend.server import serve

    server, servicer, port = serve("127.0.0.1:0", "llm")
    client = BackendClient(f"127.0.0.1:{port}")
    try:
        assert client.wait_ready(attempts=20, sleep=0.1)
        r = client.load_model(model=llava_ckpt, dtype="float32", parallel=2,
                              context_size=128, prefill_buckets=[16, 32])
        assert r.success, r.message
        buf = io.BytesIO()
        Image.new("RGB", (40, 30), (200, 40, 40)).save(buf, format="PNG")
        b64 = base64.b64encode(buf.getvalue()).decode()
        reply = client.predict(prompt_ids=[1, 5, IMG_TOK, 9], tokens=6,
                               temperature=0.0, ignore_eos=True,
                               images=[b64])
        assert reply.tokens == 6 and len(reply.token_ids) == 6
        # same request, no image → the placeholder stays one token and the
        # injected features are absent, so the continuation must differ
        # (with these random weights); mainly: both paths serve correctly
        reply2 = client.predict(prompt_ids=[1, 5, IMG_TOK, 9], tokens=6,
                                temperature=0.0, ignore_eos=True)
        assert len(reply2.token_ids) == 6
        # image on a vision-less model errors cleanly (INVALID_ARGUMENT)
    finally:
        client.close()
        servicer.shutdown()
        server.stop(grace=1)


def test_http_image_content_extraction():
    """OpenAI vision content parts → flattened text + images list
    (server/http.py _extract_images; reference: content-part handling in
    core/http/endpoints/openai chat + utils base64)."""
    from localai_tpu.server.http import API

    msgs = [
        {"role": "system", "content": "be brief"},
        {"role": "user", "content": [
            {"type": "text", "text": "what is this?"},
            {"type": "image_url",
             "image_url": {"url": "data:image/png;base64,QUJD"}},
        ]},
    ]
    out, images = API._extract_images(msgs)
    assert out[0] == msgs[0]
    assert out[1]["content"] == "what is this?\n<image>"
    assert images == ["data:image/png;base64,QUJD"]

    # plain-string content and raw-base64 urls pass through
    out2, images2 = API._extract_images(
        [{"role": "user", "content": [
            {"type": "image_url", "image_url": {"url": "QUJD"}},
            {"type": "text", "text": "hi"}]}])
    assert out2[0]["content"] == "<image>\nhi"
    assert images2 == ["QUJD"]


def test_llava_greedy_parity_with_hf(llava_ckpt, tmp_path_factory):
    """Full path: pixels → tower → projector → injected prefill → greedy
    decode == HF LlavaForConditionalGeneration.generate."""
    from localai_tpu.models.llava import (
        encode_images, expand_image_tokens, load_vision,
    )

    lcfg = load_config(llava_ckpt, dtype="float32")
    lparams = load_params(llava_ckpt, lcfg, dtype="float32")
    vcfg, vparams, meta = load_vision(llava_ckpt)

    px = np.random.default_rng(1).standard_normal((1, 3, 28, 28)).astype(
        np.float32)
    feats = np.asarray(encode_images(vparams, vcfg, meta, px),
                       np.float32)                      # [1, 4, 48]
    prompt = [1, 5, IMG_TOK, 9, 11, 7]
    ids, positions = expand_image_tokens(prompt, 1, feats.shape[1], IMG_TOK)

    m = _hf(llava_ckpt)
    with torch.no_grad():
        out = m.generate(
            input_ids=torch.tensor([ids]),
            attention_mask=torch.ones((1, len(ids)), dtype=torch.long),
            pixel_values=torch.tensor(px),
            max_new_tokens=8, do_sample=False, pad_token_id=0,
            eos_token_id=None)
    ref = out[0].tolist()[len(ids):]

    eng = Engine(lcfg, lparams, None, EngineConfig(
        max_slots=2, max_context=128, prefill_buckets=(16, 32)))
    req = GenRequest(ids, SamplingParams(temperature=0.0), max_tokens=8,
                     ignore_eos=True, mm_embeds=feats[0],
                     mm_positions=positions)
    ours = [o.token_id for o in eng.generate(req)]
    assert ours == ref
