"""Tiny MCP stdio server for tests: one `add` tool; records calls to the
file given in argv[1] (newline-delimited JSON-RPC per the MCP stdio
transport)."""
import json
import sys


def main():
    log_path = sys.argv[1] if len(sys.argv) > 1 else None
    for line in sys.stdin:
        line = line.strip()
        if not line:
            continue
        msg = json.loads(line)
        method = msg.get("method", "")
        if "id" not in msg:
            continue                       # notification
        if method == "initialize":
            result = {"protocolVersion": "2024-11-05",
                      "capabilities": {"tools": {}},
                      "serverInfo": {"name": "test-mcp", "version": "1"}}
        elif method == "tools/list":
            result = {"tools": [{
                "name": "add",
                "description": "Add two integers",
                "inputSchema": {"type": "object", "properties": {
                    "a": {"type": "integer"}, "b": {"type": "integer"}},
                    "required": ["a", "b"]},
            }]}
        elif method == "tools/call":
            params = msg.get("params", {})
            if log_path:
                with open(log_path, "a") as f:
                    f.write(json.dumps(params) + "\n")
            args = params.get("arguments", {})
            try:
                total = int(args.get("a", 0)) + int(args.get("b", 0))
                result = {"content": [{"type": "text", "text": str(total)}]}
            except Exception as e:
                result = {"content": [{"type": "text", "text": str(e)}],
                          "isError": True}
        else:
            print(json.dumps({"jsonrpc": "2.0", "id": msg["id"],
                              "error": {"code": -32601,
                                        "message": "unknown method"}}),
                  flush=True)
            continue
        print(json.dumps({"jsonrpc": "2.0", "id": msg["id"],
                          "result": result}), flush=True)


if __name__ == "__main__":
    main()
