"""Slot prompt-cache: a freed slot's KV prefix is reused by a new request
sharing the prompt prefix (llama.cpp prompt/slot cache role,
reference backend.proto:136-142)."""
import jax
import jax.numpy as jnp
import pytest

from localai_tpu.engine import Engine, EngineConfig
from localai_tpu.engine.engine import GenRequest, SamplingParams
from localai_tpu.models.llama import LlamaConfig, init_params

CFG = LlamaConfig(vocab_size=128, hidden_size=64, intermediate_size=128,
                  num_layers=2, num_heads=4, num_kv_heads=2, head_dim=16,
                  max_position=256, dtype="float32")


def _engine(**kw):
    params = init_params(CFG, jax.random.PRNGKey(0), jnp.float32)
    return Engine(CFG, params, None, EngineConfig(
        max_slots=2, max_context=128, prefill_buckets=(64,),
        prefill_chunk=64, **kw))


def _run(eng, prompt, max_tokens=6, seed=3):
    rid, q = eng.submit(GenRequest(
        prompt_ids=prompt, max_tokens=max_tokens, ignore_eos=True,
        params=SamplingParams(temperature=0.0, seed=seed)))
    toks = []
    while True:
        o = q.get(timeout=60)
        toks.append(o.token_id)
        if o.finished:
            return toks


def test_prefix_reuse_and_parity():
    base = list(range(1, 41))          # 40-token shared prefix
    p1 = base + [50, 51]
    p2 = base + [60, 61, 62]

    cold = _engine(prompt_cache=False)
    cold.start()
    try:
        _run(cold, p1)
        ref = _run(cold, p2)
        assert cold.metrics["prompt_tokens_reused"] == 0
    finally:
        cold.stop()

    warm = _engine(prompt_cache=True)
    warm.start()
    try:
        _run(warm, p1)
        out = _run(warm, p2)
        assert warm.metrics["prompt_cache_hits"] == 1
        assert warm.metrics["prompt_tokens_reused"] == len(base)
        # identical outputs: reused KV must be byte-equivalent context
        assert out == ref
    finally:
        warm.stop()


def test_short_prefix_not_reused():
    eng = _engine(prompt_cache=True, prompt_cache_min=16)
    eng.start()
    try:
        _run(eng, [1, 2, 3, 4, 5])
        _run(eng, [1, 2, 3, 9, 9])     # 3-token prefix < threshold
        assert eng.metrics["prompt_cache_hits"] == 0
    finally:
        eng.stop()


def test_reuse_caps_at_prompt_minus_one():
    """Identical prompt resubmitted: at most n-1 tokens reuse (the final
    token must prefill to produce fresh logits)."""
    eng = _engine(prompt_cache=True)
    p = list(range(1, 33))
    eng.start()
    try:
        a = _run(eng, p)
        b = _run(eng, p)
        assert eng.metrics["prompt_tokens_reused"] == len(p) - 1
        assert a == b                   # same prompt, temp 0 → same output
    finally:
        eng.stop()


def test_cold_admission_spares_warm_slot():
    """Alternating tenants with max_slots=2: a cache-miss admission must not
    evict the other tenant's warm prefix."""
    eng = _engine(prompt_cache=True)
    a = list(range(1, 40)) + [100]
    b = list(range(60, 99)) + [101]
    eng.start()
    try:
        _run(eng, a)                       # warms slot with A's prefix
        _run(eng, b)                       # cold: must take the OTHER slot
        _run(eng, list(range(1, 40)) + [102])   # A again → hit
        assert eng.metrics["prompt_cache_hits"] >= 1
        assert eng.metrics["prompt_tokens_reused"] >= 39
    finally:
        eng.stop()
