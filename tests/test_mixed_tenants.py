"""Cross-feature tenancy: one paged engine concurrently serving a grammar
slot, a multimodal (injected-embedding) slot, a wide-top_k slot, and a
context-shift slot — the interactions none of the per-feature suites cover
together. Every stream must complete with its own contract intact, and the
deterministic tenants must match their solo runs (no cross-slot bleed)."""
import threading

import numpy as np
import pytest

from localai_tpu.engine import (
    Engine, EngineConfig, GenRequest, Tokenizer, load_config, load_params,
)
from localai_tpu.functions.grammars import JSON_GRAMMAR
from localai_tpu.ops.sampling import SamplingParams

from fixtures import tiny_checkpoint


@pytest.fixture(scope="module")
def loaded(tmp_path_factory):
    ckpt = tiny_checkpoint(tmp_path_factory, max_position=512)
    cfg = load_config(ckpt, dtype="float32")
    params = load_params(ckpt, cfg)
    tok = Tokenizer.from_dir(ckpt)
    return cfg, params, tok


def _reqs(cfg, params, tok):
    embed = np.asarray(params["embed"], np.float32)
    prompt = tok.encode("the quick brown fox")
    mm = GenRequest(list(prompt), SamplingParams(temperature=0.0),
                    max_tokens=12, ignore_eos=True)
    mm.mm_embeds = embed[prompt[1:3]]
    mm.mm_positions = np.arange(1, 3)
    return {
        "grammar": GenRequest(tok.encode("emit json:"),
                              SamplingParams(temperature=0.0),
                              max_tokens=24, grammar=JSON_GRAMMAR),
        "mm": mm,
        "wide": GenRequest(tok.encode("pack my box"),
                           SamplingParams(temperature=0.9, top_k=200,
                                          seed=17),
                           max_tokens=12, ignore_eos=True),
        "shift": GenRequest(tok.encode("sphinx of black quartz"),
                            SamplingParams(temperature=0.0),
                            max_tokens=600, ignore_eos=True,
                            context_shift=True),
    }


def _run_concurrent(cfg, params, tok, reqs):
    eng = Engine(cfg, params, tok, EngineConfig(
        max_slots=4, max_context=512, prefill_buckets=(32,),
        prefill_chunk=64, kv_pages=18))
    eng.start()
    out = {}

    def drive(name, req):
        _, q = eng.submit(req)
        ids, text = [], []
        while True:
            o = q.get(timeout=600)
            if o.token_id >= 0:
                ids.append(o.token_id)
            if o.text:
                text.append(o.text)
            if o.finished:
                out[name] = (ids, "".join(text), o.finish_reason)
                return

    ths = [threading.Thread(target=drive, args=(n, r))
           for n, r in reqs.items()]
    [t.start() for t in ths]
    [t.join(timeout=900) for t in ths]
    eng.stop()
    return out


def test_mixed_tenants_share_one_paged_engine(loaded):
    cfg, params, tok = loaded
    out = _run_concurrent(cfg, params, tok, _reqs(cfg, params, tok))
    assert set(out) == {"grammar", "mm", "wide", "shift"}

    # grammar tenant: EVERY emitted token must be grammar-conformant (the
    # PDA accepts the whole sequence), truncated or not; a clean stop must
    # also parse as JSON
    import json as _json

    from localai_tpu.functions.matcher import GrammarCache

    g_ids, g_text, g_reason = out["grammar"]
    assert g_ids, "grammar tenant emitted nothing"
    matcher = GrammarCache(tok).get(JSON_GRAMMAR).state()
    for t in g_ids:
        if tok.eos_ids and t in tok.eos_ids:
            break
        assert matcher.accept(t), f"token {t} violates the grammar"
    if g_reason == "stop" and g_text:
        _json.loads(g_text)

    # context-shift tenant sailed past the cap
    s_ids, _, s_reason = out["shift"]
    assert s_reason == "length" and len(s_ids) == 600

    # deterministic tenants reproduce their SOLO runs (no cross-slot bleed)
    for name in ("mm", "wide"):
        solo = _run_concurrent(cfg, params, tok,
                               {name: _reqs(cfg, params, tok)[name]})
        assert out[name][0] == solo[name][0], f"{name} diverged under load"
