"""Speculative decoding: greedy output must EXACTLY match the target model's
own greedy decode regardless of draft quality; perfect draft → 100%
acceptance."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from localai_tpu.engine.speculative import SpeculativeDecoder
from localai_tpu.models.llama import LlamaConfig, init_params
from localai_tpu.ops.attention import mha_extend, mha_decode


TARGET = LlamaConfig(vocab_size=128, hidden_size=64, intermediate_size=128,
                     num_layers=2, num_heads=4, num_kv_heads=2, head_dim=16,
                     max_position=256, dtype="float32")
DRAFT = LlamaConfig(vocab_size=128, hidden_size=32, intermediate_size=64,
                    num_layers=1, num_heads=2, num_kv_heads=2, head_dim=16,
                    max_position=256, dtype="float32")


@pytest.fixture(scope="module")
def models():
    return (init_params(TARGET, jax.random.PRNGKey(0)),
            init_params(DRAFT, jax.random.PRNGKey(7)))


def _greedy_reference(params, cfg, prompt, n_new):
    from localai_tpu.engine import Engine, EngineConfig, GenRequest
    from localai_tpu.ops.sampling import SamplingParams

    eng = Engine(cfg, params, None, EngineConfig(
        max_slots=1, max_context=256, prefill_buckets=(32,)))
    return [o.token_id for o in eng.generate(GenRequest(
        prompt, SamplingParams(temperature=0.0), max_tokens=n_new,
        ignore_eos=True))]


def test_extend_matches_decode_chain(models):
    """extend() over a window == sequential decode_step calls."""
    from localai_tpu.models.llama import (
        decode_step, extend, init_kv_cache, prefill,
    )
    from localai_tpu.ops.rope import rope_table

    params, _ = models
    cfg = TARGET
    T = 64
    cos, sin = rope_table(cfg.rope, T)
    prompt = jnp.array([[3, 14, 15, 9, 2]], jnp.int32)
    n = prompt.shape[1]

    kc, vc = init_kv_cache(cfg, 1, T)
    _, kc, vc = prefill(params, cfg, prompt, jnp.array([n]), cos, sin,
                        kc, vc, jnp.array([0]))
    window = jnp.array([[7, 21, 4]], jnp.int32)
    elogits, kc2, vc2 = extend(params, cfg, window, jnp.array([n]),
                               cos, sin, kc, vc)

    # sequential reference
    kc3, vc3 = kc, vc
    seq_logits = []
    for i in range(3):
        dl, kc3, vc3 = decode_step(params, cfg, window[:, i],
                                   jnp.array([n + i]), cos, sin, kc3, vc3)
        seq_logits.append(np.asarray(dl[0]))
    np.testing.assert_allclose(np.asarray(elogits[0]), np.stack(seq_logits),
                               rtol=2e-4, atol=2e-4)


def test_greedy_spec_equals_target_greedy(models):
    params_t, params_d = models
    prompt = [3, 14, 15, 9, 2, 6]
    ref = _greedy_reference(params_t, TARGET, prompt, 16)
    dec = SpeculativeDecoder(TARGET, params_t, DRAFT, params_d, gamma=4,
                             max_context=256)
    out = dec.generate(prompt, 16, temperature=0.0)
    assert out == ref
    assert dec.stats.proposed > 0


def test_perfect_draft_full_acceptance(models):
    params_t, _ = models
    dec = SpeculativeDecoder(TARGET, params_t, TARGET, params_t, gamma=4,
                             max_context=256)
    prompt = [5, 9, 2, 7]
    ref = _greedy_reference(params_t, TARGET, prompt, 12)
    out = dec.generate(prompt, 12, temperature=0.0)
    assert out == ref
    assert dec.stats.acceptance_rate == 1.0


def test_sampled_spec_runs_and_matches_vocab(models):
    params_t, params_d = models
    dec = SpeculativeDecoder(TARGET, params_t, DRAFT, params_d, gamma=3,
                             max_context=256)
    out = dec.generate([1, 2, 3], 20, temperature=0.8, seed=5)
    assert len(out) == 20
    assert all(0 <= t < TARGET.vocab_size for t in out)
