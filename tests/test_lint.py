"""localai-lint: per-rule positive/negative snippet coverage + the runtime
tripwires (transfer guard, compile-count guard).

Every static rule gets at least one snippet it MUST catch and one it must
NOT (including a pragma'd case). Two snippets reconstruct shipped bug
classes: the PR 4 watchdog holding the model-map lock across Popen.wait, and
a `.item()` in the decode hot loop.
"""
import textwrap

import numpy as np
import pytest

from tools.lint import Config, run_source

HOT = "localai_tpu/engine/fake_hot.py"     # inside the hot-path scope
COLD = "localai_tpu/server/fake_cold.py"   # outside it


def lint(src: str, path: str = HOT, **cfg):
    return run_source(textwrap.dedent(src), path, Config(**cfg))


def rules_of(violations):
    return [v.rule for v in violations]


# ------------------------------------------------------------ family (a)

def test_item_in_hot_loop_caught():
    """The hot-path `.item()` reconstruction: one stray scalar read per
    decode step stalls the fused pipeline."""
    src = """
    import jax.numpy as jnp

    def decode_loop(fn, state):
        while True:
            tokens, state = fn(state)
            t = tokens[0].item()
            yield t
    """
    vs = lint(src)
    assert "host-sync-item" in rules_of(vs)


def test_item_outside_hot_path_allowed():
    vs = lint("x = arr.item()\n", path=COLD)
    assert "host-sync-item" not in rules_of(vs)


def test_item_pragma_suppresses():
    src = """
    def f(arr):
        return arr.item()  # lint: allow(host-sync-item) — once per request
    """
    assert rules_of(lint(src)) == []


def test_cast_on_device_value_caught_and_host_value_allowed():
    src = """
    import jax.numpy as jnp

    def f(x):
        y = jnp.argmax(x)
        bad = int(y)
        n = int("42")          # host value: fine
        m = int(y.shape[0])    # metadata: fine
        return bad, n, m
    """
    vs = [v for v in lint(src) if v.rule == "host-sync-cast"]
    assert len(vs) == 1


def test_cast_direct_jnp_call_caught():
    src = """
    import jax.numpy as jnp

    def f(logits):
        return float(jnp.max(logits))
    """
    assert "host-sync-cast" in rules_of(lint(src))


def test_asarray_on_device_caught_device_get_allowed():
    src = """
    import jax, numpy as np, jax.numpy as jnp

    def f(x):
        y = jnp.exp(x)
        bad = np.asarray(y)
        good = np.asarray(jax.device_get(y))
        return bad, good
    """
    vs = [v for v in lint(src) if v.rule == "host-sync-asarray"]
    assert len(vs) == 1


def test_asarray_on_host_value_allowed():
    src = """
    import numpy as np

    def f(ids):
        lens = np.asarray([len(i) for i in ids], np.int32)
        return lens
    """
    assert "host-sync-asarray" not in rules_of(lint(src))


def test_block_until_ready_caught_in_hot_path_only():
    src = "import jax\n\ndef f(x):\n    return jax.block_until_ready(x)\n"
    assert "sync-block-until-ready" in rules_of(lint(src))
    assert "sync-block-until-ready" not in rules_of(lint(src, path=COLD))
    assert "sync-block-until-ready" not in rules_of(
        lint(src, path="tools/profile_thing.py"))


def test_traced_branch_caught():
    src = """
    import jax, jax.numpy as jnp

    def step(params, x):
        if x > 0:
            return x + 1
        return x

    step_fn = jax.jit(step)
    """
    vs = [v for v in lint(src) if v.rule == "traced-branch"]
    assert len(vs) == 1
    assert "'x'" in vs[0].message


def test_traced_branch_static_and_meta_allowed():
    src = """
    import jax, jax.numpy as jnp

    def step(params, x, flag, mask=None):
        if flag:                  # static → python bool, fine
            x = x * 2
        if mask is not None:      # identity test, fine
            x = x + mask
        if x.shape[0] > 4:        # metadata, fine
            x = x[:4]
        return x

    step_fn = jax.jit(step, static_argnames=("flag",))
    """
    assert "traced-branch" not in rules_of(lint(src))


def test_jit_arg_retrace_caught_and_wrapped_allowed():
    src = """
    import jax, jax.numpy as jnp

    def f(x):
        return x

    f_fn = jax.jit(f)

    def caller(ids):
        bad = f_fn([1, 2, 3])
        also_bad = f_fn(len(ids))
        good = f_fn(jnp.asarray(ids))
        return bad, also_bad, good
    """
    vs = [v for v in lint(src, path=COLD) if v.rule == "jit-arg-retrace"]
    assert len(vs) == 2


def test_jit_static_kw_not_flagged():
    src = """
    import jax

    def f(x, steps):
        return x

    f_fn = jax.jit(f, static_argnames=("steps",))

    def caller(x, n):
        return f_fn(x, steps=len(str(n)))
    """
    assert "jit-arg-retrace" not in rules_of(lint(src, path=COLD))


def test_shape_from_len_caught():
    src = """
    import jax.numpy as jnp

    def admit(prompt_ids):
        buf = jnp.zeros((1, len(prompt_ids)), jnp.int32)
        fixed = jnp.zeros((1, 64), jnp.int32)   # bucketed: fine
        return buf, fixed
    """
    vs = [v for v in lint(src) if v.rule == "shape-from-len"]
    assert len(vs) == 1


# ------------------------------------------------------------ family (b)

def test_watchdog_lock_across_wait_reconstruction():
    """The PR 4 bug, reconstructed: the seed watchdog reaped backends while
    holding the model-map lock, so every load()/get() froze for up to the
    full Popen.wait timeout."""
    src = """
    import subprocess, threading, time

    class Manager:
        def watchdog_tick(self):
            with self._lock:
                for h in self._models.values():
                    if h.busy:
                        h.proc.terminate()
                        h.proc.wait(timeout=10)
    """
    vs = [v for v in lint(src, path="localai_tpu/core/fake_mgr.py")
          if v.rule == "lock-across-blocking"]
    assert len(vs) == 1
    assert ".wait()" in vs[0].message


def test_lock_then_blocking_outside_allowed():
    src = """
    import time

    class Manager:
        def watchdog_tick(self):
            with self._lock:
                handles = list(self._models.values())
            for h in handles:
                h.proc.wait(timeout=10)
                time.sleep(0.1)
    """
    assert "lock-across-blocking" not in rules_of(
        lint(src, path="localai_tpu/core/fake_mgr.py"))


def test_sleep_and_rpc_under_lock_caught():
    src = """
    import time

    def f(self, cfg):
        with self._model_lock(cfg.name):
            time.sleep(1.0)
            self.client.health(timeout=5.0)
    """
    vs = [v for v in lint(src, path=COLD)
          if v.rule == "lock-across-blocking"]
    assert len(vs) == 2


def test_string_and_path_join_not_flagged():
    src = """
    import os

    def f(self, parts):
        with self._lock:
            a = os.path.join(*parts)
            b = ", ".join(parts)
        return a, b
    """
    assert "lock-across-blocking" not in rules_of(lint(src, path=COLD))


def test_mark_busy_without_finally_caught():
    src = """
    def handler(handle, opts):
        handle.mark_busy()
        r = handle.client.predict(opts)
        handle.mark_idle()
        return r
    """
    vs = [v for v in lint(src, path=COLD)
          if v.rule == "acquire-release-finally"]
    assert len(vs) == 1


def test_mark_busy_with_finally_allowed():
    src = """
    def handler(handle, opts):
        handle.mark_busy()
        try:
            return handle.client.predict(opts)
        finally:
            handle.mark_idle()
    """
    assert "acquire-release-finally" not in rules_of(lint(src, path=COLD))


def test_mark_busy_never_released_caught():
    src = """
    def handler(handle):
        handle.mark_busy()
        return handle.port
    """
    assert "acquire-release-finally" in rules_of(lint(src, path=COLD))


def test_span_begin_cross_function_release_allowed():
    # the engine pattern: span opened at admission, finished at slot release
    # (a different function) — must NOT flag
    src = """
    def admit(self, req):
        self.span = self.tracer.begin("engine.request")

    def release(self, slot):
        self.tracer.finish(self.span)
    """
    assert "acquire-release-finally" not in rules_of(lint(src, path=COLD))


# ------------------------------------------------------------ family (c)

def test_inline_partition_spec_caught():
    src = """
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    def place(mesh, x):
        return jax.device_put(x, NamedSharding(mesh, P(None, "model")))
    """
    assert "sharding-spec-source" in rules_of(lint(src, path=COLD))


def test_sourced_and_replicated_specs_allowed():
    src = """
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    def place(mesh, x, cfg):
        a = jax.device_put(x, NamedSharding(mesh, kv_cache_spec()))
        b = jax.device_put(x, NamedSharding(mesh, P(None, None)))
        c = jax.device_put(x, safe_sharding(mesh, P("data"), x.shape))
        return a, b, c
    """
    assert "sharding-spec-source" not in rules_of(lint(src, path=COLD))


def test_shard_map_inline_specs_caught():
    src = """
    from jax.experimental.shard_map import shard_map
    from jax.sharding import PartitionSpec as P

    def wrap(mesh, body):
        return shard_map(body, mesh=mesh, in_specs=(P("model"),),
                         out_specs=P("model"))
    """
    vs = [v for v in lint(src, path=COLD)
          if v.rule == "sharding-spec-source"]
    assert len(vs) >= 1


def test_pb2_direct_import_caught_and_shim_allowed():
    bad = "from localai_tpu.backend import backend_pb2\n"
    assert "pb2-direct-import" in rules_of(lint(bad, path=COLD))
    assert "pb2-direct-import" in rules_of(
        lint("import backend_pb2\n", path=COLD))
    # the shim itself and the generator are exempt
    assert "pb2-direct-import" not in rules_of(
        lint("import backend_pb2\n", path="localai_tpu/backend/pb.py"))
    # google runtime modules are upstream, not ours
    assert "pb2-direct-import" not in rules_of(
        lint("from google.protobuf import descriptor_pb2\n", path=COLD))


def test_unregistered_marker_caught_registered_allowed():
    src = """
    import pytest

    @pytest.mark.slow
    @pytest.mark.made_up_lane
    def test_x():
        pass
    """
    vs = lint(src, path="tests/fake_test.py",
              registered_markers=frozenset({"slow"}))
    marker_vs = [v for v in vs if v.rule == "pytest-marker-registered"]
    assert len(marker_vs) == 1
    assert "made_up_lane" in marker_vs[0].message


def test_repo_markers_all_registered():
    """The live tree's markers must be registered (satellite: marker
    hygiene). Runs the real rule over the real tests/ directory."""
    from tools.lint import run_paths

    vs = run_paths(["tests"], Config(select=("pytest-marker-registered",)))
    assert vs == [], [v.render() for v in vs]


# ------------------------------------------------------------ pragma + CLI

def test_bad_pragma_rule_name_is_itself_flagged():
    src = "x = 1  # lint: allow(no-such-rule)\n"
    assert "bad-pragma" in rules_of(lint(src, path=COLD))


def test_pragma_standalone_covers_next_statement():
    src = """
    import jax, numpy as np, jax.numpy as jnp

    def f(x):
        y = jnp.exp(x)
        # lint: allow(host-sync-asarray) — test reason
        z = np.asarray(
            y)
        return z
    """
    assert "host-sync-asarray" not in rules_of(lint(src))


def test_tree_lints_clean():
    """The acceptance gate, as a test: the shipped tree has zero unsuppressed
    violations. Keeps `python -m tools.lint` green without waiting for CI."""
    from tools.lint import run_paths

    vs = run_paths(["localai_tpu", "tools", "tests"])
    assert vs == [], "\n".join(v.render() for v in vs)


def test_cli_exit_codes(tmp_path):
    import subprocess
    import sys

    bad = tmp_path / "localai_tpu" / "engine"
    bad.mkdir(parents=True)
    (tmp_path / "pyproject.toml").write_text("[project]\nname='x'\n")
    (bad / "hot.py").write_text("def f(a):\n    return a.item()\n")
    import os

    env = dict(os.environ, PYTHONPATH=os.getcwd())
    r = subprocess.run(
        [sys.executable, "-m", "tools.lint", str(tmp_path)],
        capture_output=True, text=True, env=env, cwd=os.getcwd())
    assert r.returncode == 1
    assert "host-sync-item" in r.stdout
    r2 = subprocess.run(
        [sys.executable, "-m", "tools.lint", "--list-rules"],
        capture_output=True, text=True, env=env, cwd=os.getcwd())
    assert r2.returncode == 0 and "lock-across-blocking" in r2.stdout


# ------------------------------------------------------------ tripwires

TINY = dict(vocab_size=96, hidden_size=32, intermediate_size=64,
            num_layers=2, num_heads=2, num_kv_heads=2, head_dim=16,
            max_position=256, dtype="float32")


@pytest.fixture(scope="module")
def tiny_engine_parts():
    import jax

    from localai_tpu.models.llama import LlamaConfig, init_params

    cfg = LlamaConfig(**TINY)
    params = init_params(cfg, jax.random.PRNGKey(0))
    return cfg, params


def _drive(eng, reqs):
    """Submit all requests, drive the loop to completion, return finish
    reasons."""
    outs = [eng.submit(r)[1] for r in reqs]
    reasons = []
    for out in outs:
        while True:
            o = out.get(timeout=60)
            if o.finished:
                reasons.append(o.finish_reason)
                break
    return reasons


@pytest.mark.tripwire
def test_decode_compiles_exactly_once_across_mixed_stream(tiny_engine_parts):
    """The compile-count guard (acceptance): a mixed-length request stream
    with uniform sampling knobs compiles the decode step EXACTLY once —
    prefill buckets absorb prompt-length variance, and a second stream of
    fresh lengths compiles NOTHING new anywhere. Extended to the while-loop
    path: after engine warmup() the whole compiled-variant set is CLOSED —
    a full mixed stream (loop dispatches included) adds zero programs."""
    from localai_tpu.engine.engine import Engine, EngineConfig, GenRequest
    from localai_tpu.ops.sampling import SamplingParams
    from localai_tpu.testing.tripwires import (
        CompileCounter, decode_cache_sizes, decode_compile_count,
        jit_cache_size,
    )

    cfg, params = tiny_engine_parts
    eng = Engine(cfg, params, None, EngineConfig(
        max_slots=2, max_context=128, prefill_buckets=(16, 64),
        decode_block=1, decode_loop=0, prompt_cache=False))
    eng.start()
    try:
        greedy = SamplingParams(temperature=0.0)
        mixed = [GenRequest(prompt_ids=list(range(1, 1 + n)), params=greedy,
                            max_tokens=m, ignore_eos=True)
                 for n, m in ((5, 6), (13, 4), (40, 8), (22, 3))]
        reasons = _drive(eng, mixed)
        assert all(r == "length" for r in reasons), reasons
        assert decode_compile_count(eng) == 1, decode_cache_sizes(eng)

        # second mixed stream, fresh lengths: ZERO new compilations of any
        # program (admission buckets included — they were warmed above)
        with CompileCounter() as cc:
            more = [GenRequest(prompt_ids=list(range(2, 2 + n)),
                               params=greedy, max_tokens=m, ignore_eos=True)
                    for n, m in ((9, 5), (33, 4))]
            reasons = _drive(eng, more)
        assert all(r == "length" for r in reasons), reasons
        assert cc.total == 0, cc.counts
        assert decode_compile_count(eng) == 1, decode_cache_sizes(eng)
    finally:
        eng.stop()

    # ---- while-loop path: the loop program compiles once per sort-free
    # sampling tier at warmup and NEVER again — a retracing loop body
    # (tracer-dependent shape, unhashed arg) would grow the cache here
    loop_eng = Engine(cfg, params, None, EngineConfig(
        max_slots=2, max_context=128, prefill_buckets=(16, 64),
        decode_block=4, decode_loop=32, prompt_cache=False))
    loop_eng.warmup()
    warm = decode_compile_count(loop_eng)
    loop_variants = jit_cache_size(loop_eng._decode_loop_fn)
    assert loop_variants >= 1
    loop_eng.start()
    try:
        greedy = SamplingParams(temperature=0.0)
        mixed = [GenRequest(prompt_ids=list(range(1, 1 + n)), params=greedy,
                            max_tokens=m, ignore_eos=True)
                 for n, m in ((5, 6), (13, 4), (40, 8), (22, 3))]
        reasons = _drive(loop_eng, mixed)
        assert all(r == "length" for r in reasons), reasons
        assert decode_compile_count(loop_eng) == warm, \
            decode_cache_sizes(loop_eng)
        with CompileCounter() as cc:
            more = [GenRequest(prompt_ids=list(range(2, 2 + n)),
                               params=greedy, max_tokens=m, ignore_eos=True)
                    for n, m in ((9, 5), (33, 4))]
            reasons = _drive(loop_eng, more)
        assert all(r == "length" for r in reasons), reasons
        assert cc.total == 0, cc.counts
        assert jit_cache_size(loop_eng._decode_loop_fn) == loop_variants, \
            decode_cache_sizes(loop_eng)
    finally:
        loop_eng.stop()


@pytest.mark.tripwire
def test_decode_dispatch_budget_on_128_token_stream(tiny_engine_parts):
    """The dispatch-count guard (ISSUE 6 satellite): a 128-token single-slot
    stream rides the fused while loop in <= 3 decode dispatches (the ladder
    took 8-16, per-step 128). dispatch_budget raises if the loop stops
    engaging."""
    from localai_tpu.engine.engine import Engine, EngineConfig, GenRequest
    from localai_tpu.ops.sampling import SamplingParams
    from localai_tpu.testing.tripwires import dispatch_budget

    cfg, params = tiny_engine_parts
    eng = Engine(cfg, params, None, EngineConfig(
        max_slots=2, max_context=160, prefill_buckets=(16,),
        prompt_cache=False))
    eng.start()
    try:
        with dispatch_budget(eng, max_per_128_tokens=3.0):
            reasons = _drive(eng, [GenRequest(
                prompt_ids=[1, 2, 3, 4, 5],
                params=SamplingParams(temperature=0.0),
                max_tokens=128, ignore_eos=True)])
        assert reasons == ["length"]
        assert eng.metrics["decode_dispatches"] <= 3, eng.metrics
        assert eng.metrics["decode_steps_dispatched"] == 128, eng.metrics
        # and the guard itself has teeth: a budget of 0.5/128 must trip
        with pytest.raises(AssertionError, match="dispatch budget"):
            with dispatch_budget(eng, max_per_128_tokens=0.25):
                _drive(eng, [GenRequest(
                    prompt_ids=[1, 2, 3],
                    params=SamplingParams(temperature=0.0),
                    max_tokens=128, ignore_eos=True)])
    finally:
        eng.stop()


@pytest.mark.tripwire
def test_transfer_guard_clean_on_fused_decode(tiny_engine_parts,
                                              monkeypatch):
    """jax.transfer_guard('disallow') around the fused decode block: the
    shipped dispatch makes NO implicit transfers (every host→device crossing
    is an explicit jnp.asarray/device_put), so a full mixed stream completes
    under the guard — including the fused decode_block path."""
    monkeypatch.setenv("LOCALAI_TRANSFER_GUARD", "disallow")
    from localai_tpu.engine.engine import Engine, EngineConfig, GenRequest
    from localai_tpu.ops.sampling import SamplingParams

    cfg, params = tiny_engine_parts
    # decode_loop=16 covers the single-dispatch while-loop path (ISSUE 6:
    # its per-dispatch uploads — active/remaining/check_eos — must all be
    # explicit); decode_loop=0 covers the scan-block fallback
    for loop in (16, 0):
        eng = Engine(cfg, params, None, EngineConfig(
            max_slots=2, max_context=128, prefill_buckets=(16, 64),
            decode_block=4, decode_loop=loop, prompt_cache=False))
        assert eng._xfer_guard == "disallow"
        eng.start()
        try:
            reqs = [GenRequest(prompt_ids=list(range(1, 1 + n)),
                               params=SamplingParams(temperature=0.0),
                               max_tokens=12, ignore_eos=True)
                    for n in (6, 30)]
            reasons = _drive(eng, reqs)
            assert all(r == "length" for r in reasons), reasons
            assert eng.metrics["tokens_generated"] == 24
        finally:
            eng.stop()


@pytest.mark.tripwire
def test_transfer_guard_has_teeth():
    """Prove the guard actually trips: an implicit numpy→device transfer at
    a jit boundary raises under 'disallow' (this is exactly what a stray
    un-wrapped host array in the decode dispatch would look like)."""
    import jax
    import jax.numpy as jnp

    from localai_tpu.testing.tripwires import transfer_guard

    f = jax.jit(lambda a, b: a + b)
    x = jnp.ones(4)
    f(x, np.ones(4))  # warm: implicit transfer is legal un-guarded
    with transfer_guard("disallow"):
        f(x, x)  # device-resident args: fine
        with pytest.raises(Exception, match="[Dd]isallow"):
            f(x, np.ones(4))
    # and the engine helper is a no-op when the env is unset
    from localai_tpu.testing.tripwires import decode_guard_level

    assert decode_guard_level() in ("", "disallow", "log", "allow",
                                    "log_explicit", "disallow_explicit")
