"""Test harness: force a hermetic 8-device virtual CPU mesh.

The reference has no automated multi-node tests (SURVEY.md §4); we do better by
running every sharding-sensitive test on a virtual 8-device CPU mesh — the
TPU-idiomatic fake-cluster harness.

Two subtleties in this environment:
- `JAX_PLATFORMS=axon` is exported AND an axon site hook registers the TPU
  backend at interpreter start, so env-var tricks are too late.
  `jax.config.update("jax_platforms", "cpu")` still wins because backend
  selection is lazy — it must run before the first `jax.devices()` call.
- XLA_FLAGS is read when the CPU client is created (also lazy), so setting it
  here is early enough.
"""
import os
import re

# keep backend-spawning tests fast: skip the serving prewarm request the
# llm backend otherwise runs at LoadModel (backend/llm.py _prewarm)
os.environ.setdefault("LOCALAI_NO_PREWARM", "1")

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()
# honor a pre-set count (the TP CI job runs `-m tp` on 4 devices — the
# exact mesh bench.py --mode tp uses); default stays 8
_m = re.search(r"xla_force_host_platform_device_count=(\d+)",
               os.environ["XLA_FLAGS"])
_FORCED_N = int(_m.group(1)) if _m else 8

import pytest  # noqa: E402
import jax  # noqa: E402

# LOCALAI_TPU_TESTS=1 runs the suite on the real accelerator instead (the
# TPU-gated tests in test_tpu_real.py only execute in that mode; the driver
# uses this to validate real-chip lowering, the round-3 gap). Mesh-dependent
# tests need 8 devices — on smaller TPU hosts only the real-TPU tests run.
_REAL = os.environ.get("LOCALAI_TPU_TESTS") == "1"
if not _REAL:
    jax.config.update("jax_platforms", "cpu")
# numerics tests compare against f64 numpy references; keep CPU matmuls exact
jax.config.update("jax_default_matmul_precision", "float32")

if not _REAL:
    assert jax.devices()[0].platform == "cpu", "tests must run on CPU"
    assert len(jax.devices()) == _FORCED_N >= 4, \
        f"virtual {_FORCED_N}-device mesh required (min 4)"


def pytest_collection_modifyitems(config, items):
    """Real-accelerator mode on a host with fewer than 8 devices: only the
    TPU-gated lowering tests are meaningful — the rest assume the virtual
    8-device mesh harness."""
    if not _REAL or len(jax.devices()) >= 8:
        return
    skip = pytest.mark.skip(reason="LOCALAI_TPU_TESTS=1 with <8 devices: "
                                   "only real-TPU lowering tests run")
    for item in items:
        if "test_tpu_real" not in str(item.fspath):
            item.add_marker(skip)


@pytest.fixture(scope="session")
def devices():
    return jax.devices()


@pytest.fixture(scope="session")
def mesh8():
    """2x4 ('data','model') mesh over the virtual CPU devices."""
    if len(jax.devices()) < 8:
        pytest.skip("mesh8 needs the 8-device harness")
    from localai_tpu.parallel import MeshConfig, build_mesh

    return build_mesh(MeshConfig(data=2, model=4))
