"""Test harness: force an 8-device virtual CPU mesh before JAX initializes.

The reference has no automated multi-node tests (SURVEY.md §4); we do better by
running every sharding-sensitive test on a virtual 8-device CPU mesh, the
TPU-idiomatic fake-cluster harness.
"""
import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import pytest  # noqa: E402
import jax  # noqa: E402

# numerics tests compare against f64 numpy references; keep CPU matmuls exact
jax.config.update("jax_default_matmul_precision", "float32")


@pytest.fixture(scope="session")
def devices():
    import jax

    return jax.devices()
