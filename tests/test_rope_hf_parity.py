"""RoPE frequency tables must match the HF reference formulas exactly.

Round-1 advisor finding: self-consistency tests (rotation preserves norm) hold
for ANY frequency table, so they missed a doubled exponent and an inverted
YaRN ramp. These tests pin our tables to transformers' rope-utils output.
"""
import numpy as np
import pytest
import torch

from transformers import PretrainedConfig
from transformers.modeling_rope_utils import ROPE_INIT_FUNCTIONS

from localai_tpu.ops.rope import RopeConfig, rope_freqs


def _hf_config(head_dim, base, max_pos, rope_scaling=None):
    cfg = PretrainedConfig()
    cfg.head_dim = head_dim
    cfg.hidden_size = head_dim * 4
    cfg.num_attention_heads = 4
    cfg.rope_theta = base
    cfg.max_position_embeddings = max_pos
    cfg.rope_scaling = rope_scaling
    # transformers >=4.54 reads rope params through rope_parameters
    rp = {"rope_theta": base, "rope_type": "default"}
    if rope_scaling:
        rp.update(rope_scaling)
    cfg.rope_parameters = rp
    return cfg


def _hf_freqs(rope_type, head_dim, base, max_pos, rope_scaling=None):
    cfg = _hf_config(head_dim, base, max_pos, rope_scaling)
    inv_freq, attn_scale = ROPE_INIT_FUNCTIONS[rope_type](cfg, device="cpu")
    return np.asarray(inv_freq.to(torch.float64)), float(attn_scale)


def test_default_matches_hf():
    ours, _ = rope_freqs(RopeConfig(head_dim=128, base=500000.0))
    theirs, scale = _hf_freqs("default", 128, 500000.0, 8192)
    np.testing.assert_allclose(np.asarray(ours), theirs, rtol=1e-6)
    assert scale == 1.0


def test_linear_matches_hf():
    ours, _ = rope_freqs(
        RopeConfig(head_dim=64, base=10000.0, scaling="linear", scale_factor=4.0)
    )
    theirs, _ = _hf_freqs(
        "linear", 64, 10000.0, 4096, {"rope_type": "linear", "factor": 4.0}
    )
    np.testing.assert_allclose(np.asarray(ours), theirs, rtol=1e-6)


def test_llama3_matches_hf():
    scaling = {
        "rope_type": "llama3",
        "factor": 8.0,
        "low_freq_factor": 1.0,
        "high_freq_factor": 4.0,
        "original_max_position_embeddings": 8192,
    }
    ours, _ = rope_freqs(
        RopeConfig(
            head_dim=128, base=500000.0, scaling="llama3", scale_factor=8.0,
            original_max_position=8192, low_freq_factor=1.0, high_freq_factor=4.0,
        )
    )
    theirs, _ = _hf_freqs("llama3", 128, 500000.0, 8192, scaling)
    np.testing.assert_allclose(np.asarray(ours), theirs, rtol=1e-6)


def test_yarn_long_context_clamp_matches_hf():
    """original_max_position large enough that the upper correction bound
    exceeds head_dim//2 — HF clamps to head_dim-1, a round-1 divergence."""
    scaling = {
        "rope_type": "yarn",
        "factor": 4.0,
        "beta_fast": 32.0,
        "beta_slow": 1.0,
        "original_max_position_embeddings": 131072,
    }
    ours, _ = rope_freqs(
        RopeConfig(
            head_dim=128, base=10000.0, scaling="yarn", scale_factor=4.0,
            original_max_position=131072, beta_fast=32.0, beta_slow=1.0,
        )
    )
    theirs, _ = _hf_freqs("yarn", 128, 10000.0, 131072, scaling)
    np.testing.assert_allclose(np.asarray(ours), theirs, rtol=1e-5)


def test_yarn_explicit_attention_factor_used_verbatim():
    scaling = {
        "rope_type": "yarn",
        "factor": 4.0,
        "beta_fast": 32.0,
        "beta_slow": 1.0,
        "attention_factor": 0.9,
        "original_max_position_embeddings": 4096,
    }
    _, mscale = rope_freqs(
        RopeConfig(
            head_dim=128, base=10000.0, scaling="yarn", scale_factor=4.0,
            original_max_position=4096, attn_factor=0.9,
        )
    )
    _, hf_mscale = _hf_freqs("yarn", 128, 10000.0, 4096, scaling)
    assert mscale == pytest.approx(hf_mscale) == 0.9


def test_yarn_matches_hf():
    scaling = {
        "rope_type": "yarn",
        "factor": 4.0,
        "beta_fast": 32.0,
        "beta_slow": 1.0,
        "original_max_position_embeddings": 4096,
    }
    ours, mscale = rope_freqs(
        RopeConfig(
            head_dim=128, base=10000.0, scaling="yarn", scale_factor=4.0,
            original_max_position=4096, beta_fast=32.0, beta_slow=1.0,
        )
    )
    theirs, hf_mscale = _hf_freqs("yarn", 128, 10000.0, 4096, scaling)
    np.testing.assert_allclose(np.asarray(ours), theirs, rtol=1e-5)
    assert mscale == pytest.approx(hf_mscale, rel=1e-6)
