"""Disk prompt-KV persistence (reference PromptCachePath/All/RO,
backend.proto:136-142): a prompt's KV survives an engine restart."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from localai_tpu.engine import Engine, EngineConfig
from localai_tpu.engine.engine import GenRequest, SamplingParams
from localai_tpu.models.llama import LlamaConfig, init_params

CFG = LlamaConfig(vocab_size=128, hidden_size=64, intermediate_size=128,
                  num_layers=2, num_heads=4, num_kv_heads=2, head_dim=16,
                  max_position=256, dtype="float32")


def _engine(cache_type=""):
    params = init_params(CFG, jax.random.PRNGKey(0), jnp.float32)
    return Engine(CFG, params, None, EngineConfig(
        max_slots=2, max_context=128, prefill_buckets=(64,),
        prefill_chunk=64, cache_type=cache_type))


def _run(eng, prompt, path="", ro=False, seed=5):
    _, q = eng.submit(GenRequest(
        prompt_ids=prompt, max_tokens=5, ignore_eos=True,
        params=SamplingParams(temperature=0.0, seed=seed),
        prompt_cache_path=path, prompt_cache_ro=ro))
    toks = []
    while True:
        o = q.get(timeout=60)
        toks.append(o.token_id)
        if o.finished:
            return toks


@pytest.mark.parametrize("cache_type", ["", "int8"])
def test_kv_survives_engine_restart(tmp_path, cache_type):
    path = str(tmp_path / "prompt.kv.npz")
    prompt = list(range(1, 41))

    e1 = _engine(cache_type)
    e1.start()
    try:
        ref = _run(e1, prompt, path=path)
    finally:
        e1.stop()
    assert (tmp_path / "prompt.kv.npz").exists()

    # fresh engine (restart): same prompt must reuse the saved prefix AND
    # produce the same output
    e2 = _engine(cache_type)
    e2.start()
    try:
        out = _run(e2, prompt, path=path)
        assert e2.metrics["prompt_tokens_reused"] == len(prompt) - 1
        assert out == ref
    finally:
        e2.stop()


def test_ro_does_not_write(tmp_path):
    path = str(tmp_path / "ro.kv.npz")
    eng = _engine()
    eng.start()
    try:
        _run(eng, list(range(1, 30)), path=path, ro=True)
    finally:
        eng.stop()
    assert not (tmp_path / "ro.kv.npz").exists()


def test_corrupt_file_falls_back_cold(tmp_path):
    path = tmp_path / "bad.kv.npz"
    path.write_bytes(b"this is not an npz")
    eng = _engine()
    eng.start()
    try:
        toks = _run(eng, list(range(1, 30)), path=str(path))
        assert len(toks) == 5
        assert eng.metrics["prompt_tokens_reused"] == 0
    finally:
        eng.stop()


def test_mismatched_prompt_ignored(tmp_path):
    path = str(tmp_path / "other.kv.npz")
    e1 = _engine()
    e1.start()
    try:
        _run(e1, list(range(1, 41)), path=path)
    finally:
        e1.stop()

    e2 = _engine()
    e2.start()
    try:
        _run(e2, list(range(60, 100)), path=path)   # disjoint prompt
        assert e2.metrics["prompt_tokens_reused"] == 0
    finally:
        e2.stop()


def test_bf16_cache_roundtrips(tmp_path):
    """bfloat16 KV (the default model dtype) must survive the npz round trip
    (npz stores bf16 as raw void bytes — the save path upcasts to f32)."""
    import dataclasses

    path = str(tmp_path / "bf16.kv.npz")
    cfg = dataclasses.replace(CFG, dtype="bfloat16")
    params = init_params(cfg, jax.random.PRNGKey(0))
    prompt = list(range(1, 41))

    def engine():
        return Engine(cfg, params, None, EngineConfig(
            max_slots=2, max_context=128, prefill_buckets=(64,),
            prefill_chunk=64))

    e1 = engine()
    e1.start()
    try:
        ref = _run(e1, prompt, path=path)
    finally:
        e1.stop()

    e2 = engine()
    e2.start()
    try:
        out = _run(e2, prompt, path=path)
        assert e2.metrics["prompt_tokens_reused"] == len(prompt) - 1
        assert out == ref
    finally:
        e2.stop()


def test_zip_magic_corrupt_file_survives(tmp_path):
    """A file with zip magic but garbage content (BadZipFile territory) must
    cold-prefill, not kill the engine."""
    path = tmp_path / "zip.kv.npz"
    path.write_bytes(b"PK\x03\x04" + b"\x00" * 64)
    eng = _engine()
    eng.start()
    try:
        toks = _run(eng, list(range(1, 30)), path=str(path))
        assert len(toks) == 5
        assert eng.metrics["prompt_tokens_reused"] == 0
        # engine still alive for the next request
        toks2 = _run(eng, list(range(1, 20)))
        assert len(toks2) == 5
    finally:
        eng.stop()


def test_kv_survives_restart_under_mesh(tmp_path):
    """Single-process mesh (the sharded flagship config): disk prompt-KV
    save/restore must work — every shard is host-addressable, so the slot
    slice/inject runs exactly as unmeshed."""
    from localai_tpu.models.llama import param_specs
    from localai_tpu.parallel.mesh import MeshConfig, build_mesh, shard_params

    mesh = build_mesh(MeshConfig(data=2, model=2), jax.devices()[:4])
    params = init_params(CFG, jax.random.PRNGKey(0), jnp.float32)

    def meng():
        return Engine(CFG, shard_params(params, param_specs(CFG), mesh),
                      None, EngineConfig(
                          max_slots=2, max_context=128, prefill_buckets=(64,),
                          prefill_chunk=64, mesh=mesh))

    path = str(tmp_path / "prompt.kv.npz")
    prompt = list(range(1, 41))
    e1 = meng()
    e1.start()
    try:
        ref = _run(e1, prompt, path=path)
    finally:
        e1.stop()
    assert (tmp_path / "prompt.kv.npz").exists()

    e2 = meng()
    e2.start()
    try:
        out = _run(e2, prompt, path=path)
        assert e2.metrics["prompt_tokens_reused"] == len(prompt) - 1
        assert out == ref
    finally:
        e2.stop()
