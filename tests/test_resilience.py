"""Chaos / resilience suite (ISSUE 4) — the fault-injection harness drives
the full HTTP→gRPC→engine stack through backend kill -9, injected
UNAVAILABLE, slow-start spawns, crash-at-spawn (the free_port TOCTOU shape),
overload shedding, watchdog busy-reaps, and graceful drain, asserting the
specified client-visible outcome for each (VERDICT Weak #7's ask and beyond).

Faults are declared once in LOCALAI_FAULT (localai_tpu/testing/faults.py),
scoped per model name and counted across process boundaries through
LOCALAI_FAULT_DIR, so each scenario is deterministic.
"""
import asyncio
import json
import os
import signal
import threading
import time

import pytest
import requests
import yaml

from fixtures import tiny_checkpoint

pytestmark = pytest.mark.resilience

# The heavyweight end-to-end scenarios (slow-start spawns, crash loops,
# stalled streams, drain waits) additionally carry the `slow` marker: the
# CI `resilience` job and the slow lane run them (`-m resilience` selects
# them regardless), while the tier-1 lane keeps only the cheap pieces —
# the fault sleeps must not eat the tier-1 time budget (ISSUE 4 satellite).

_FAULTS = ",".join([
    "unavailable:0:1:tiny",        # first Predict on tiny aborts UNAVAILABLE
    "slow_start:4::slowpoke",      # every slowpoke spawn stalls 4 s pre-health
    "spawn_crash:::crashy",        # crashy's backend always dies at spawn
    "spawn_crash:0:1:crashy2",     # crashy2 dies once, then spawns fine
    "stall_stream:30:1:staller1",  # first stream wedges 30 s after chunk 1
    "stall_stream:20:1:staller2",  # ditto (overload scenario)
    "stall_stream:30:1:wtiny",     # watchdog-reap scenario
    "stall_stream:1.5:1:dtiny",    # drain scenario: brief mid-stream stall
])


def _free_port():
    import socket

    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    p = s.getsockname()[1]
    s.close()
    return p


@pytest.fixture(scope="module")
def faultenv(tmp_path_factory):
    fault_dir = str(tmp_path_factory.mktemp("faults"))
    old = {k: os.environ.get(k)
           for k in ("LOCALAI_FAULT", "LOCALAI_FAULT_DIR")}
    os.environ["LOCALAI_FAULT"] = _FAULTS
    os.environ["LOCALAI_FAULT_DIR"] = fault_dir
    yield fault_dir
    for k, v in old.items():
        if v is None:
            os.environ.pop(k, None)
        else:
            os.environ[k] = v


def _write_model(models, name, ckpt, parallel=2):
    (models / f"{name}.yaml").write_text(yaml.safe_dump({
        "name": name,
        "backend": "llm",
        "context_size": 128,
        "parallel": parallel,
        "dtype": "float32",
        "prefill_buckets": [32, 64],
        "parameters": {"model": ckpt, "temperature": 0.0, "max_tokens": 8},
    }))


def _serve(app_cfg, models):
    """Spin up a real API server on a thread; returns (base, manager, api,
    stop)."""
    from aiohttp import web

    from localai_tpu.config import ModelConfigLoader
    from localai_tpu.core.manager import ModelManager
    from localai_tpu.server.http import API

    configs = ModelConfigLoader(str(models))
    manager = ModelManager(app_cfg)
    api = API(app_cfg, configs, manager)
    loop = asyncio.new_event_loop()

    def run():
        asyncio.set_event_loop(loop)
        runner = web.AppRunner(api.app)
        loop.run_until_complete(runner.setup())
        host, _, port = app_cfg.address.rpartition(":")
        site = web.TCPSite(runner, host, int(port))
        loop.run_until_complete(site.start())
        loop.run_forever()

    threading.Thread(target=run, daemon=True).start()
    base = f"http://{app_cfg.address}"
    for _ in range(50):
        try:
            requests.get(base + "/healthz", timeout=1)
            break
        except requests.ConnectionError:
            time.sleep(0.1)

    def stop():
        manager.stop_all()
        loop.call_soon_threadsafe(loop.stop)

    return base, manager, api, stop


@pytest.fixture(scope="module")
def stack(tmp_path_factory, faultenv):
    """Main chaos stack: tight resilience knobs, several fault-scoped
    models, real backend subprocesses."""
    from localai_tpu.config import AppConfig

    ckpt = tiny_checkpoint(tmp_path_factory)
    models = tmp_path_factory.mktemp("models")
    for name in ("tiny", "slowpoke", "crashy", "crashy2", "staller1"):
        _write_model(models, name, ckpt)
    _write_model(models, "staller2", ckpt, parallel=1)

    os.environ["LOCALAI_JAX_PLATFORM"] = "cpu"
    app_cfg = AppConfig(
        address=f"127.0.0.1:{_free_port()}", models_path=str(models),
        parallel_requests=2, queue_depth=0, retry_budget=1,
        breaker_threshold=2, breaker_cooldown=2.0,
        spawn_retries=1, spawn_timeout=60.0, drain_timeout=10.0)
    base, manager, api, stop = _serve(app_cfg, models)
    yield base, manager, api
    stop()


def _chat(base, model, n=3, stream=False, timeout=300, headers=None):
    return requests.post(base + "/v1/chat/completions", json={
        "model": model,
        "messages": [{"role": "user", "content": "the quick brown"}],
        "max_tokens": n,
        "stream": stream,
    }, stream=stream, timeout=timeout, headers=headers or {})


def _sse_events(resp):
    """Drain an SSE response into a list of parsed events (+ 'DONE')."""
    events = []
    for line in resp.iter_lines():
        if not line.startswith(b"data: "):
            continue
        payload = line[6:]
        events.append("DONE" if payload == b"[DONE]"
                      else json.loads(payload))
    return events


def _read_until_content(it):
    """Advance an SSE line iterator until a non-empty content delta has
    arrived (i.e. generation bytes have provably reached this client —
    the stall faults wedge the backend right after that first text
    chunk). Returns True when one was seen."""
    for line in it:
        if not line.startswith(b"data: "):
            continue
        payload = line[6:]
        if payload == b"[DONE]":
            return False
        obj = json.loads(payload)
        choices = obj.get("choices") or []
        if choices and choices[0].get("delta", {}).get("content"):
            return True
    return False


# ----------------------------------------------------------- unit pieces


def test_circuit_breaker_transitions():
    from localai_tpu.core.resilience import CircuitBreaker

    t = [0.0]
    br = CircuitBreaker(threshold=2, cooldown=5.0, clock=lambda: t[0])
    assert br.allow() and br.state == "closed"
    br.record_failure()
    assert br.allow()
    br.record_failure()
    assert not br.allow() and br.state == "open"
    assert 4.0 < br.retry_after() <= 5.0
    t[0] = 5.1
    assert br.state == "half_open" and br.allow()
    br.record_failure()                      # failed probe → open again
    assert not br.allow()
    t[0] = 10.3
    assert br.allow()
    br.record_success()
    assert br.state == "closed" and br.retry_after() == 0.0


def test_deadline_contextvar_propagates_to_thread():
    from localai_tpu.core import resilience

    assert resilience.deadline_remaining() is None

    async def main():
        tok = resilience.set_deadline(5.0)
        try:
            rem = await asyncio.to_thread(resilience.deadline_remaining)
            assert rem is not None and 4.0 < rem <= 5.0
        finally:
            resilience.reset_deadline(tok)

    asyncio.run(main())
    assert resilience.deadline_remaining() is None


def test_admission_gate_sheds_and_recovers():
    from localai_tpu.config import AppConfig, ModelConfig
    from localai_tpu.core.manager import ModelManager
    from localai_tpu.core.resilience import RequestShed
    from localai_tpu.server.http import API

    app_cfg = AppConfig(queue_depth=0)
    api = API(app_cfg, None, ModelManager(app_cfg))
    cfg = ModelConfig(name="m", backend="llm", parallel=1)

    async def main():
        async with api._admit(cfg):
            with pytest.raises(RequestShed) as ei:
                async with api._admit(cfg):
                    pass
            assert ei.value.status == 429 and ei.value.model == "m"
            assert ei.value.reason == "queue_full"
        # slot released → admitted again
        async with api._admit(cfg):
            pass

    asyncio.run(main())


def test_admission_gate_bounded_queue():
    """depth=1: one waiter queues (and runs once the slot frees), the next
    is shed."""
    from localai_tpu.config import AppConfig, ModelConfig
    from localai_tpu.core.manager import ModelManager
    from localai_tpu.core.resilience import RequestShed
    from localai_tpu.server.http import API

    app_cfg = AppConfig(queue_depth=1)
    api = API(app_cfg, None, ModelManager(app_cfg))
    cfg = ModelConfig(name="m", backend="llm", parallel=1)
    order = []

    async def main():
        release = asyncio.Event()

        async def holder():
            async with api._admit(cfg):
                order.append("holder")
                await release.wait()

        async def waiter():
            async with api._admit(cfg):
                order.append("waiter")

        h = asyncio.create_task(holder())
        await asyncio.sleep(0.05)
        w = asyncio.create_task(waiter())
        await asyncio.sleep(0.05)          # waiter now queued (depth 1 full)
        with pytest.raises(RequestShed):
            async with api._admit(cfg):
                pass
        release.set()
        await asyncio.gather(h, w)

    asyncio.run(main())
    assert order == ["holder", "waiter"]


def test_federation_breaker_skips_open_worker():
    from localai_tpu.federation import FederatedServer

    srv = FederatedServer(["http://a", "http://b"])
    wa, wb = srv.workers
    for _ in range(3):
        wa.breaker.record_failure()
    assert wa.breaker.state == "open"
    for _ in range(10):
        assert srv.pick() is wb
    for _ in range(3):
        wb.breaker.record_failure()
    assert srv.pick() is not None        # never wedge with zero candidates


# ----------------------------------------------------------- engine-level


@pytest.fixture(scope="module")
def engine(tmp_path_factory):
    from localai_tpu.engine import (
        Engine, EngineConfig, Tokenizer, load_config, load_params,
    )

    ckpt = tiny_checkpoint(tmp_path_factory, max_position=2048)
    cfg = load_config(ckpt, dtype="float32")
    params = load_params(ckpt, cfg)
    tok = Tokenizer.from_dir(ckpt)
    eng = Engine(cfg, params, tok, EngineConfig(
        max_slots=2, max_context=2048, prefill_buckets=(32,),
        prefill_chunk=32))
    eng.start()
    yield eng, tok
    eng.stop()


def test_engine_evicts_expired_queued_request(engine):
    from localai_tpu.engine import GenRequest

    eng, tok = engine
    rid, out = eng.submit(GenRequest(
        prompt_ids=tok.encode("hello"), max_tokens=8,
        deadline=time.monotonic() - 0.5))
    o = out.get(timeout=30)
    assert o.finished and o.finish_reason == "timeout"
    assert o.generated_tokens == 0


def test_engine_evicts_expired_slot_mid_generation(engine):
    from localai_tpu.engine import GenRequest

    eng, tok = engine
    rid, out = eng.submit(GenRequest(
        prompt_ids=tok.encode("the quick"), max_tokens=100_000,
        ignore_eos=True, deadline=time.monotonic() + 0.4))
    t0 = time.monotonic()
    while True:
        o = out.get(timeout=60)
        if o.finished:
            break
    assert o.finish_reason == "timeout"
    assert time.monotonic() - t0 < 30    # evicted, not run to length
    assert 0 < o.generated_tokens < 100_000


def test_engine_cancel_evicts_slot(engine):
    from localai_tpu.engine import GenRequest

    eng, tok = engine
    rid, out = eng.submit(GenRequest(
        prompt_ids=tok.encode("pack my box"), max_tokens=100_000,
        ignore_eos=True))
    first = out.get(timeout=60)          # generation underway
    assert not first.finished
    eng.cancel(rid)
    while True:
        o = out.get(timeout=60)
        if o.finished:
            break
    assert o.finish_reason == "cancelled"
    assert o.generated_tokens < 100_000
    # bookkeeping drained: a finished/unknown rid cancel is a no-op
    eng.cancel(rid)
    assert rid not in eng._cancelled and rid not in eng._live


# --------------------------------------------------------- chaos: HTTP stack


def test_unavailable_unary_retried_transparently(stack):
    """Injected gRPC UNAVAILABLE on tiny's first Predict: the supervisor
    retries against the live backend and the client sees a clean 200."""
    base, manager, _ = stack
    r = _chat(base, "tiny", n=3)
    assert r.status_code == 200, r.text
    assert r.json()["usage"]["completion_tokens"] == 3
    assert manager.events[("tiny", "request_retry")] >= 1
    assert manager.get("tiny").busy == 0     # try/finally accounting held


@pytest.mark.slow
def test_load_of_b_not_blocked_by_slow_spawn_of_a(stack):
    """Per-model locks: slowpoke's 4 s slow-start spawn must not freeze
    tiny (the seed held ONE global lock through wait_ready)."""
    base, manager, _ = stack
    results = {}

    def spawn_slow():
        results["slow"] = _chat(base, "slowpoke", n=2, timeout=300)

    th = threading.Thread(target=spawn_slow)
    th.start()
    time.sleep(0.5)                       # slowpoke spawn is now in flight
    t0 = time.monotonic()
    r = _chat(base, "tiny", n=2)
    dt = time.monotonic() - t0
    assert r.status_code == 200
    assert dt < 3.0, f"tiny request waited {dt:.1f}s behind slowpoke's spawn"
    th.join(timeout=300)
    assert results["slow"].status_code == 200, results["slow"].text


@pytest.mark.slow
def test_crashing_backend_fails_fast_then_breaker_opens(stack):
    """crashy's backend dies at every spawn: the dead child is detected in
    seconds (not the 120 s health budget), the spawn retries on a fresh
    port, and after breaker_threshold failed loads the circuit opens —
    requests fail fast with 503 + Retry-After."""
    base, manager, _ = stack
    t0 = time.monotonic()
    r1 = _chat(base, "crashy", n=2, timeout=120)
    first_dt = time.monotonic() - t0
    assert r1.status_code == 500
    assert first_dt < 30, f"dead-child spawn burned {first_dt:.0f}s"
    assert manager.events[("crashy", "spawn_retry")] >= 1
    r2 = _chat(base, "crashy", n=2, timeout=120)
    assert r2.status_code == 500
    # breaker open (threshold 2) → instant 503, no spawn attempt
    t0 = time.monotonic()
    r3 = _chat(base, "crashy", n=2, timeout=30)
    assert r3.status_code == 503, r3.text
    assert time.monotonic() - t0 < 1.0
    assert "Retry-After" in r3.headers
    assert "circuit breaker" in r3.json()["error"]["message"]
    assert manager.events[("crashy", "breaker_reject")] >= 1


@pytest.mark.slow
def test_spawn_crash_once_recovers_on_fresh_port(stack):
    """The free_port TOCTOU shape: crashy2's child dies once (shared-count
    fault), the manager respawns on a new port within the same load() and
    the request succeeds."""
    base, manager, _ = stack
    r = _chat(base, "crashy2", n=3, timeout=300)
    assert r.status_code == 200, r.text
    assert manager.events[("crashy2", "spawn_retry")] == 1


@pytest.mark.slow
def test_kill9_midstream_clean_sse_error_then_respawn(stack):
    """VERDICT Weak #7: kill -9 mid-PredictStream → the client sees a clean
    terminal SSE error event (not a hung connection), the handle is reaped,
    and the next request respawns and succeeds."""
    base, manager, _ = stack
    r = _chat(base, "staller1", n=24, stream=True, timeout=(30, 60))
    it = r.iter_lines()
    assert _read_until_content(it)       # bytes streamed; backend now wedged
    h = manager.get("staller1")
    assert h is not None
    os.kill(h.proc.pid, signal.SIGKILL)
    h.proc.wait(timeout=10)
    tail = []
    for line in it:                      # stream MUST terminate cleanly
        if line.startswith(b"data: "):
            payload = line[6:]
            tail.append("DONE" if payload == b"[DONE]"
                        else json.loads(payload))
    assert tail and tail[-1] == "DONE", f"no clean terminal event: {tail}"
    errors = [e for e in tail if isinstance(e, dict) and "error" in e]
    assert errors, f"expected a terminal SSE error event, got {tail}"
    assert errors[-1]["error"]["code"] in (502, 503)
    # reaped on classification…
    assert manager.get("staller1") is None or \
        manager.get("staller1").proc.pid != h.proc.pid, \
        f"events={dict(manager.events)}"
    # …and the follow-up request respawns a fresh backend and completes
    r2 = _chat(base, "staller1", n=3, timeout=300)
    assert r2.status_code == 200, r2.text
    h2 = manager.get("staller1")
    assert h2 is not None and h2.proc.pid != h.proc.pid


@pytest.mark.slow
def test_overload_sheds_429_with_retry_after(stack):
    """staller2 (parallel=1, queue_depth=0): one wedged stream holds the
    slot; the next request is shed fast with 429 + Retry-After, and the
    shed shows up in localai_shed_total."""
    base, manager, _ = stack
    r1 = _chat(base, "staller2", n=16, stream=True, timeout=(30, 60))
    it = r1.iter_lines()
    assert _read_until_content(it)       # stream is live → slot held
    try:
        t0 = time.monotonic()
        r2 = _chat(base, "staller2", n=2, timeout=30)
        assert r2.status_code == 429, r2.text
        assert time.monotonic() - t0 < 1.0, "shed must fail FAST"
        assert "Retry-After" in r2.headers
        assert r2.json()["error"]["type"] == "overloaded_error"
        m = requests.get(base + "/metrics", timeout=30)
        assert b'localai_shed_total' in m.content
        assert b'model="staller2",reason="queue_full"' in m.content
    finally:
        r1.close()                       # cancels the wedged stream


def test_deadline_header_maps_to_504(stack):
    """X-Request-Timeout lowers the request budget; an impossible budget
    surfaces as 504 timeout_error — whether the RPC dies with gRPC
    DEADLINE_EXCEEDED or the budget evaporates during a supervised retry
    (e.g. tiny's injected-UNAVAILABLE fault, if still unconsumed)."""
    base, _, _ = stack
    # warm spawn so the deadline clock measures the RPC, not the load
    assert _chat(base, "tiny", n=2, timeout=300).status_code == 200
    r = _chat(base, "tiny", n=64, timeout=60,
              headers={"X-Request-Timeout": "0.02"})
    assert r.status_code == 504, r.text
    assert r.json()["error"]["type"] == "timeout_error"


# --------------------------------------------------- watchdog busy-reap 504


@pytest.fixture(scope="module")
def wd_stack(tmp_path_factory, faultenv):
    from localai_tpu.config import AppConfig

    ckpt = tiny_checkpoint(tmp_path_factory)
    models = tmp_path_factory.mktemp("models-wd")
    _write_model(models, "wtiny", ckpt)
    os.environ["LOCALAI_JAX_PLATFORM"] = "cpu"
    app_cfg = AppConfig(
        address=f"127.0.0.1:{_free_port()}", models_path=str(models),
        parallel_requests=2, watchdog_busy_timeout=1.5,
        spawn_timeout=60.0, retry_budget=1)
    base, manager, api, stop = _serve(app_cfg, models)
    manager.start_watchdog(interval=0.3)
    yield base, manager
    stop()


@pytest.mark.slow
def test_watchdog_busy_reap_names_watchdog_in_504(wd_stack):
    """A busy-watchdog reap must fail the in-flight stream with an explicit
    watchdog-named error event — not a raw severed-channel RpcError."""
    base, manager = wd_stack
    r = _chat(base, "wtiny", n=24, stream=True, timeout=(30, 60))
    events = _sse_events(r)              # wedged stream → watchdog reaps
    assert events and events[-1] == "DONE"
    errors = [e for e in events if isinstance(e, dict) and "error" in e]
    assert errors, f"no terminal error event: {events}"
    err = errors[-1]["error"]
    assert err["code"] == 504
    assert "watchdog" in err["message"].lower()
    assert manager.events[("wtiny", "watchdog_busy_reap")] >= 1


# --------------------------------------------------------- graceful drain


@pytest.fixture(scope="module")
def drain_stack(tmp_path_factory, faultenv):
    from localai_tpu.config import AppConfig

    ckpt = tiny_checkpoint(tmp_path_factory)
    models = tmp_path_factory.mktemp("models-drain")
    _write_model(models, "dtiny", ckpt)
    os.environ["LOCALAI_JAX_PLATFORM"] = "cpu"
    app_cfg = AppConfig(
        address=f"127.0.0.1:{_free_port()}", models_path=str(models),
        parallel_requests=2, drain_timeout=15.0, spawn_timeout=60.0)
    base, manager, api, stop = _serve(app_cfg, models)
    yield base, manager, api
    stop()


@pytest.mark.slow
def test_graceful_drain_finishes_inflight_rejects_new(drain_stack):
    """/backend/shutdown (the SIGTERM path drives the same _drain): the
    in-flight stream finishes under the drain deadline while concurrent new
    requests get 503, then every backend is stopped."""
    base, manager, api = drain_stack
    # warm the backend so the drain test measures serving, not spawn
    assert _chat(base, "dtiny", n=2).status_code == 200

    r1 = _chat(base, "dtiny", n=24, stream=True, timeout=(30, 60))
    it = r1.iter_lines()
    assert _read_until_content(it)      # mid-stream (stall holds it ~1.5 s)
    shut = {}

    def shutdown():
        shut["r"] = requests.post(base + "/backend/shutdown", json={},
                                  timeout=60)

    th = threading.Thread(target=shutdown)
    th.start()
    time.sleep(0.4)                      # drain flag is now up
    r2 = _chat(base, "dtiny", n=2, timeout=30)
    assert r2.status_code == 503, r2.text
    assert "Retry-After" in r2.headers

    tail = []
    for line in it:                      # in-flight stream runs to completion
        if line.startswith(b"data: "):
            payload = line[6:]
            tail.append("DONE" if payload == b"[DONE]"
                        else json.loads(payload))
    assert tail and tail[-1] == "DONE"
    assert not any(isinstance(e, dict) and "error" in e for e in tail), \
        f"drain severed the in-flight stream: {tail}"
    finals = [e for e in tail if isinstance(e, dict) and e.get("choices")
              and e["choices"][0].get("finish_reason")]
    assert finals, "stream ended without finish_reason"

    th.join(timeout=60)
    assert shut["r"].status_code == 200 and shut["r"].json()["success"]
    assert manager.loaded() == []        # backends stopped after the drain
    # the server stays up but sheds everything while draining
    r3 = _chat(base, "dtiny", n=2, timeout=30)
    assert r3.status_code == 503
