"""Shared test fixtures: a tiny REAL HF Llama checkpoint built locally.

Zero-egress environment → we can't download TinyLlama; instead we construct a
genuine transformers LlamaForCausalLM (random weights), save it as safetensors
with a trained byte-level BPE tokenizer, and treat that directory as the
checkpoint under test. Parity tests compare our engine against the HF forward
pass on the same weights — the same guarantee a downloaded model would give.
"""
from __future__ import annotations

import json
import os

CORPUS = [
    "the quick brown fox jumps over the lazy dog",
    "hello world, this is a test of the tokenizer",
    "TPU native inference with JAX and XLA collectives",
    "pack my box with five dozen liquor jugs",
    "sphinx of black quartz judge my vow",
    "números y acentos: café, naïve, über, 東京",
]

CHAT_TEMPLATE = (
    "{{ bos_token }}{% for message in messages %}"
    "<|{{ message['role'] }}|>\n{{ message['content'] }}</s>\n"
    "{% endfor %}"
    "{% if add_generation_prompt %}<|assistant|>\n{% endif %}"
)


def build_tiny_checkpoint(dirpath: str, *, vocab_size: int = 384,
                          hidden: int = 64, layers: int = 2, heads: int = 4,
                          kv_heads: int = 2, inter: int = 128,
                          tie: bool = False, seed: int = 0,
                          max_position: int = 256) -> str:
    """Create a tiny HF Llama checkpoint + tokenizer at `dirpath`."""
    import torch
    from tokenizers import Tokenizer, models, pre_tokenizers, decoders, trainers
    from transformers import LlamaConfig, LlamaForCausalLM

    os.makedirs(dirpath, exist_ok=True)

    tok = Tokenizer(models.BPE(unk_token=None))
    tok.pre_tokenizer = pre_tokenizers.ByteLevel(add_prefix_space=False)
    tok.decoder = decoders.ByteLevel()
    trainer = trainers.BpeTrainer(
        vocab_size=vocab_size - 4,
        special_tokens=["<s>", "</s>", "<|user|>", "<|assistant|>"],
        initial_alphabet=pre_tokenizers.ByteLevel.alphabet(),
        show_progress=False,
    )
    tok.train_from_iterator(CORPUS * 4, trainer=trainer)
    real_vocab = tok.get_vocab_size()
    tok.save(os.path.join(dirpath, "tokenizer.json"))
    with open(os.path.join(dirpath, "tokenizer_config.json"), "w") as f:
        json.dump({
            "bos_token": "<s>", "eos_token": "</s>",
            "add_bos_token": True,
            "chat_template": CHAT_TEMPLATE,
        }, f)

    torch.manual_seed(seed)
    cfg = LlamaConfig(
        vocab_size=real_vocab, hidden_size=hidden, intermediate_size=inter,
        num_hidden_layers=layers, num_attention_heads=heads,
        num_key_value_heads=kv_heads, max_position_embeddings=max_position,
        rms_norm_eps=1e-5, rope_theta=10000.0, tie_word_embeddings=tie,
        bos_token_id=0, eos_token_id=1,
    )
    model = LlamaForCausalLM(cfg)
    model.eval()
    model.save_pretrained(dirpath, safe_serialization=True)
    return dirpath


_CACHE = {}


def tiny_checkpoint(tmp_path_factory, **kw) -> str:
    """Session-cached tiny checkpoint (building one takes a few seconds)."""
    key = tuple(sorted(kw.items()))
    if key not in _CACHE:
        d = tmp_path_factory.mktemp("tinyllama")
        _CACHE[key] = build_tiny_checkpoint(str(d), **kw)
    return _CACHE[key]


def _write_safetensors(path: str, tensors: dict):
    """Minimal safetensors writer (f32 little-endian)."""
    import numpy as np

    header = {}
    offset = 0
    blobs = []
    for k, v in tensors.items():
        v = np.ascontiguousarray(v, np.float32)
        n = v.nbytes
        header[k] = {"dtype": "F32", "shape": list(v.shape),
                     "data_offsets": [offset, offset + n]}
        blobs.append(v.tobytes())
        offset += n
    hjson = json.dumps(header).encode()
    with open(path, "wb") as f:
        f.write(len(hjson).to_bytes(8, "little"))
        f.write(hjson)
        for b in blobs:
            f.write(b)


def build_tiny_sd_checkpoint(dirpath: str) -> str:
    """Tiny Stable-Diffusion-class checkpoint in the diffusers directory
    layout (unet/ + vae/ + text_encoder/ safetensors + configs) — the layout
    localai_tpu.models.latent_diffusion loads. Text encoder is a REAL
    transformers CLIPTextModel so parity can be checked against torch."""
    import numpy as np
    import torch
    from transformers import CLIPTextConfig, CLIPTextModel

    rng = np.random.default_rng(0)

    def t(*shape, scale=None):
        scale = scale if scale is not None else (shape[-1] ** -0.5 if
                                                 len(shape) > 1 else 0.02)
        return (rng.standard_normal(shape) * scale).astype(np.float32)

    os.makedirs(dirpath, exist_ok=True)
    with open(os.path.join(dirpath, "model_index.json"), "w") as f:
        json.dump({"_class_name": "StableDiffusionPipeline"}, f)

    # ---- text encoder: real CLIPTextModel
    td = os.path.join(dirpath, "text_encoder")
    tcfg = CLIPTextConfig(
        vocab_size=256, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4,
        max_position_embeddings=77)
    torch.manual_seed(0)
    CLIPTextModel(tcfg).save_pretrained(td, safe_serialization=True)

    # ---- unet
    C0, C1, TE, CROSS, G = 32, 64, 64, 64, 8
    u = {}

    def conv(name, o, i, k=3):
        u[name + ".weight"] = t(o, i, k, k)
        u[name + ".bias"] = np.zeros((o,), np.float32)

    def norm(name, c):
        u[name + ".weight"] = np.ones((c,), np.float32)
        u[name + ".bias"] = np.zeros((c,), np.float32)

    def lin(name, o, i, bias=True):
        u[name + ".weight"] = t(o, i)
        if bias:
            u[name + ".bias"] = np.zeros((o,), np.float32)

    def resnet(p, cin, cout, temb=True):
        norm(p + "norm1", cin)
        conv(p + "conv1", cout, cin)
        if temb:
            lin(p + "time_emb_proj", cout, TE)
        norm(p + "norm2", cout)
        conv(p + "conv2", cout, cout)
        if cin != cout:
            conv(p + "conv_shortcut", cout, cin, k=1)

    def xattn(p, c, heads_dim=8):
        norm(p + "norm", c)
        conv(p + "proj_in", c, c, k=1)
        b = p + "transformer_blocks.0."
        norm(b + "norm1", c)
        lin(b + "attn1.to_q", c, c, bias=False)
        lin(b + "attn1.to_k", c, c, bias=False)
        lin(b + "attn1.to_v", c, c, bias=False)
        lin(b + "attn1.to_out.0", c, c)
        norm(b + "norm2", c)
        lin(b + "attn2.to_q", c, c, bias=False)
        lin(b + "attn2.to_k", c, CROSS, bias=False)
        lin(b + "attn2.to_v", c, CROSS, bias=False)
        lin(b + "attn2.to_out.0", c, c)
        norm(b + "norm3", c)
        lin(b + "ff.net.0.proj", 8 * c, c)
        lin(b + "ff.net.2", c, 4 * c)
        conv(p + "proj_out", c, c, k=1)

    conv("conv_in", C0, 4)
    lin("time_embedding.linear_1", TE, C0)
    lin("time_embedding.linear_2", TE, TE)
    # down 0: CrossAttn; down 1: plain with channel change + no downsampler
    resnet("down_blocks.0.resnets.0.", C0, C0)
    xattn("down_blocks.0.attentions.0.", C0)
    conv("down_blocks.0.downsamplers.0.conv", C0, C0)
    resnet("down_blocks.1.resnets.0.", C0, C1)
    resnet("mid_block.resnets.0.", C1, C1)
    xattn("mid_block.attentions.0.", C1)
    resnet("mid_block.resnets.1.", C1, C1)
    # up 0 (plain, mirrors down 1): skips C1, C0 ; up 1 (crossattn)
    resnet("up_blocks.0.resnets.0.", C1 + C1, C1)
    resnet("up_blocks.0.resnets.1.", C1 + C0, C1)
    conv("up_blocks.0.upsamplers.0.conv", C1, C1)
    resnet("up_blocks.1.resnets.0.", C1 + C0, C0)
    xattn("up_blocks.1.attentions.0.", C0)
    resnet("up_blocks.1.resnets.1.", C0 + C0, C0)
    xattn("up_blocks.1.attentions.1.", C0)
    norm("conv_norm_out", C0)
    conv("conv_out", 4, C0)

    ud = os.path.join(dirpath, "unet")
    os.makedirs(ud, exist_ok=True)
    _write_safetensors(os.path.join(ud, "diffusion_pytorch_model.safetensors"), u)
    with open(os.path.join(ud, "config.json"), "w") as f:
        json.dump({
            "block_out_channels": [C0, C1], "layers_per_block": 1,
            "attention_head_dim": 8, "cross_attention_dim": CROSS,
            "norm_num_groups": G, "in_channels": 4, "out_channels": 4,
            "down_block_types": ["CrossAttnDownBlock2D", "DownBlock2D"],
            "up_block_types": ["UpBlock2D", "CrossAttnUpBlock2D"],
        }, f)

    # ---- vae decoder
    u = {}
    V0, V1 = 32, 64
    conv("post_quant_conv", 4, 4, k=1)
    conv("decoder.conv_in", V1, 4)
    resnet("decoder.mid_block.resnets.0.", V1, V1, temb=False)
    norm("decoder.mid_block.attentions.0.group_norm", V1)
    lin("decoder.mid_block.attentions.0.to_q", V1, V1)
    lin("decoder.mid_block.attentions.0.to_k", V1, V1)
    lin("decoder.mid_block.attentions.0.to_v", V1, V1)
    lin("decoder.mid_block.attentions.0.to_out.0", V1, V1)
    resnet("decoder.mid_block.resnets.1.", V1, V1, temb=False)
    for j in range(3):
        resnet(f"decoder.up_blocks.0.resnets.{j}.", V1, V1, temb=False)
    conv("decoder.up_blocks.0.upsamplers.0.conv", V1, V1)
    resnet("decoder.up_blocks.1.resnets.0.", V1, V0, temb=False)
    for j in (1, 2):
        resnet(f"decoder.up_blocks.1.resnets.{j}.", V0, V0, temb=False)
    norm("decoder.conv_norm_out", V0)
    conv("decoder.conv_out", 3, V0)

    vd = os.path.join(dirpath, "vae")
    os.makedirs(vd, exist_ok=True)
    _write_safetensors(os.path.join(vd, "diffusion_pytorch_model.safetensors"), u)
    with open(os.path.join(vd, "config.json"), "w") as f:
        json.dump({"block_out_channels": [V0, V1], "latent_channels": 4,
                   "norm_num_groups": G, "scaling_factor": 0.18215}, f)
    return dirpath


def build_tiny_sdxl_checkpoint(dirpath: str) -> str:
    """Tiny SDXL-geometry checkpoint: dual text encoders (the second with a
    projection head), transformer_layers_per_block, and the text_time
    addition embedding — the structural deltas SDXL adds over SD 1.x/2.x."""
    import numpy as np
    import torch
    from transformers import (
        CLIPTextConfig, CLIPTextModel, CLIPTextModelWithProjection,
    )

    rng = np.random.default_rng(1)

    def t(*shape, scale=None):
        scale = scale if scale is not None else (shape[-1] ** -0.5 if
                                                 len(shape) > 1 else 0.02)
        return (rng.standard_normal(shape) * scale).astype(np.float32)

    os.makedirs(dirpath, exist_ok=True)
    with open(os.path.join(dirpath, "model_index.json"), "w") as f:
        json.dump({"_class_name": "StableDiffusionXLPipeline"}, f)

    # ---- text encoders: CLIP-L role (hidden H1) + OpenCLIP-G role
    # (hidden H2, projection head → pooled text_embeds)
    H1, H2, PROJ = 32, 48, 48
    torch.manual_seed(0)
    CLIPTextModel(CLIPTextConfig(
        vocab_size=256, hidden_size=H1, intermediate_size=64,
        num_hidden_layers=2, num_attention_heads=4,
        max_position_embeddings=77)).save_pretrained(
        os.path.join(dirpath, "text_encoder"), safe_serialization=True)
    CLIPTextModelWithProjection(CLIPTextConfig(
        vocab_size=256, hidden_size=H2, intermediate_size=96,
        num_hidden_layers=2, num_attention_heads=4, projection_dim=PROJ,
        bos_token_id=254, eos_token_id=255,   # reachable in the tiny vocab
        max_position_embeddings=77)).save_pretrained(
        os.path.join(dirpath, "text_encoder_2"), safe_serialization=True)

    # ---- unet: SDXL structure — first down block attention-free,
    # transformer depth 2 on the deep block, text_time add embedding
    C0, C1, TE, CROSS, G, ATD = 32, 64, 64, H1 + H2, 8, 8
    u = {}

    def conv(name, o, i, k=3):
        u[name + ".weight"] = t(o, i, k, k)
        u[name + ".bias"] = np.zeros((o,), np.float32)

    def norm(name, c):
        u[name + ".weight"] = np.ones((c,), np.float32)
        u[name + ".bias"] = np.zeros((c,), np.float32)

    def lin(name, o, i, bias=True):
        u[name + ".weight"] = t(o, i)
        if bias:
            u[name + ".bias"] = np.zeros((o,), np.float32)

    def resnet(p, cin, cout, temb=True):
        norm(p + "norm1", cin)
        conv(p + "conv1", cout, cin)
        if temb:
            lin(p + "time_emb_proj", cout, TE)
        norm(p + "norm2", cout)
        conv(p + "conv2", cout, cout)
        if cin != cout:
            conv(p + "conv_shortcut", cout, cin, k=1)

    def xattn(p, c, depth=1):
        norm(p + "norm", c)
        lin(p + "proj_in", c, c)        # use_linear_projection (SDXL)
        for d in range(depth):
            b = f"{p}transformer_blocks.{d}."
            norm(b + "norm1", c)
            lin(b + "attn1.to_q", c, c, bias=False)
            lin(b + "attn1.to_k", c, c, bias=False)
            lin(b + "attn1.to_v", c, c, bias=False)
            lin(b + "attn1.to_out.0", c, c)
            norm(b + "norm2", c)
            lin(b + "attn2.to_q", c, c, bias=False)
            lin(b + "attn2.to_k", c, CROSS, bias=False)
            lin(b + "attn2.to_v", c, CROSS, bias=False)
            lin(b + "attn2.to_out.0", c, c)
            norm(b + "norm3", c)
            lin(b + "ff.net.0.proj", 8 * c, c)
            lin(b + "ff.net.2", c, 4 * c)
        lin(p + "proj_out", c, c)

    conv("conv_in", C0, 4)
    lin("time_embedding.linear_1", TE, C0)
    lin("time_embedding.linear_2", TE, TE)
    # text_time addition embedding: in = pooled PROJ + 6 * ATD fourier dims
    lin("add_embedding.linear_1", TE, PROJ + 6 * ATD)
    lin("add_embedding.linear_2", TE, TE)
    # down 0: plain (SDXL's first block has no attention); down 1: depth-2
    resnet("down_blocks.0.resnets.0.", C0, C0)
    conv("down_blocks.0.downsamplers.0.conv", C0, C0)
    resnet("down_blocks.1.resnets.0.", C0, C1)
    xattn("down_blocks.1.attentions.0.", C1, depth=2)
    resnet("mid_block.resnets.0.", C1, C1)
    xattn("mid_block.attentions.0.", C1, depth=2)
    resnet("mid_block.resnets.1.", C1, C1)
    # up 0 mirrors down 1 (crossattn, depth 2); up 1 plain
    resnet("up_blocks.0.resnets.0.", C1 + C1, C1)
    xattn("up_blocks.0.attentions.0.", C1, depth=2)
    resnet("up_blocks.0.resnets.1.", C1 + C0, C1)
    xattn("up_blocks.0.attentions.1.", C1, depth=2)
    conv("up_blocks.0.upsamplers.0.conv", C1, C1)
    resnet("up_blocks.1.resnets.0.", C1 + C0, C0)
    resnet("up_blocks.1.resnets.1.", C0 + C0, C0)
    norm("conv_norm_out", C0)
    conv("conv_out", 4, C0)

    ud = os.path.join(dirpath, "unet")
    os.makedirs(ud, exist_ok=True)
    _write_safetensors(os.path.join(ud, "diffusion_pytorch_model.safetensors"), u)
    with open(os.path.join(ud, "config.json"), "w") as f:
        json.dump({
            "block_out_channels": [C0, C1], "layers_per_block": 1,
            "attention_head_dim": [4, 8], "cross_attention_dim": CROSS,
            "transformer_layers_per_block": [1, 2],
            "addition_embed_type": "text_time",
            "addition_time_embed_dim": ATD,
            "norm_num_groups": G, "in_channels": 4, "out_channels": 4,
            "down_block_types": ["DownBlock2D", "CrossAttnDownBlock2D"],
            "up_block_types": ["CrossAttnUpBlock2D", "UpBlock2D"],
        }, f)

    # ---- vae decoder (SDXL scaling factor)
    u = {}
    V0, V1 = 32, 64
    conv("post_quant_conv", 4, 4, k=1)
    conv("decoder.conv_in", V1, 4)
    resnet("decoder.mid_block.resnets.0.", V1, V1, temb=False)
    norm("decoder.mid_block.attentions.0.group_norm", V1)
    lin("decoder.mid_block.attentions.0.to_q", V1, V1)
    lin("decoder.mid_block.attentions.0.to_k", V1, V1)
    lin("decoder.mid_block.attentions.0.to_v", V1, V1)
    lin("decoder.mid_block.attentions.0.to_out.0", V1, V1)
    resnet("decoder.mid_block.resnets.1.", V1, V1, temb=False)
    for j in range(3):
        resnet(f"decoder.up_blocks.0.resnets.{j}.", V1, V1, temb=False)
    conv("decoder.up_blocks.0.upsamplers.0.conv", V1, V1)
    resnet("decoder.up_blocks.1.resnets.0.", V1, V0, temb=False)
    for j in (1, 2):
        resnet(f"decoder.up_blocks.1.resnets.{j}.", V0, V0, temb=False)
    norm("decoder.conv_norm_out", V0)
    conv("decoder.conv_out", 3, V0)

    vd = os.path.join(dirpath, "vae")
    os.makedirs(vd, exist_ok=True)
    _write_safetensors(os.path.join(vd, "diffusion_pytorch_model.safetensors"), u)
    with open(os.path.join(vd, "config.json"), "w") as f:
        json.dump({"block_out_channels": [V0, V1], "latent_channels": 4,
                   "norm_num_groups": G, "scaling_factor": 0.13025}, f)
    return dirpath


def build_tiny_flux_checkpoint(dirpath: str) -> str:
    """Tiny Flux-geometry checkpoint (diffusers FluxPipeline layout): CLIP
    pooled vector + T5 sequence conditioning, double- and single-stream
    MMDiT blocks with 3-axis rope + QK RMS norms, 2x2-packed latents."""
    import numpy as np
    import torch
    from transformers import (
        CLIPTextConfig, CLIPTextModel, T5Config, T5EncoderModel,
    )

    rng = np.random.default_rng(2)
    os.makedirs(dirpath, exist_ok=True)
    with open(os.path.join(dirpath, "model_index.json"), "w") as f:
        json.dump({"_class_name": "FluxPipeline"}, f)

    HID, HEADS, HD = 32, 4, 8            # transformer hidden / heads
    T5D, CLIPH, LC = 16, 24, 4           # t5 d_model, clip hidden, latents

    torch.manual_seed(0)
    CLIPTextModel(CLIPTextConfig(
        vocab_size=256, hidden_size=CLIPH, intermediate_size=48,
        num_hidden_layers=2, num_attention_heads=4,
        bos_token_id=254, eos_token_id=255,
        max_position_embeddings=77)).save_pretrained(
        os.path.join(dirpath, "text_encoder"), safe_serialization=True)
    T5EncoderModel(T5Config(
        vocab_size=128, d_model=T5D, d_kv=8, d_ff=32, num_layers=2,
        num_heads=2, feed_forward_proj="gated-gelu",
        relative_attention_num_buckets=8,
        relative_attention_max_distance=16)).save_pretrained(
        os.path.join(dirpath, "text_encoder_2"), safe_serialization=True)

    u = {}

    def t(*shape, scale=None):
        scale = scale if scale is not None else (shape[-1] ** -0.5 if
                                                 len(shape) > 1 else 0.02)
        return (rng.standard_normal(shape) * scale).astype(np.float32)

    def lin(name, o, i, bias=True):
        u[name + ".weight"] = t(o, i)
        if bias:
            u[name + ".bias"] = np.zeros((o,), np.float32)

    def ones(name, n):
        u[name + ".weight"] = np.ones((n,), np.float32)

    lin("x_embedder", HID, LC * 4)
    lin("context_embedder", HID, T5D)
    lin("time_text_embed.timestep_embedder.linear_1", HID, 256)
    lin("time_text_embed.timestep_embedder.linear_2", HID, HID)
    lin("time_text_embed.guidance_embedder.linear_1", HID, 256)
    lin("time_text_embed.guidance_embedder.linear_2", HID, HID)
    lin("time_text_embed.text_embedder.linear_1", HID, CLIPH)
    lin("time_text_embed.text_embedder.linear_2", HID, HID)
    b = "transformer_blocks.0."
    lin(b + "norm1.linear", 6 * HID, HID)
    lin(b + "norm1_context.linear", 6 * HID, HID)
    for n in ("to_q", "to_k", "to_v", "add_q_proj", "add_k_proj",
              "add_v_proj"):
        lin(b + "attn." + n, HID, HID)
    for n in ("norm_q", "norm_k", "norm_added_q", "norm_added_k"):
        ones(b + "attn." + n, HD)
    lin(b + "attn.to_out.0", HID, HID)
    lin(b + "attn.to_add_out", HID, HID)
    lin(b + "ff.net.0.proj", 4 * HID, HID)
    lin(b + "ff.net.2", HID, 4 * HID)
    lin(b + "ff_context.net.0.proj", 4 * HID, HID)
    lin(b + "ff_context.net.2", HID, 4 * HID)
    s = "single_transformer_blocks.0."
    lin(s + "norm.linear", 3 * HID, HID)
    for n in ("to_q", "to_k", "to_v"):
        lin(s + "attn." + n, HID, HID)
    ones(s + "attn.norm_q", HD)
    ones(s + "attn.norm_k", HD)
    lin(s + "proj_mlp", 4 * HID, HID)
    lin(s + "proj_out", HID, 5 * HID)
    lin("norm_out.linear", 2 * HID, HID)
    lin("proj_out", LC * 4, HID)

    td = os.path.join(dirpath, "transformer")
    os.makedirs(td, exist_ok=True)
    _write_safetensors(os.path.join(td, "diffusion_pytorch_model.safetensors"), u)
    with open(os.path.join(td, "config.json"), "w") as f:
        json.dump({
            "num_layers": 1, "num_single_layers": 1,
            "num_attention_heads": HEADS, "attention_head_dim": HD,
            "in_channels": LC * 4, "joint_attention_dim": T5D,
            "pooled_projection_dim": CLIPH, "guidance_embeds": True,
            "axes_dims_rope": [2, 4, 2],
        }, f)

    # vae decoder (16ch-flux role at tiny scale; latent_channels=LC)
    u = {}
    V0, V1, G = 32, 64, 8

    def conv(name, o, i, k=3):
        u[name + ".weight"] = t(o, i, k, k)
        u[name + ".bias"] = np.zeros((o,), np.float32)

    def norm(name, c):
        u[name + ".weight"] = np.ones((c,), np.float32)
        u[name + ".bias"] = np.zeros((c,), np.float32)

    def resnet(p, cin, cout):
        norm(p + "norm1", cin)
        conv(p + "conv1", cout, cin)
        norm(p + "norm2", cout)
        conv(p + "conv2", cout, cout)
        if cin != cout:
            conv(p + "conv_shortcut", cout, cin, k=1)

    conv("post_quant_conv", LC, LC, k=1)
    conv("decoder.conv_in", V1, LC)
    resnet("decoder.mid_block.resnets.0.", V1, V1)
    norm("decoder.mid_block.attentions.0.group_norm", V1)
    lin("decoder.mid_block.attentions.0.to_q", V1, V1)
    lin("decoder.mid_block.attentions.0.to_k", V1, V1)
    lin("decoder.mid_block.attentions.0.to_v", V1, V1)
    lin("decoder.mid_block.attentions.0.to_out.0", V1, V1)
    resnet("decoder.mid_block.resnets.1.", V1, V1)
    for j in range(3):
        resnet(f"decoder.up_blocks.0.resnets.{j}.", V1, V1)
    conv("decoder.up_blocks.0.upsamplers.0.conv", V1, V1)
    resnet("decoder.up_blocks.1.resnets.0.", V1, V0)
    for j in (1, 2):
        resnet(f"decoder.up_blocks.1.resnets.{j}.", V0, V0)
    norm("decoder.conv_norm_out", V0)
    conv("decoder.conv_out", 3, V0)

    vd = os.path.join(dirpath, "vae")
    os.makedirs(vd, exist_ok=True)
    _write_safetensors(os.path.join(vd, "diffusion_pytorch_model.safetensors"), u)
    with open(os.path.join(vd, "config.json"), "w") as f:
        json.dump({"block_out_channels": [V0, V1], "latent_channels": LC,
                   "norm_num_groups": G, "scaling_factor": 0.3611,
                   "shift_factor": 0.1159}, f)
    return dirpath
