"""Shared test fixtures: a tiny REAL HF Llama checkpoint built locally.

Zero-egress environment → we can't download TinyLlama; instead we construct a
genuine transformers LlamaForCausalLM (random weights), save it as safetensors
with a trained byte-level BPE tokenizer, and treat that directory as the
checkpoint under test. Parity tests compare our engine against the HF forward
pass on the same weights — the same guarantee a downloaded model would give.
"""
from __future__ import annotations

import json
import os

CORPUS = [
    "the quick brown fox jumps over the lazy dog",
    "hello world, this is a test of the tokenizer",
    "TPU native inference with JAX and XLA collectives",
    "pack my box with five dozen liquor jugs",
    "sphinx of black quartz judge my vow",
    "números y acentos: café, naïve, über, 東京",
]

CHAT_TEMPLATE = (
    "{{ bos_token }}{% for message in messages %}"
    "<|{{ message['role'] }}|>\n{{ message['content'] }}</s>\n"
    "{% endfor %}"
    "{% if add_generation_prompt %}<|assistant|>\n{% endif %}"
)


def build_tiny_checkpoint(dirpath: str, *, vocab_size: int = 384,
                          hidden: int = 64, layers: int = 2, heads: int = 4,
                          kv_heads: int = 2, inter: int = 128,
                          tie: bool = False, seed: int = 0) -> str:
    """Create a tiny HF Llama checkpoint + tokenizer at `dirpath`."""
    import torch
    from tokenizers import Tokenizer, models, pre_tokenizers, decoders, trainers
    from transformers import LlamaConfig, LlamaForCausalLM

    os.makedirs(dirpath, exist_ok=True)

    tok = Tokenizer(models.BPE(unk_token=None))
    tok.pre_tokenizer = pre_tokenizers.ByteLevel(add_prefix_space=False)
    tok.decoder = decoders.ByteLevel()
    trainer = trainers.BpeTrainer(
        vocab_size=vocab_size - 4,
        special_tokens=["<s>", "</s>", "<|user|>", "<|assistant|>"],
        initial_alphabet=pre_tokenizers.ByteLevel.alphabet(),
        show_progress=False,
    )
    tok.train_from_iterator(CORPUS * 4, trainer=trainer)
    real_vocab = tok.get_vocab_size()
    tok.save(os.path.join(dirpath, "tokenizer.json"))
    with open(os.path.join(dirpath, "tokenizer_config.json"), "w") as f:
        json.dump({
            "bos_token": "<s>", "eos_token": "</s>",
            "add_bos_token": True,
            "chat_template": CHAT_TEMPLATE,
        }, f)

    torch.manual_seed(seed)
    cfg = LlamaConfig(
        vocab_size=real_vocab, hidden_size=hidden, intermediate_size=inter,
        num_hidden_layers=layers, num_attention_heads=heads,
        num_key_value_heads=kv_heads, max_position_embeddings=256,
        rms_norm_eps=1e-5, rope_theta=10000.0, tie_word_embeddings=tie,
        bos_token_id=0, eos_token_id=1,
    )
    model = LlamaForCausalLM(cfg)
    model.eval()
    model.save_pretrained(dirpath, safe_serialization=True)
    return dirpath


_CACHE = {}


def tiny_checkpoint(tmp_path_factory, **kw) -> str:
    """Session-cached tiny checkpoint (building one takes a few seconds)."""
    key = tuple(sorted(kw.items()))
    if key not in _CACHE:
        d = tmp_path_factory.mktemp("tinyllama")
        _CACHE[key] = build_tiny_checkpoint(str(d), **kw)
    return _CACHE[key]
