"""VITS neural TTS vs HF torch parity on a locally-built tiny random
checkpoint. Noise scales pinned to 0 make the whole pipeline (including the
stochastic duration predictor's inverse spline flows) deterministic, so the
waveforms must match sample-for-sample."""
import json

import numpy as np
import pytest


def _make_ckpt(d, stochastic=True):
    import torch
    from transformers import VitsConfig, VitsModel

    torch.manual_seed(0)
    cfg = VitsConfig(
        vocab_size=40, hidden_size=32, num_hidden_layers=2,
        num_attention_heads=2, ffn_dim=64, window_size=4,
        flow_size=32, spectrogram_bins=33,
        upsample_initial_channel=32,
        upsample_rates=[4, 4], upsample_kernel_sizes=[8, 8],
        resblock_kernel_sizes=[3, 5],
        resblock_dilation_sizes=[[1, 3], [1, 3]],
        prior_encoder_num_flows=2, prior_encoder_num_wavenet_layers=2,
        duration_predictor_num_flows=2, depth_separable_num_layers=2,
        use_stochastic_duration_prediction=stochastic,
        duration_predictor_filter_channels=32,
    )
    m = VitsModel(cfg)
    m.eval()
    m.save_pretrained(d, safe_serialization=True)
    return m


@pytest.fixture(scope="module", params=[True, False],
                ids=["stochastic-dp", "plain-dp"])
def vits_pair(request, tmp_path_factory):
    d = str(tmp_path_factory.mktemp(f"vits-{request.param}"))
    m = _make_ckpt(d, request.param)
    return d, m


def test_text_encoder_matches_hf(vits_pair):
    import torch

    from localai_tpu.models.vits import (
        load_vits_config, load_vits_params, text_encoder,
    )
    import jax.numpy as jnp

    d, m = vits_pair
    cfg = load_vits_config(d)
    params = load_vits_params(d, cfg)
    ids = np.array([[1, 5, 9, 13, 17, 21]], np.int64)

    hidden, m_p, logs_p = text_encoder(
        params, cfg, jnp.asarray(ids, jnp.int32),
        jnp.ones((1, ids.shape[1]), jnp.float32))
    with torch.no_grad():
        ref = m.text_encoder(
            input_ids=torch.tensor(ids),
            padding_mask=torch.ones(1, ids.shape[1], 1))
    np.testing.assert_allclose(np.asarray(hidden).transpose(0, 2, 1),
                               ref.last_hidden_state.numpy(),
                               rtol=2e-4, atol=2e-4)
    np.testing.assert_allclose(np.asarray(m_p), ref.prior_means.numpy(),
                               rtol=2e-4, atol=2e-4)
    np.testing.assert_allclose(np.asarray(logs_p),
                               ref.prior_log_variances.numpy(),
                               rtol=2e-4, atol=2e-4)


def test_waveform_matches_hf_deterministic(vits_pair):
    import torch

    from localai_tpu.models.vits import (
        load_vits_config, load_vits_params, synthesize_ids,
    )

    d, m = vits_pair
    cfg = load_vits_config(d)
    params = load_vits_params(d, cfg)
    ids = np.array([2, 6, 10, 14, 18, 22, 26], np.int64)

    # pin every stochastic knob to zero on both sides
    m.noise_scale = 0.0
    m.noise_scale_duration = 0.0
    m.speaking_rate = 1.0
    with torch.no_grad():
        ref = m(input_ids=torch.tensor(ids[None])).waveform.numpy()[0]

    wav = synthesize_ids(params, cfg, ids, noise_scale=0.0,
                         noise_scale_duration=0.0, speaking_rate=1.0)
    assert wav.shape == ref.shape, (wav.shape, ref.shape)
    np.testing.assert_allclose(wav, ref, rtol=2e-3, atol=2e-3)


def test_tokenizer_and_voice(tmp_path):
    from localai_tpu.models.vits import VitsCharTokenizer, VitsTTS, is_vits_dir

    d = str(tmp_path / "voice")
    _make_ckpt(d, stochastic=True)
    vocab = {"<pad>": 0}
    for i, ch in enumerate("abcdefghijklmnopqrstuvwxyz '-", start=1):
        vocab[ch] = i
    (tmp_path / "voice" / "vocab.json").write_text(json.dumps(vocab))
    assert is_vits_dir(d)

    tok = VitsCharTokenizer(d)
    ids = tok.encode("Hi a!")
    # lowercased, unknown chars dropped, blanks interleaved
    assert ids[0] == 0 and ids[-1] == 0
    assert list(ids[1::2]) == [vocab["h"], vocab["i"], vocab[" "], vocab["a"]]

    tts = VitsTTS(d)
    wav = tts.synthesize("hello world")
    assert wav.ndim == 1 and wav.size > 0
    assert np.isfinite(wav).all() and np.abs(wav).max() <= 1.0
    assert tts.rate == 16000


def test_tts_servicer_neural_voice(tmp_path):
    """LoadModel with a VITS dir serves the neural voice through the TTS
    RPC (WAV written to dst)."""
    from localai_tpu.backend import pb
    from localai_tpu.backend.whisper import TTSServicer

    d = str(tmp_path / "voice")
    _make_ckpt(d, stochastic=True)
    vocab = {"<pad>": 0}
    for i, ch in enumerate("abcdefghijklmnopqrstuvwxyz ", start=1):
        vocab[ch] = i
    (tmp_path / "voice" / "vocab.json").write_text(json.dumps(vocab))

    s = TTSServicer()
    r = s.LoadModel(pb.ModelOptions(model=d), None)
    assert r.success, r.message
    assert s.voice is not None
    dst = str(tmp_path / "out.wav")
    r = s.TTS(pb.TTSRequest(text="hello", dst=dst), None)
    assert r.success
    import wave

    with wave.open(dst) as w:
        assert w.getframerate() == 16000
        assert w.getnframes() > 0
