"""HF Inference-API passthrough backend against a local fake endpoint."""
import json
import threading

import pytest


@pytest.fixture()
def fake_hf():
    from http.server import BaseHTTPRequestHandler, HTTPServer

    seen = []

    class H(BaseHTTPRequestHandler):
        def log_message(self, *a):
            pass

        def do_POST(self):
            body = json.loads(self.rfile.read(
                int(self.headers["Content-Length"])))
            seen.append({"path": self.path, "body": body,
                         "auth": self.headers.get("Authorization", "")})
            out = json.dumps([{
                "generated_text": f"echo:{body['inputs']} STOP tail"}]).encode()
            self.send_response(200)
            self.send_header("Content-Type", "application/json")
            self.end_headers()
            self.wfile.write(out)

    srv = HTTPServer(("127.0.0.1", 0), H)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    yield f"http://127.0.0.1:{srv.server_address[1]}", seen
    srv.shutdown()


def test_predict_roundtrip(fake_hf):
    from localai_tpu.backend import pb
    from localai_tpu.backend.hfapi import HFApiServicer

    url, seen = fake_hf
    s = HFApiServicer()
    r = s.LoadModel(pb.ModelOptions(
        model="org/some-model",
        options=json.dumps({"endpoint": url, "token": "tok-1"})), None)
    assert r.success, r.message

    reply = s.Predict(pb.PredictOptions(
        prompt="hello", tokens=16, temperature=0.5,
        stop_prompts=["STOP"]), _Ctx())
    assert reply.message.decode() == "echo:hello "
    assert seen[0]["path"] == "/org/some-model"
    assert seen[0]["auth"] == "Bearer tok-1"
    assert seen[0]["body"]["parameters"]["max_new_tokens"] == 16

    chunks = list(s.PredictStream(pb.PredictOptions(prompt="x"), _Ctx()))
    assert len(chunks) == 1 and chunks[0].message.decode().startswith("echo:x")


def test_requires_token(monkeypatch):
    from localai_tpu.backend import pb
    from localai_tpu.backend.hfapi import HFApiServicer

    monkeypatch.delenv("HUGGINGFACEHUB_API_TOKEN", raising=False)
    s = HFApiServicer()
    r = s.LoadModel(pb.ModelOptions(model="m"), None)
    assert not r.success and "token" in r.message


def test_served_role_spawns(fake_hf):
    """Through the real gRPC server process role registry."""
    from localai_tpu.backend.client import BackendClient
    from localai_tpu.backend.server import serve

    url, _ = fake_hf
    server, servicer, port = serve("127.0.0.1:0", "huggingface")
    try:
        client = BackendClient(f"127.0.0.1:{port}")
        assert client.wait_ready(attempts=20, sleep=0.1)
        r = client.load_model(model="m", options=json.dumps(
            {"endpoint": url, "token": "t"}))
        assert r.success
        out = client.predict(prompt="ping")
        assert out.message.decode().startswith("echo:ping")
    finally:
        server.stop(grace=1)


class _Ctx:
    def abort(self, code, details):
        raise AssertionError(f"{code}: {details}")
