"""Grammar-constrained decoding: native matcher semantics + engine
enforcement end-to-end (a random-weight model MUST still emit valid JSON when
masked — the whole point of hard constraints)."""
import json

import pytest

from fixtures import tiny_checkpoint
from localai_tpu.functions.grammars import JSON_GRAMMAR, json_schema_grammar
from localai_tpu.functions.matcher import CompiledGrammar, GrammarCache, token_texts


def test_matcher_json_object_walk():
    vocab = ['{', '}', '"', 'a', ':', ' ', '1', '{"', '":']
    g = CompiledGrammar(JSON_GRAMMAR, vocab)
    s = g.state()

    def allowed():
        bits = s.mask_bits()
        return {vocab[i] for i in range(len(vocab))
                if bits[i >> 3] >> (i & 7) & 1}

    assert '{' in allowed() and '}' not in allowed()
    assert s.accept(vocab.index('{'))
    assert '}' in allowed()
    for t in ['"', 'a', '":', ' ', '1', '}']:
        assert s.accept(vocab.index(t)), t
    assert s.done
    # nothing may follow a completed root object
    assert not s.accept(vocab.index('{'))


def test_matcher_rejects_invalid():
    vocab = ['{', '}', ':', 'x']
    s = CompiledGrammar(JSON_GRAMMAR, vocab).state()
    assert not s.accept(vocab.index(':'))
    assert s.accept(vocab.index('{'))
    assert not s.accept(vocab.index(':'))


def test_matcher_literal_and_repetition():
    g = CompiledGrammar('root ::= "ab" [0-9]+ ("x" | "y")?',
                        ['a', 'b', '1', '23', 'x', 'y', 'q', 'ab1'])
    s = g.state()
    assert s.accept(7)      # "ab1"
    assert s.accept(3)      # "23"
    assert s.done           # repetition satisfied, optional tail pending
    assert s.accept(4)      # "x"
    assert s.done and not s.can_continue


@pytest.fixture(scope="module")
def loaded(tmp_path_factory):
    from localai_tpu.engine import Engine, EngineConfig, Tokenizer, load_config, load_params

    ckpt = tiny_checkpoint(tmp_path_factory)
    cfg = load_config(ckpt, dtype="float32")
    params = load_params(ckpt, cfg)
    tok = Tokenizer.from_dir(ckpt)
    return cfg, params, tok


def test_token_texts_bytelevel(loaded):
    _, _, tok = loaded
    texts = token_texts(tok)
    ids = tok.encode("hello world", add_bos=False)
    assert "".join(texts[i] for i in ids) == "hello world"


def test_engine_enforces_json_grammar(loaded):
    """Random weights + JSON grammar → output must parse as a JSON object."""
    from localai_tpu.engine import Engine, EngineConfig, GenRequest
    from localai_tpu.ops.sampling import SamplingParams

    cfg, params, tok = loaded
    eng = Engine(cfg, params, tok, EngineConfig(max_slots=2, max_context=128,
                                                prefill_buckets=(32,)))
    outs = list(eng.generate(GenRequest(
        tok.encode("give me json"),
        SamplingParams(temperature=0.9, seed=42),
        max_tokens=60, grammar=JSON_GRAMMAR)))
    text = "".join(o.text for o in outs)
    assert outs[-1].finished
    # a finished grammar run must be valid JSON (possibly truncated by
    # max_tokens → only require prefix validity in that case)
    if outs[-1].finish_reason in ("stop", "eos"):
        obj = json.loads(text)
        assert isinstance(obj, dict)
    else:
        assert text.startswith("{")


def test_engine_enforces_schema_grammar(loaded):
    from localai_tpu.engine import Engine, EngineConfig, GenRequest
    from localai_tpu.ops.sampling import SamplingParams

    cfg, params, tok = loaded
    g = json_schema_grammar({
        "type": "object",
        "properties": {"ok": {"type": "boolean"}},
        "required": ["ok"],
    })
    eng = Engine(cfg, params, tok, EngineConfig(max_slots=1, max_context=128,
                                                prefill_buckets=(32,)))
    outs = list(eng.generate(GenRequest(
        tok.encode("status"), SamplingParams(temperature=0.9, seed=1),
        max_tokens=40, grammar=g)))
    text = "".join(o.text for o in outs)
    if outs[-1].finish_reason in ("stop", "eos"):
        assert json.loads(text) in ({"ok": True}, {"ok": False})


def test_mixed_grammar_and_free_slots(loaded):
    """One constrained + one unconstrained request in the same batch."""
    from localai_tpu.engine import Engine, EngineConfig, GenRequest
    from localai_tpu.ops.sampling import SamplingParams

    cfg, params, tok = loaded
    eng = Engine(cfg, params, tok, EngineConfig(max_slots=2, max_context=128,
                                                prefill_buckets=(32,)))
    free_ref = Engine(cfg, params, tok, EngineConfig(
        max_slots=2, max_context=128, prefill_buckets=(32,)))
    ref_text = free_ref.generate_text(GenRequest(
        tok.encode("hello"), SamplingParams(temperature=0.0), max_tokens=8,
        ignore_eos=True))

    r1 = eng.submit(GenRequest(tok.encode("json"), SamplingParams(0.9, seed=3),
                               max_tokens=30, grammar=JSON_GRAMMAR))
    r2 = eng.submit(GenRequest(tok.encode("hello"),
                               SamplingParams(temperature=0.0),
                               max_tokens=8, ignore_eos=True))
    for _ in range(100):
        if not eng.step():
            break
    texts = {}
    for rid, q in (r1, r2):
        t = ""
        while not q.empty():
            o = q.get()
            t += o.text
        texts[rid] = t
    # the unconstrained greedy request is unaffected by its neighbor's mask
    assert texts[r2[0]] == ref_text
    assert texts[r1[0]].startswith("{")


def test_all_optional_object_commas():
    """Schemas with no required properties must still force commas between
    emitted properties (advisor finding: first-flag never cleared)."""
    schema = {"type": "object",
              "properties": {"a": {"type": "integer"},
                             "b": {"type": "integer"}},
              "required": []}
    g = json_schema_grammar(schema)
    vocab = ['{', '}', '"a"', '"b"', ':', ',', '1', ' ']
    s = CompiledGrammar(g, vocab).state()

    def allowed():
        bits = s.mask_bits()
        return {vocab[i] for i in range(len(vocab))
                if bits[i >> 3] >> (i & 7) & 1}

    for t in ['{', '"a"', ':', '1']:
        assert s.accept(vocab.index(t)), t
    # after the first property, '"b"' may NOT follow without a comma
    assert '"b"' not in allowed()
    assert ',' in allowed() and '}' in allowed()
    assert s.accept(vocab.index(','))
    for t in ['"b"', ':', '1', '}']:
        assert s.accept(vocab.index(t)), t
    assert s.done
