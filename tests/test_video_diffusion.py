"""Video diffusion: motion modules mix frames (the whole point vs the old
GIF-of-independent-frames), zero-adapter degenerates to per-frame SD, and the
backend serves a temporal video file.
"""
import numpy as np
import pytest

from fixtures import build_tiny_sd_checkpoint


def _add_motion_adapter(ckpt: str, zero: bool = False, seed: int = 0):
    """Write a diffusers-MotionAdapter-layout subdir for the tiny SD UNet."""
    import json
    import os

    from safetensors.numpy import save_file

    cfg = json.load(open(os.path.join(ckpt, "unet", "config.json")))
    chans = cfg["block_out_channels"]
    lpb = cfg.get("layers_per_block", 2)
    rng = np.random.default_rng(seed)

    w = {}

    def module(pfx, c):
        # torch Linear orientation: [out_features, in_features]
        k = 0.2 / np.sqrt(c)
        w[pfx + "norm.weight"] = np.ones(c, np.float32)
        w[pfx + "norm.bias"] = np.zeros(c, np.float32)
        w[pfx + "proj_in.weight"] = rng.normal(0, k, (c, c)).astype(np.float32)
        w[pfx + "proj_in.bias"] = np.zeros(c, np.float32)
        t = pfx + "transformer_blocks.0."
        for nm in ("norm1", "norm2"):
            w[t + nm + ".weight"] = np.ones(c, np.float32)
            w[t + nm + ".bias"] = np.zeros(c, np.float32)
        for p in ("to_q", "to_k", "to_v"):
            w[t + f"attn1.{p}.weight"] = rng.normal(0, k, (c, c)).astype(
                np.float32)
        w[t + "attn1.to_out.0.weight"] = rng.normal(0, k, (c, c)).astype(
            np.float32)
        w[t + "attn1.to_out.0.bias"] = np.zeros(c, np.float32)
        w[t + "ff.net.0.proj.weight"] = rng.normal(0, k, (4 * c, c)).astype(
            np.float32)
        w[t + "ff.net.0.proj.bias"] = np.zeros(4 * c, np.float32)
        w[t + "ff.net.2.weight"] = rng.normal(0, k, (c, 4 * c)).astype(
            np.float32)
        w[t + "ff.net.2.bias"] = np.zeros(c, np.float32)
        out = rng.normal(0, k, (c, c)).astype(np.float32)
        w[pfx + "proj_out.weight"] = np.zeros_like(out) if zero else out
        w[pfx + "proj_out.bias"] = np.zeros(c, np.float32)

    for i, c in enumerate(chans):
        for j in range(lpb):
            module(f"down_blocks.{i}.motion_modules.{j}.", c)
    module("mid_block.motion_modules.0.", chans[-1])
    for i in range(len(chans)):
        c = chans[len(chans) - 1 - i]
        for j in range(lpb + 1):
            module(f"up_blocks.{i}.motion_modules.{j}.", c)

    sub = os.path.join(ckpt, "motion_adapter")
    os.makedirs(sub, exist_ok=True)
    save_file(w, os.path.join(sub, "diffusion_pytorch_model.safetensors"))
    json.dump({"_class_name": "MotionAdapter"},
              open(os.path.join(sub, "config.json"), "w"))
    return ckpt


@pytest.fixture(scope="module")
def video_ckpt(tmp_path_factory):
    ckpt = build_tiny_sd_checkpoint(str(tmp_path_factory.mktemp("sdvid")))
    return _add_motion_adapter(ckpt)


def test_detect_video_checkpoint(video_ckpt):
    from localai_tpu.models.video_diffusion import is_video_checkpoint

    assert is_video_checkpoint(video_ckpt)


def test_frames_are_coupled(video_ckpt):
    """Motion modules make frame f depend on the other frames: changing ONE
    frame's latent init (via num_frames) must change the others' outputs —
    and a zero-proj_out adapter must reproduce the per-frame SD exactly."""
    import shutil

    from localai_tpu.models.video_diffusion import VideoDiffusion

    vd = VideoDiffusion(video_ckpt)
    vid = vd.txt2video("a cat", width=32, height=32, num_frames=4, steps=2,
                       seed=3)
    assert vid.shape == (4, 32, 32, 3) and vid.dtype == np.uint8
    # frames must NOT be identical (temporal attention is not collapse)
    assert np.abs(vid[0].astype(int) - vid[-1].astype(int)).max() > 0

    # zero adapter → identity motion modules → per-frame independence:
    zero_dir = video_ckpt + "-zero"
    if not __import__("os").path.isdir(zero_dir):
        shutil.copytree(video_ckpt, zero_dir)
        _add_motion_adapter(zero_dir, zero=True)
    vz = VideoDiffusion(zero_dir)
    vid_z = vz.txt2video("a cat", width=32, height=32, num_frames=4, steps=2,
                        seed=3)
    base = vz.base
    # frame 0 of the zero-adapter video == plain SD sampling of the same
    # latent is impossible to reproduce exactly (different RNG shapes), but
    # the LIVE adapter must differ from the zero adapter — the modules are
    # load-bearing
    assert np.abs(vid.astype(int) - vid_z.astype(int)).max() > 0


def test_backend_serves_video(video_ckpt, tmp_path):
    """The serving wrapper writes a multi-frame file via the temporal
    pipeline (not the per-frame fallback)."""
    from PIL import Image

    from localai_tpu.backend.image import _LatentWrapper
    from localai_tpu.models.video_diffusion import VideoDiffusion

    v = VideoDiffusion(video_ckpt)
    m = _LatentWrapper(v.base, v)
    dst = str(tmp_path / "out.gif")
    m.generate_video("a dog", dst, num_frames=4, fps=4, width=32, height=32,
                     steps=2, seed=1)
    im = Image.open(dst)
    assert getattr(im, "n_frames", 1) == 4
