"""Concurrency stress — the reference's `-race`-detector role (SURVEY §5).

Python has no tsan, so the race surface is exercised the way it breaks in
production: many client threads hammering submit/stream/cancel against one
engine, config reloads racing requests, and the store backend under parallel
mutation. Deterministic per-request RNG streams double as the race oracle:
a lost update or cross-slot bleed changes outputs."""
import queue
import threading

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from localai_tpu.engine import Engine, EngineConfig
from localai_tpu.engine.engine import GenRequest, SamplingParams
from localai_tpu.models.llama import LlamaConfig, init_params

CFG = LlamaConfig(vocab_size=128, hidden_size=64, intermediate_size=128,
                  num_layers=2, num_heads=4, num_kv_heads=2, head_dim=16,
                  max_position=256, dtype="float32")


def test_concurrent_submitters_deterministic():
    """16 threads × mixed prompts: every request's output must equal the
    output of the same request run alone (per-slot RNG streams must not
    bleed across concurrent slots)."""
    params = init_params(CFG, jax.random.PRNGKey(0), jnp.float32)
    eng = Engine(CFG, params, None, EngineConfig(
        max_slots=4, max_context=128, prefill_buckets=(32,),
        prefill_chunk=32))

    def run_one(engine, seed):
        prompt = [1 + (seed % 50), 2, 3 + (seed % 20)]
        _, q = engine.submit(GenRequest(
            prompt_ids=prompt, max_tokens=6, ignore_eos=True,
            params=SamplingParams(temperature=0.9, top_k=30, seed=seed)))
        toks = []
        while True:
            o = q.get(timeout=120)
            toks.append(o.token_id)
            if o.finished:
                return toks

    # serial reference outputs
    eng.start()
    try:
        expected = {seed: run_one(eng, seed) for seed in range(8)}

        results: dict[int, list[int]] = {}
        errors: list[Exception] = []

        def worker(seed):
            try:
                results[seed] = run_one(eng, seed)
            except Exception as e:  # pragma: no cover
                errors.append(e)

        threads = [threading.Thread(target=worker, args=(s,))
                   for s in range(8) for _ in range(2)]
        [t.start() for t in threads]
        [t.join(timeout=300) for t in threads]
        assert not any(t.is_alive() for t in threads), "engine deadlocked"
        assert not errors, errors
        assert len(results) == 8
        for seed, toks in results.items():
            assert toks == expected[seed], f"seed {seed} diverged under load"
    finally:
        eng.stop()


def test_submit_after_stop_rejected():
    params = init_params(CFG, jax.random.PRNGKey(0), jnp.float32)
    eng = Engine(CFG, params, None, EngineConfig(
        max_slots=2, max_context=64, prefill_buckets=(16,),
        prefill_chunk=16))
    eng.start()
    eng.stop()
    with pytest.raises(RuntimeError):
        eng.submit(GenRequest(prompt_ids=[1, 2], max_tokens=2))


def test_store_parallel_mutation():
    """Native store under 8 writer/reader threads: all writes land, finds
    return well-formed results."""
    from localai_tpu.stores import LocalStore

    store = LocalStore(dim=8)
    errors = []

    def worker(base):
        try:
            rng = np.random.default_rng(base)
            for i in range(30):
                k = rng.standard_normal(8).astype(np.float32)
                store.set([k], [f"v{base}-{i}".encode()])
                keys, vals, sims = store.find(k, 3)
                assert len(vals) == len(sims)
        except Exception as e:  # pragma: no cover
            errors.append(e)

    threads = [threading.Thread(target=worker, args=(b,)) for b in range(8)]
    [t.start() for t in threads]
    [t.join(timeout=120) for t in threads]
    assert not errors, errors
    assert len(store) == 8 * 30
