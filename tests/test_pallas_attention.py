"""Pallas kernel parity vs the reference attention ops (interpreter mode on
CPU; same code compiles for the MXU on TPU)."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from localai_tpu.ops.attention import mha_decode, mha_prefill
from localai_tpu.ops.pallas import flash_prefill, ragged_decode


def _rand(key, shape):
    return jax.random.normal(jax.random.PRNGKey(key), shape, jnp.float32)


@pytest.mark.parametrize("H,KVH", [(4, 4), (4, 2), (8, 1)])
def test_flash_prefill_matches_reference(H, KVH):
    B, S, D = 2, 64, 16
    q = _rand(0, (B, S, H, D))
    k = _rand(1, (B, S, KVH, D))
    v = _rand(2, (B, S, KVH, D))
    lengths = jnp.array([S, 37], jnp.int32)
    ref = mha_prefill(q, k, v, lengths)
    out = flash_prefill(q, k, v, lengths, block_q=16, block_k=16)
    # compare only valid rows (padded rows are garbage in both)
    for b in range(B):
        n = int(lengths[b])
        np.testing.assert_allclose(np.asarray(out[b, :n]),
                                   np.asarray(ref[b, :n]),
                                   rtol=2e-5, atol=2e-5)


def test_flash_prefill_sliding_window():
    B, S, H, D = 1, 48, 2, 8
    q, k, v = _rand(3, (B, S, H, D)), _rand(4, (B, S, H, D)), _rand(5, (B, S, H, D))
    lengths = jnp.array([S], jnp.int32)
    ref = mha_prefill(q, k, v, lengths, sliding_window=8)
    out = flash_prefill(q, k, v, lengths, sliding_window=8,
                        block_q=16, block_k=16)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("H,KVH", [(4, 4), (8, 2)])
def test_ragged_decode_matches_reference(H, KVH):
    B, T, D = 3, 64, 16
    q = _rand(6, (B, 1, H, D))
    kc = _rand(7, (B, KVH, T, D))
    vc = _rand(8, (B, KVH, T, D))
    lengths = jnp.array([5, 64, 23], jnp.int32)
    ref = mha_decode(q, kc, vc, lengths)
    out = ragged_decode(q, kc, vc, lengths, block_k=16)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_ragged_decode_sliding_window():
    B, T, H, D = 2, 32, 2, 8
    q = _rand(9, (B, 1, H, D))
    kc = _rand(10, (B, H, T, D))
    vc = _rand(11, (B, H, T, D))
    lengths = jnp.array([30, 12], jnp.int32)
    ref = mha_decode(q, kc, vc, lengths, sliding_window=8)
    out = ragged_decode(q, kc, vc, lengths, sliding_window=8, block_k=8)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_flash_prefill_partial_blocks():
    """S not a multiple of block_k: pl.ds clamps, so the kernel must pad K/V
    (round-4 review finding — silently wrong keys in the final block)."""
    B, S, H, D = 1, 192, 4, 16
    q, k, v = _rand(20, (B, S, H, D)), _rand(21, (B, S, H, D)), _rand(22, (B, S, H, D))
    lengths = jnp.array([137], jnp.int32)
    ref = mha_prefill(q, k, v, lengths)
    out = flash_prefill(q, k, v, lengths, block_q=128, block_k=128)
    np.testing.assert_allclose(np.asarray(out[0, :137]), np.asarray(ref[0, :137]),
                               rtol=2e-5, atol=2e-5)


def test_ragged_decode_partial_final_block():
    """T not a multiple of block_k: padded tail rows are undefined and must
    not poison the accumulator (round-4 review finding — NaN logits)."""
    B, T, H, KVH, D = 2, 40, 4, 2, 16
    q = _rand(23, (B, 1, H, D))
    kc = _rand(24, (B, KVH, T, D))
    vc = _rand(25, (B, KVH, T, D))
    for lens in ([40, 7], [39, 16], [33, 40]):
        lengths = jnp.array(lens, jnp.int32)
        ref = mha_decode(q, kc, vc, lengths)
        out = ragged_decode(q, kc, vc, lengths, block_k=16)
        assert np.isfinite(np.asarray(out)).all()
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-5, atol=2e-5)


def test_model_end_to_end_with_pallas(monkeypatch):
    """Whole model through the Pallas kernels (interpret mode): cached decode
    must equal the XLA-path full forward."""
    from localai_tpu.models.llama import (
        LlamaConfig, forward_train, init_kv_cache, init_params, prefill,
        decode_step,
    )
    from localai_tpu.ops.rope import rope_table

    cfg = LlamaConfig(vocab_size=128, hidden_size=32, intermediate_size=64,
                      num_layers=2, num_heads=4, num_kv_heads=2, head_dim=8,
                      max_position=64, dtype="float32")
    params = init_params(cfg, jax.random.PRNGKey(0))
    tokens = jax.random.randint(jax.random.PRNGKey(1), (1, 6), 0, 128)
    ref = np.asarray(forward_train(params, cfg, tokens))  # XLA path

    monkeypatch.setenv("LOCALAI_FORCE_PALLAS", "1")
    cos, sin = rope_table(cfg.rope, 32)
    kc, vc = init_kv_cache(cfg, 2, 32)
    lengths = jnp.array([6], jnp.int32)
    logits, kc, vc = prefill(params, cfg, tokens, lengths, cos, sin, kc, vc,
                             jnp.array([0], jnp.int32))
    np.testing.assert_allclose(np.asarray(logits[0]), ref[0, -1],
                               rtol=2e-4, atol=2e-4)
    nxt = jnp.argmax(logits, -1).astype(jnp.int32)
    slot_tokens = jnp.zeros((2,), jnp.int32).at[0].set(nxt[0])
    slot_lengths = jnp.zeros((2,), jnp.int32).at[0].set(6)
    dlogits, _, _ = decode_step(params, cfg, slot_tokens, slot_lengths,
                                cos, sin, kc, vc)
    seq = jnp.concatenate([tokens, nxt[None]], axis=1)
    monkeypatch.delenv("LOCALAI_FORCE_PALLAS")
    full = np.asarray(forward_train(params, cfg, seq))
    np.testing.assert_allclose(np.asarray(dlogits[0]), full[0, -1],
                               rtol=2e-4, atol=2e-4)


def test_bf16_io_f32_accumulate():
    B, S, H, D = 1, 32, 2, 16
    q = _rand(12, (B, S, H, D)).astype(jnp.bfloat16)
    k = _rand(13, (B, S, H, D)).astype(jnp.bfloat16)
    v = _rand(14, (B, S, H, D)).astype(jnp.bfloat16)
    lengths = jnp.array([S], jnp.int32)
    out = flash_prefill(q, k, v, lengths, block_q=16, block_k=16)
    assert out.dtype == jnp.bfloat16
    ref = mha_prefill(q.astype(jnp.float32), k.astype(jnp.float32),
                      v.astype(jnp.float32), lengths)
    np.testing.assert_allclose(np.asarray(out, np.float32), np.asarray(ref),
                               rtol=2e-2, atol=2e-2)
