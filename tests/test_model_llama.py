import jax
import jax.numpy as jnp
import numpy as np
import pytest

from localai_tpu.models.llama import (
    LlamaConfig, init_params, init_kv_cache, prefill, decode_step,
    forward_train, param_specs,
)
from localai_tpu.ops.rope import rope_table

TINY = LlamaConfig(
    vocab_size=256, hidden_size=64, intermediate_size=128, num_layers=2,
    num_heads=4, num_kv_heads=2, head_dim=16, max_position=128,
    dtype="float32",
)


@pytest.fixture(scope="module")
def tiny_params():
    return init_params(TINY, jax.random.PRNGKey(0))


def test_forward_train_shape(tiny_params):
    tokens = jnp.arange(12).reshape(2, 6) % TINY.vocab_size
    logits = forward_train(tiny_params, TINY, tokens)
    assert logits.shape == (2, 6, TINY.vocab_size)
    assert jnp.isfinite(logits).all()


def test_prefill_decode_matches_forward(tiny_params):
    """Greedy decode via cache must match argmax of the full forward pass."""
    cfg = TINY
    B, S, T = 2, 5, 32
    tokens = jax.random.randint(jax.random.PRNGKey(1), (B, S), 0, cfg.vocab_size)
    lengths = jnp.array([S, 3], jnp.int32)
    cos, sin = rope_table(cfg.rope, T)
    kc, vc = init_kv_cache(cfg, 4, T)
    slot_map = jnp.array([0, 2], jnp.int32)

    logits, kc, vc = prefill(tiny_params, cfg, tokens, lengths, cos, sin, kc, vc, slot_map)
    assert logits.shape == (B, cfg.vocab_size)

    # row 0: compare against full forward on the same sequence
    full = forward_train(tiny_params, cfg, tokens[:1])
    np.testing.assert_allclose(
        np.asarray(logits[0]), np.asarray(full[0, S - 1]), rtol=2e-4, atol=2e-4
    )

    # decode one step for slot 0 and slot 2; compare with forward on seq+tok
    next_tok = jnp.argmax(logits, -1).astype(jnp.int32)
    slot_tokens = jnp.zeros((4,), jnp.int32).at[slot_map].set(next_tok)
    slot_lengths = jnp.zeros((4,), jnp.int32).at[slot_map].set(lengths)
    dlogits, kc, vc = decode_step(tiny_params, cfg, slot_tokens, slot_lengths,
                                  cos, sin, kc, vc)
    seq = jnp.concatenate([tokens[:1], next_tok[:1][None]], axis=1)
    full2 = forward_train(tiny_params, cfg, seq)
    np.testing.assert_allclose(
        np.asarray(dlogits[0]), np.asarray(full2[0, S]), rtol=2e-4, atol=2e-4
    )


def test_param_specs_tree_matches_params(tiny_params):
    specs = param_specs(TINY)
    flat_p = jax.tree_util.tree_structure(tiny_params)
    flat_s = jax.tree_util.tree_structure(specs)
    assert flat_p == flat_s


def test_gqa_and_bias_variant():
    cfg = LlamaConfig(vocab_size=64, hidden_size=32, intermediate_size=64,
                      num_layers=1, num_heads=4, num_kv_heads=1, head_dim=8,
                      qkv_bias=True, tie_embeddings=True, dtype="float32")
    p = init_params(cfg, jax.random.PRNGKey(2))
    assert "lm_head" not in p and "bq" in p["layers"]
    logits = forward_train(p, cfg, jnp.zeros((1, 4), jnp.int32))
    assert logits.shape == (1, 4, 64)
