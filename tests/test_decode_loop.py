"""Single-dispatch on-device decode loop (ISSUE 6).

The fused `lax.while_loop` decode path (models/llama.build_decode_loop,
engine _dispatch_loop/_consume_loop) must be observationally identical to
the per-step reference path — same fused sample→decode body, same per-slot
RNG streams — while collapsing a 64–128-token block into ONE dispatch:

- fused-while vs per-step parity across mixed streams, including slots
  hitting EOS at different steps mid-block, in f32 and int8-W, dense and
  paged, single device and a 4-device virtual TP mesh;
- device-side early exit: when every slot finishes at step k of an N-step
  loop, the device step counter proves only k steps ran;
- async double-buffered token streaming: tokens arrive in order, and a
  mid-stream cancel still yields a terminal event.
"""
import time

import numpy as np
import pytest
import jax

from localai_tpu.engine.engine import Engine, EngineConfig, GenRequest
from localai_tpu.models.llama import LlamaConfig, init_params
from localai_tpu.ops.quant import quantize_params
from localai_tpu.ops.sampling import SamplingParams
from localai_tpu.parallel.mesh import MeshConfig, build_mesh

# head/kv/ffn/vocab dims all divide 4 so the same geometry runs the
# 4-device TP mesh leg
TINY = dict(vocab_size=96, hidden_size=32, intermediate_size=64,
            num_layers=2, num_heads=4, num_kv_heads=4, head_dim=16,
            max_position=512, dtype="float32")

CFG = LlamaConfig(**TINY)


class FakeTok:
    """The minimal tokenizer surface the engine's decode path touches."""

    def __init__(self, eos=()):
        self.eos_ids = set(eos)

    def stream_decoder(self):
        class _D:
            def push(self, t):
                return f"<{t}>"

            def flush(self):
                return ""

        return _D()


@pytest.fixture(scope="module")
def f32_params():
    return init_params(CFG, jax.random.PRNGKey(0))


@pytest.fixture(scope="module")
def int8_params(f32_params):
    return quantize_params(f32_params)


def _reqs(n=3, max_tokens=20):
    """Mixed prompts/knobs: greedy, seeded top-k, seeded top-p."""
    protos = [
        ([1, 2, 3, 4, 5], SamplingParams(temperature=0.0)),
        (list(range(4, 17)), SamplingParams(temperature=0.9, top_k=20,
                                            seed=7)),
        (list(range(9, 14)), SamplingParams(temperature=0.7, top_p=0.9,
                                            seed=3)),
    ]
    return [GenRequest(prompt_ids=list(p), params=sp, max_tokens=max_tokens,
                       ignore_eos=True)
            for p, sp in protos[:n]]


def _run(params, reqs, *, loop, tok=None, mesh=None, kv_pages=0,
         decode_loop=16, max_context=256):
    eng = Engine(CFG, params, tok, EngineConfig(
        max_slots=4, max_context=max_context, prefill_buckets=(16, 64),
        decode_block=1 if not loop else 8,
        decode_loop=decode_loop if loop else 0,
        mesh=mesh, kv_pages=kv_pages, prompt_cache=False))
    outs = [eng.submit(r) for r in reqs]
    for _ in range(2000):
        if not eng.step():
            break
    res = []
    for rid, q in outs:
        toks, reason = [], None
        while not q.empty():
            o = q.get()
            toks.append(o.token_id)
            if o.finished:
                reason = o.finish_reason
        res.append((toks, reason))
    return res, eng


@pytest.mark.parametrize("dtype,paged", [
    ("f32", 0), ("f32", 24), ("int8", 0), ("int8", 24),
], ids=["f32-dense", "f32-paged", "int8-dense", "int8-paged"])
def test_fused_while_matches_per_step(f32_params, int8_params, dtype, paged):
    """Parity: the while-loop path and the single-step reference emit
    identical token streams and finish reasons for a mixed stream (the loop
    reuses the same fused sample→decode body, so per-slot RNG streams line
    up step for step)."""
    params = f32_params if dtype == "f32" else int8_params
    got, loop_eng = _run(params, _reqs(), loop=True, kv_pages=paged)
    ref, ref_eng = _run(params, _reqs(), loop=False, kv_pages=paged)
    assert got == ref
    assert all(r == "length" for _, r in got)
    # the loop path actually fused: ~2 dispatches per 20-token stream at
    # decode_loop=16 vs ~20 for the per-step reference
    assert loop_eng.metrics["decode_dispatches"] * 4 <= \
        ref_eng.metrics["decode_dispatches"]


def test_mixed_eos_mid_block_parity(f32_params):
    """Slots hit EOS at DIFFERENT steps mid-block: the device-side EOS-set
    stop must finish each slot at exactly the token the host path would
    have, and the post-EOS loop iterations must not perturb any surviving
    slot's stream."""
    # discover each slot's unconstrained stream, then promote tokens that
    # appear at different depths (step 3 of slot 0, step 9 of slot 1) to EOS
    base, _ = _run(f32_params, _reqs(), loop=False)
    eos = {base[0][0][3], base[1][0][9]}
    tok = FakeTok(eos)
    reqs = [GenRequest(prompt_ids=r.prompt_ids, params=r.params,
                       max_tokens=r.max_tokens, ignore_eos=False)
            for r in _reqs()]

    def fresh():
        return [GenRequest(prompt_ids=list(r.prompt_ids), params=r.params,
                           max_tokens=r.max_tokens, ignore_eos=False)
                for r in reqs]

    got, _ = _run(f32_params, fresh(), loop=True, tok=tok)
    ref, _ = _run(f32_params, fresh(), loop=False, tok=tok)
    assert got == ref
    reasons = [r for _, r in got]
    assert reasons.count("eos") >= 2, reasons
    # EOS at step 3 means 4 emitted tokens (the EOS token is emitted with
    # finished=True, matching the host path)
    assert len(got[0][0]) == 4


@pytest.mark.tp
def test_fused_while_parity_on_tp_mesh(f32_params, int8_params):
    """Loop vs per-step parity UNDER the same 4-device TP mesh (f32 and
    int8-W): the sharding constraints inside the loop body must reproduce
    the scan block's numerics exactly — same mesh, same reduction order."""
    for params in (f32_params, int8_params):
        mesh = build_mesh(MeshConfig(data=1, model=4), jax.devices()[:4])
        got, _ = _run(params, _reqs(n=2), loop=True, mesh=mesh)
        mesh = build_mesh(MeshConfig(data=1, model=4), jax.devices()[:4])
        ref, _ = _run(params, _reqs(n=2), loop=False, mesh=mesh)
        assert got == ref
        assert all(r == "length" for _, r in got)


def test_early_exit_skips_dead_steps(f32_params):
    """All slots finish at step 3 of a 64-step loop: the device's step
    counter (credited into decode_steps_dispatched at consume) proves the
    loop exited instead of burning the remaining 61 steps."""
    eng = Engine(CFG, f32_params, None, EngineConfig(
        max_slots=2, max_context=256, prefill_buckets=(16,),
        decode_loop=64, prompt_cache=False))
    reqs = [GenRequest(prompt_ids=[1 + i, 2, 3], max_tokens=3,
                       params=SamplingParams(temperature=0.0),
                       ignore_eos=True) for i in range(2)]
    outs = [eng.submit(r) for r in reqs]
    for _ in range(100):
        if not eng.step():
            break
    for _, q in outs:
        last = None
        while not q.empty():
            last = q.get()
        assert last.finished and last.finish_reason == "length"
    assert eng.metrics["decode_dispatches"] == 1
    assert eng.metrics["decode_steps_dispatched"] == 3
    assert eng.metrics["tokens_generated"] == 6


def test_async_stream_order_and_mid_stream_cancel(f32_params):
    """Under double-buffered async fetches tokens still stream strictly in
    order, and cancelling mid-stream yields a terminal cancelled event while
    a concurrent stream runs to completion."""
    eng = Engine(CFG, f32_params, None, EngineConfig(
        max_slots=2, max_context=256, prefill_buckets=(16,),
        decode_loop=16, prompt_cache=False))
    eng.start()
    try:
        rid, q = eng.submit(GenRequest(
            prompt_ids=[1, 2, 3], params=SamplingParams(temperature=0.0),
            max_tokens=200, ignore_eos=True))
        _, q2 = eng.submit(GenRequest(
            prompt_ids=[4, 5], params=SamplingParams(temperature=0.0),
            max_tokens=24, ignore_eos=True))
        seen = [q.get(timeout=30) for _ in range(5)]
        # strictly ordered, gapless stream
        assert [o.generated_tokens for o in seen] == [1, 2, 3, 4, 5]
        eng.cancel(rid)
        deadline = time.monotonic() + 30
        last = None
        while time.monotonic() < deadline:
            last = q.get(timeout=30)
            if last.finished:
                break
        assert last is not None and last.finished
        assert last.finish_reason == "cancelled"
        # cancellation latency is bounded by the loop block, not max_tokens
        assert last.generated_tokens < 200
        # the surviving stream is unaffected and terminates normally
        toks = []
        while True:
            o = q2.get(timeout=30)
            toks.append(o.token_id)
            if o.finished:
                assert o.finish_reason == "length"
                break
        assert len(toks) == 24
    finally:
        eng.stop()


def test_loop_respects_max_tokens_exactly(f32_params):
    """Pipelined loop dispatches must never overshoot a budget: per-slot
    reservations make the second in-flight block skip slots whose budget is
    fully reserved."""
    for n in (1, 15, 16, 17, 40):
        eng = Engine(CFG, f32_params, None, EngineConfig(
            max_slots=1, max_context=256, prefill_buckets=(16,),
            decode_loop=16, prompt_cache=False))
        _, q = eng.submit(GenRequest(
            prompt_ids=[1, 2, 3], params=SamplingParams(temperature=0.0),
            max_tokens=n, ignore_eos=True))
        for _ in range(500):
            if not eng.step():
                break
        toks = []
        while not q.empty():
            o = q.get()
            toks.append(o.token_id)
        assert len(toks) == n, f"max_tokens={n} emitted {len(toks)}"
        assert o.finished and o.finish_reason == "length"
