"""Manifest-level key-mapping tests at REAL released-checkpoint geometry.

The per-model parity tests use tiny random-weight HF models, which validates
math but not key-mapping breadth: a renamed key family in the real released
layout would only surface in production. These tests instantiate the HF
architectures at the EXACT hyperparameters of real released checkpoints
(whisper-base, facebook/mms-tts-eng — values from their public config.json),
save them, and assert our loaders consume every key in the file (minus a
documented inference-irrelevant skip set) and produce a working forward.

Weights are random (zero-egress image) — the *key manifest and shapes* are
identical to the released artifacts, which is what these tests pin.
"""
import numpy as np
import pytest

import localai_tpu.engine.loader as loader_mod


@pytest.fixture()
def key_recorder(monkeypatch):
    """Record every tensor name the loaders request from _TensorReader."""
    requested: set[str] = set()
    orig = loader_mod._TensorReader.get

    def tracking_get(self, name):
        requested.add(name)
        return orig(self, name)

    monkeypatch.setattr(loader_mod._TensorReader, "get", tracking_get)
    return requested


def _all_keys(model_dir: str) -> set[str]:
    r = loader_mod._TensorReader(model_dir)
    try:
        return set(r.index.keys())
    finally:
        r.close()


def test_whisper_base_manifest(tmp_path, key_recorder):
    """openai/whisper-base layout: every checkpoint key is consumed and the
    enc-dec transcription path runs."""
    import torch
    from transformers import WhisperConfig, WhisperForConditionalGeneration

    torch.manual_seed(0)
    cfg_hf = WhisperConfig(        # public whisper-base config.json values
        vocab_size=51865, num_mel_bins=80,
        d_model=512, encoder_layers=6, encoder_attention_heads=8,
        decoder_layers=6, decoder_attention_heads=8,
        encoder_ffn_dim=2048, decoder_ffn_dim=2048,
        max_source_positions=1500, max_target_positions=448)
    m = WhisperForConditionalGeneration(cfg_hf)
    m.generation_config.forced_decoder_ids = None
    m.generation_config.suppress_tokens = None
    m.generation_config.begin_suppress_tokens = None
    d = str(tmp_path / "whisper-base")
    m.save_pretrained(d, safe_serialization=True)

    from localai_tpu.models.whisper import WhisperModel, load_config

    cfg = load_config(d)
    assert (cfg.d_model, cfg.encoder_layers, cfg.heads) == (512, 6, 8)

    w = WhisperModel(d)

    available = _all_keys(d)
    unread = available - key_recorder
    # proj_out is tied to decoder.embed_tokens (dropped by safetensors when
    # tied; consumed via the embed key when present)
    unread -= {"proj_out.weight"}
    assert not unread, f"loader never read: {sorted(unread)[:10]}"

    audio = (0.01 * np.random.default_rng(0).standard_normal(16000)
             ).astype(np.float32)
    toks = w.transcribe_tokens(audio, max_tokens=8, beam_size=1,
                               temperatures=(0.0,))
    assert isinstance(toks, list)        # random weights → arbitrary ids


def test_mms_tts_eng_manifest(tmp_path, key_recorder):
    """facebook/mms-tts-eng layout (full-size VITS incl. weight-norm
    parametrizations + stochastic duration predictor): all inference keys
    consumed, synthesis runs end to end."""
    import torch
    from transformers import VitsConfig, VitsModel

    torch.manual_seed(0)
    # public mms-tts-eng config.json: the architecture fields are the
    # transformers VitsConfig defaults; eng's vocab is 38
    cfg_hf = VitsConfig(vocab_size=38)
    m = VitsModel(cfg_hf)
    d = str(tmp_path / "mms-tts-eng")
    m.save_pretrained(d, safe_serialization=True)

    from localai_tpu.models.vits import (
        load_vits_config, load_vits_params, synthesize_ids,
    )

    cfg = load_vits_config(d)
    assert (cfg.hidden_size, cfg.num_layers, cfg.ffn_dim) == (192, 6, 768)
    assert cfg.upsample_rates == (8, 8, 2, 2)
    params = load_vits_params(d, cfg)

    available = _all_keys(d)
    unread = {k for k in available if k not in key_recorder}
    # the posterior encoder (audio → latent) and the stochastic duration
    # predictor's post_* branch (posterior over latent durations) exist only
    # for training; inference runs text encoder + reverse flows + decoder
    unread = {k for k in unread
              if not k.startswith("posterior_encoder.")
              and not k.startswith("duration_predictor.post_")}
    assert not unread, f"loader never read: {sorted(unread)[:10]}"

    ids = np.array([1, 5, 9, 3, 2, 7], np.int32)
    wav = synthesize_ids(params, cfg, ids, seed=0)
    assert wav.ndim == 1 and len(wav) > 256   # 256x upsample of >=1 frame
    assert np.isfinite(wav).all()
