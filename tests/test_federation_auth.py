"""Federation auth: HMAC header round trip, replay window, LB enforcement +
upstream re-signing, worker middleware acceptance, explorer registration
gate. (VERDICT r4 missing #2: the federation layer had no auth at all;
reference trust model: p2p token+OTP, core/p2p/p2p.go:31-66.)
"""
import asyncio
import threading

import pytest
from aiohttp import web

from localai_tpu.federation import FederatedServer
from localai_tpu.federation.auth import HEADER, sign, verify


def test_sign_verify_roundtrip():
    h = sign("tok", "POST", "/v1/chat", b"{}")
    assert verify("tok", h, "POST", "/v1/chat", b"{}")
    # any binding mismatch fails
    assert not verify("tok", h, "GET", "/v1/chat", b"{}")
    assert not verify("tok", h, "POST", "/v1/other", b"{}")
    assert not verify("tok", h, "POST", "/v1/chat", b"{x}")
    assert not verify("other", h, "POST", "/v1/chat", b"{}")
    assert not verify("tok", None, "POST", "/v1/chat", b"{}")
    assert not verify("tok", "garbage", "POST", "/v1/chat", b"{}")


def test_replay_window():
    h = sign("tok", "GET", "/x", b"", ts=1000)
    assert verify("tok", h, "GET", "/x", b"", now=1050)
    assert not verify("tok", h, "GET", "/x", b"", now=1200)  # stale
    assert not verify("tok", h, "GET", "/x", b"", now=800)   # future skew


class _Loop:
    """Run aiohttp apps on a background loop; returns base URLs."""

    def __init__(self):
        self.loop = asyncio.new_event_loop()
        self.thread = threading.Thread(target=self._run, daemon=True)
        self.thread.start()

    def _run(self):
        asyncio.set_event_loop(self.loop)
        self.loop.run_forever()

    def serve(self, app) -> str:
        import socket

        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
        s.close()

        async def start():
            runner = web.AppRunner(app)
            await runner.setup()
            site = web.TCPSite(runner, "127.0.0.1", port)
            await site.start()

        asyncio.run_coroutine_threadsafe(start(), self.loop).result(10)
        return f"http://127.0.0.1:{port}"

    def close(self):
        self.loop.call_soon_threadsafe(self.loop.stop)


@pytest.fixture(scope="module")
def loops():
    lo = _Loop()
    yield lo
    lo.close()


def _worker_app(seen):
    """Echo worker that records the federation header it received."""
    async def echo(request):
        seen.append(request.headers.get(HEADER))
        return web.json_response({"ok": True, "path": request.path})

    app = web.Application()
    app.router.add_route("*", "/{tail:.*}", echo)
    return app


def test_lb_requires_and_resigns_token(loops):
    import urllib.error
    import urllib.request

    seen = []
    worker_url = loops.serve(_worker_app(seen))
    lb = FederatedServer([worker_url], token="sekrit")
    lb_url = loops.serve(lb.app)

    # unsigned → 401
    with pytest.raises(urllib.error.HTTPError) as e:
        urllib.request.urlopen(lb_url + "/v1/models", timeout=10)
    assert e.value.code == 401

    # signed → proxied, and the upstream hop carries a FRESH valid signature
    req = urllib.request.Request(lb_url + "/v1/models")
    req.add_header(HEADER, sign("sekrit", "GET", "/v1/models"))
    body = urllib.request.urlopen(req, timeout=10).read()
    assert b"ok" in body
    assert seen and seen[-1] is not None
    assert verify("sekrit", seen[-1], "GET", "/v1/models", b"")

    # /federation/workers is gated too
    with pytest.raises(urllib.error.HTTPError) as e:
        urllib.request.urlopen(lb_url + "/federation/workers", timeout=10)
    assert e.value.code == 401


def test_lb_open_without_token(loops):
    import urllib.request

    seen = []
    worker_url = loops.serve(_worker_app(seen))
    lb = FederatedServer([worker_url])
    lb_url = loops.serve(lb.app)
    body = urllib.request.urlopen(lb_url + "/v1/models", timeout=10).read()
    assert b"ok" in body


def test_explorer_registration_gate(loops, tmp_path):
    import json
    import urllib.error
    import urllib.request

    from localai_tpu.explorer import Database, build_explorer_app

    db = Database(path=str(tmp_path / "flock.json"))
    url = loops.serve(build_explorer_app(db, register_token="reg"))
    payload = json.dumps({"url": "http://n1", "name": "n1"}).encode()

    req = urllib.request.Request(url + "/network/add", payload,
                                 {"Content-Type": "application/json"})
    with pytest.raises(urllib.error.HTTPError) as e:
        urllib.request.urlopen(req, timeout=10)
    assert e.value.code == 401

    req.add_header(HEADER, sign("reg", "POST", "/network/add", payload))
    out = json.loads(urllib.request.urlopen(req, timeout=10).read())
    assert out["ok"] is True
    # reads stay open
    nets = json.loads(urllib.request.urlopen(url + "/networks",
                                             timeout=10).read())
    assert len(nets) == 1
