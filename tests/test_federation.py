"""Federated LB tests: selection strategies, proxying, dead-worker skip
(reference federated_server.go semantics) against lightweight fake workers."""
import asyncio
import json
import threading

import pytest
import requests
from aiohttp import web

from localai_tpu.federation import FederatedServer, Worker


def _free_port():
    import socket

    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    p = s.getsockname()[1]
    s.close()
    return p


class _Stack:
    """Run a set of aiohttp apps in one background loop."""

    def __init__(self):
        self.loop = asyncio.new_event_loop()
        self.thread = threading.Thread(target=self._run, daemon=True)
        self.thread.start()

    def _run(self):
        asyncio.set_event_loop(self.loop)
        self.loop.run_forever()

    def serve(self, app) -> int:
        port = _free_port()

        async def start():
            runner = web.AppRunner(app)
            await runner.setup()
            site = web.TCPSite(runner, "127.0.0.1", port)
            await site.start()

        asyncio.run_coroutine_threadsafe(start(), self.loop).result(10)
        return port

    def stop(self):
        self.loop.call_soon_threadsafe(self.loop.stop)


def _worker_app(name: str):
    app = web.Application()

    async def health(request):
        return web.json_response({"status": "ok"})

    async def who(request):
        return web.json_response({"worker": name})

    async def echo(request):
        body = await request.json()
        return web.json_response({"worker": name, "echo": body})

    app.router.add_get("/healthz", health)
    app.router.add_get("/v1/models", who)
    app.router.add_post("/v1/chat/completions", echo)
    return app


@pytest.fixture(scope="module")
def stack():
    s = _Stack()
    yield s
    s.stop()


def test_proxy_and_strategies(stack):
    p1 = stack.serve(_worker_app("w1"))
    p2 = stack.serve(_worker_app("w2"))
    urls = [f"http://127.0.0.1:{p1}", f"http://127.0.0.1:{p2}"]

    fed = FederatedServer(urls, strategy="round_robin")
    fport = stack.serve(fed.app)
    base = f"http://127.0.0.1:{fport}"

    seen = set()
    for _ in range(4):
        r = requests.get(base + "/v1/models", timeout=10)
        assert r.status_code == 200
        seen.add(r.json()["worker"])
    assert seen == {"w1", "w2"}  # round robin alternates

    r = requests.post(base + "/v1/chat/completions",
                      json={"messages": [{"role": "user", "content": "x"}]},
                      timeout=10)
    assert r.json()["echo"]["messages"][0]["content"] == "x"

    r = requests.get(base + "/federation/workers", timeout=10)
    info = r.json()
    assert len(info) == 2 and all(w["total"] > 0 for w in info)


def test_least_used_picks_idle_worker():
    fed = FederatedServer(["http://a", "http://b"], strategy="least_used")
    fed.workers[0].in_flight = 5
    assert fed.pick().url == "http://b"
    fed.workers[1].in_flight = 9
    assert fed.pick().url == "http://a"


def test_dead_worker_skipped(stack):
    p1 = stack.serve(_worker_app("alive"))
    dead_port = _free_port()  # nothing listens here
    fed = FederatedServer([f"http://127.0.0.1:{dead_port}",
                           f"http://127.0.0.1:{p1}"],
                          strategy="round_robin", health_interval=0.0)
    fport = stack.serve(fed.app)
    base = f"http://127.0.0.1:{fport}"
    for _ in range(3):
        r = requests.get(base + "/v1/models", timeout=15)
        assert r.status_code == 200
        assert r.json()["worker"] == "alive"


def test_bad_strategy_rejected():
    with pytest.raises(ValueError):
        FederatedServer(["http://x"], strategy="wat")
