"""Session KV hierarchy tests (engine/kvhost.py, ISSUE 17): the host-RAM
spill tier (budget/LRU/pin/digest units), the federation text-chain prefix
digest + KV-affinity picker, and the engine-driving re-admission flows
(greedy parity through re-admitted int8 blocks, worker-restart adoption
of a survivor pool).  Pool/digest/affinity units run in tier-1; the
engine-driving streams are slow-marked and run standalone via -m session.
"""
import numpy as np
import pytest

from localai_tpu.engine.kvhost import (
    HostKVBlock, HostKVPool, PrefixDigest, body_prompt_text, coverage,
    request_hint, text_chain_ids,
)


def _blk(seed: int = 0) -> HostKVBlock:
    """A tiny deterministic block: 8+16+8+16 = 48 bytes."""
    r = np.random.default_rng(seed)
    return HostKVBlock(
        kq=r.integers(-128, 127, (1, 1, 4, 2)).astype(np.int8),
        ks=r.random((1, 1, 1, 4)).astype(np.float32),
        vq=r.integers(-128, 127, (1, 1, 4, 2)).astype(np.int8),
        vs=r.random((1, 1, 1, 4)).astype(np.float32),
    )


BLK_BYTES = _blk().nbytes        # 48


def _h(i: int) -> bytes:
    return i.to_bytes(16, "big")


# ------------------------------------------------------------ pool units


def test_pool_put_get_roundtrip():
    pool = HostKVPool(budget_bytes=1 << 20)
    b = _blk(1)
    assert pool.accepts(_h(1))
    assert pool.put(_h(1), b) == 0
    assert pool.contains(_h(1)) and len(pool) == 1
    got = pool.get(_h(1))
    np.testing.assert_array_equal(got.kq, b.kq)
    np.testing.assert_array_equal(got.vs, b.vs)
    # non-destructive: still resident, hit counted
    assert pool.contains(_h(1))
    st = pool.stats()
    assert st["hits"] == 1 and st["spills"] == 1 and st["bytes"] == b.nbytes
    assert pool.get(_h(2)) is None and pool.stats()["misses"] == 1


def test_pool_refuses_dups_zero_budget_and_oversized():
    pool = HostKVPool(budget_bytes=0)
    assert not pool.accepts(_h(1))
    assert pool.put(_h(1), _blk()) == 0 and len(pool) == 0
    pool = HostKVPool(budget_bytes=1 << 20)
    pool.put(_h(1), _blk())
    assert not pool.accepts(_h(1))          # dup pre-flight
    pool.put(_h(1), _blk())                 # dup put refused
    assert len(pool) == 1 and pool.stats()["rejects"] == 1
    tiny = HostKVPool(budget_bytes=BLK_BYTES - 1)   # block > whole budget
    assert tiny.put(_h(1), _blk()) == 0
    assert len(tiny) == 0 and tiny.stats()["rejects"] == 1


def test_pool_budget_evicts_lru_group_tail_first():
    # room for exactly 3 blocks; two groups of 2 would overflow by 1
    pool = HostKVPool(budget_bytes=3 * BLK_BYTES)
    g1, g2 = _h(100), _h(200)
    pool.put(_h(1), _blk(1), group=g1)
    pool.put(_h(2), _blk(2), group=g1)
    pool.put(_h(3), _blk(3), group=g2)
    assert pool.put(_h(4), _blk(4), group=g2) == 1
    # oldest group (g1) loses its TAIL block (_h(2)); its head survives
    assert pool.contains(_h(1)) and not pool.contains(_h(2))
    assert pool.contains(_h(3)) and pool.contains(_h(4))
    st = pool.stats()
    assert st["evictions"] == 1 and st["bytes"] == 3 * BLK_BYTES
    assert st["peak_bytes"] == 4 * BLK_BYTES
    # a get() touches g1 to MRU: the next overflow victimizes g2 instead
    pool.get(_h(1))
    pool.put(_h(5), _blk(5), group=g1)
    assert not pool.contains(_h(4)) and pool.contains(_h(1))


def test_pool_pin_blocks_eviction():
    pool = HostKVPool(budget_bytes=2 * BLK_BYTES)
    pool.put(_h(1), _blk(1), group=_h(100))
    pool.put(_h(2), _blk(2), group=_h(100))
    assert pool.pin(_h(1)) and pool.pin(_h(2))
    # everything resident is pinned: the only evictable block is the
    # newcomer itself, so the budget holds and the pinned pair survives
    pool.put(_h(3), _blk(3), group=_h(200))
    assert pool.contains(_h(1)) and pool.contains(_h(2))
    assert not pool.contains(_h(3))
    assert pool.stats()["bytes"] == 2 * BLK_BYTES
    pool.unpin(_h(2))
    pool.put(_h(4), _blk(4), group=_h(200))
    assert not pool.contains(_h(2))         # unpinned tail goes first
    assert pool.contains(_h(4))
    assert not pool.pin(_h(99))             # absent hash


def test_pool_digest_mru_groups_chain_order():
    pool = HostKVPool(budget_bytes=1 << 20)
    pool.put(_h(1), _blk(1), group=_h(100))
    pool.put(_h(2), _blk(2), group=_h(100))
    pool.put(_h(3), _blk(3), group=_h(200))
    # g200 is MRU: digest leads with it, then g100 in CHAIN order
    assert pool.digest() == [_h(3).hex(), _h(1).hex(), _h(2).hex()]
    pool.get(_h(1))                          # touch g100
    assert pool.digest(k=2) == [_h(1).hex(), _h(2).hex()]


# ------------------------------------------- text-chain ids / coverage


def test_text_chain_ids_chained_prefix_stability():
    a = "x" * 1024 + "y" * 512
    ids_a = text_chain_ids(a)
    assert len(ids_a) == 3
    # growing the conversation keeps the leading ids identical
    assert text_chain_ids(a + "z" * 600)[:3] == ids_a
    # trailing partial chunk never hashes
    assert text_chain_ids(a + "z" * 100) == ids_a
    # chaining: same chunk content, different prefix -> different id
    b = "w" * 512 + a[512:]
    assert text_chain_ids(b)[1] != ids_a[1]
    assert text_chain_ids("short") == []
    assert len(text_chain_ids("q" * 10240, limit=4)) == 4


def test_body_prompt_text_shapes():
    msgs = {"messages": [
        {"role": "system", "content": "be brief"},
        {"role": "user", "content": [
            {"type": "text", "text": "what is"},
            {"type": "image_url", "image_url": {"url": "ignored"}},
            {"type": "text", "text": " this?"},
        ]},
    ]}
    t = body_prompt_text(msgs)
    assert "be brief" in t and "what is this?" in t and "ignored" not in t
    # role participates (same content under another role must differ)
    other = {"messages": [{"role": "user", "content": "be brief"}]}
    assert body_prompt_text(other) != body_prompt_text(
        {"messages": [{"role": "system", "content": "be brief"}]})
    assert body_prompt_text({"prompt": "plain"}) == "plain"
    assert body_prompt_text({"prompt": ["a", "b"]}) == "ab"
    assert body_prompt_text({"prompt": 7}) == ""
    assert body_prompt_text("nope") == ""


def test_prefix_digest_mru_and_cap():
    d = PrefixDigest(cap=3)
    d.add(["a", "b"])
    d.add(["c", "d"])                        # 'a' falls off the cap
    assert len(d) == 3
    assert d.to_list() == ["d", "c", "b"]    # MRU first
    d.add(["b"])                             # touch to MRU
    assert d.to_list(k=2) == ["b", "d"]
    d.add([])                                # no-op


def test_coverage_leading_run_only():
    digest = frozenset(["a", "b", "d"])
    assert coverage(digest, ["a", "b", "c", "d"]) == 2
    assert coverage(digest, ["c", "a"]) == 0  # mid-match without head: 0
    assert coverage(digest, []) == 0
    assert coverage(frozenset(), ["a"]) == 0
    assert coverage(["a", "b"], ["a", "b"]) == 2   # list digest works too


def test_request_hint_best_effort():
    import json

    body = {"messages": [{"role": "user", "content": "m" * 2048}]}
    hint = request_hint(json.dumps(body).encode())
    assert hint == text_chain_ids(body_prompt_text(body))
    assert len(hint) >= 2
    assert request_hint(b"not json{") == []
    assert request_hint(json.dumps({"prompt": ""}).encode()) == []


# ------------------------------------------------------------ federation


def test_pick_prefers_kv_coverage():
    from localai_tpu.federation import FederatedServer

    fed = FederatedServer(["http://a", "http://b", "http://c"])
    wa, wb, wc = fed.workers
    hint = text_chain_ids("h" * 2048)        # 4 ids
    wb.kv_digest = frozenset(hint[:3])
    wc.kv_digest = frozenset(hint[:1])
    assert fed.pick(prompt_hint=hint) is wb
    # no hint: falls back to least_used
    wa.in_flight, wb.in_flight, wc.in_flight = 0, 5, 5
    assert fed.pick() is wa
    # zero coverage everywhere: strategy decides, not affinity
    assert fed.pick(prompt_hint=["zzz"]) is wa


def test_pick_affinity_skips_dead_and_degraded():
    from localai_tpu.federation import FederatedServer

    fed = FederatedServer(["http://a", "http://b"])
    wa, wb = fed.workers
    hint = text_chain_ids("h" * 2048)
    wa.kv_digest = frozenset(hint)
    wa.healthy = False                       # KV lives on a dead worker
    assert fed.pick(prompt_hint=hint) is wb  # affinity never picks dead
    wb.healthy = False                       # fully degraded cluster
    got = fed.pick(prompt_hint=hint)
    assert got is not None                   # any worker beats none
    # coverage ties break by strategy (least_used)
    wa.healthy = wb.healthy = True
    wb.kv_digest = frozenset(hint)
    wa.in_flight, wb.in_flight = 9, 1
    assert fed.pick(prompt_hint=hint) is wb


def test_sched_reason_codes_registered():
    from localai_tpu.telemetry.sched import REASON_CODES, reason_category

    for code in ("kv_host_spill", "kv_host_readmit",
                 "kv_host_miss_reprefill", "kv_host_evict_budget"):
        assert code in REASON_CODES
        assert reason_category(code) == "kv"


# ------------------------------------------------------ engine-driving

TINY = dict(vocab_size=128, hidden_size=64, intermediate_size=128,
            num_layers=2, num_heads=4, num_kv_heads=2, head_dim=16,
            max_position=512, dtype="float32")


@pytest.fixture(scope="module")
def tiny_parts():
    import jax

    from localai_tpu.models.llama import LlamaConfig, init_params

    cfg = LlamaConfig(**TINY)
    return cfg, init_params(cfg, jax.random.PRNGKey(0))


def _engine(tiny_parts, **kw):
    from localai_tpu.engine.engine import Engine, EngineConfig

    cfg, params = tiny_parts
    kvhost = kw.pop("kvhost", None)
    # kv_pages is TIGHT on purpose: 5 usable blocks barely fit one
    # conversation, so the churn tenants must reclaim the released turn-1
    # chain — the host tier is then its only home
    base = dict(max_slots=2, max_context=512, prefill_buckets=(64,),
                prefill_chunk=64, kv_pages=6, prompt_cache=True,
                cache_type="int8")
    base.update(kw)
    return Engine(cfg, params, None, EngineConfig(**base), kvhost=kvhost)


def _run(eng, ids, n=8):
    from localai_tpu.engine.engine import GenRequest
    from localai_tpu.ops.sampling import SamplingParams

    rid, out = eng.submit(GenRequest(
        prompt_ids=list(ids), max_tokens=n,
        params=SamplingParams(temperature=0.0), ignore_eos=True))
    toks = []
    while True:
        eng.step()
        while not out.empty():
            so = out.get()
            if so.token_id >= 0:
                toks.append(so.token_id)
            if so.finished:
                while eng.step():
                    pass
                return toks


def _churn(eng, n_tenants=3, length=256):
    for s in range(41, 41 + n_tenants):
        r = np.random.default_rng(s)
        _run(eng, r.integers(1, 127, length).tolist(), n=4)


def test_kv_host_requires_paged_pool(tiny_parts):
    with pytest.raises(ValueError, match="paged"):
        _engine(tiny_parts, kv_pages=0, kv_host_bytes=1 << 20)


@pytest.mark.slow
@pytest.mark.session
def test_readmission_parity_and_budget(tiny_parts):
    """Turn 2 after device-pool churn re-admits spilled int8 blocks from
    the host tier and reproduces the warm device-hit greedy stream bit for
    bit; metrics move and the byte budget holds."""
    r = np.random.default_rng(7)
    t1 = r.integers(1, 127, 256).tolist()

    warm = _engine(tiny_parts)               # no host tier: device hit ref
    g1 = _run(warm, t1)
    conv = t1 + g1 + r.integers(1, 127, 64).tolist()
    ref = _run(warm, conv)                   # retained-on-device resume

    eng = _engine(tiny_parts, kv_host_bytes=1 << 26)
    assert _run(eng, t1) == g1
    _churn(eng)                              # reclaim turn-1's chain
    eng._host_drain()
    st = eng.kvhost_snapshot()
    assert st["blocks"] > 0 and st["spills"] > 0
    assert eng._kvhost.digest()              # gossip sees the spills
    hits0 = eng.metrics["kv_host_hits"]
    got = _run(eng, conv)
    eng._host_drain()
    assert eng.metrics["kv_host_hits"] > hits0      # host tier actually hit
    assert got == ref                                # greedy parity 1.00
    st = eng.kvhost_snapshot()
    assert st["peak_bytes"] <= st["budget_bytes"]
    assert eng.metrics["kv_host_bytes_peak"] == st["peak_bytes"]
    assert "pending" in st
    # reason codes reached the sched ledger
    codes = (eng.sched_snapshot().get("reason_counters") or {})
    assert codes.get("kv_host_spill", 0) > 0
    assert codes.get("kv_host_readmit", 0) > 0


@pytest.mark.slow
@pytest.mark.session
def test_worker_restart_adopts_survivor_pool(tiny_parts):
    """A FRESH engine handed the survivor HostKVPool re-admits the old
    worker's spilled blocks: turn 2 after a restart matches the warm
    stream without re-prefilling the covered prefix."""
    r = np.random.default_rng(9)
    t1 = r.integers(1, 127, 256).tolist()

    warm = _engine(tiny_parts)
    g1 = _run(warm, t1)
    conv = t1 + g1 + r.integers(1, 127, 64).tolist()
    ref = _run(warm, conv)

    old = _engine(tiny_parts, kv_host_bytes=1 << 26)
    assert _run(old, t1) == g1
    _churn(old)
    old._host_drain()
    survivor = old._kvhost
    assert len(survivor) > 0

    fresh = _engine(tiny_parts, kvhost=survivor)     # the restarted worker
    hits0 = survivor.stats()["hits"]
    got = _run(fresh, conv)
    assert survivor.stats()["hits"] > hits0
    assert got == ref
    reused = int(fresh.metrics.get("prompt_tokens_reused", 0))
    assert reused >= 128                     # at least one re-admitted block
