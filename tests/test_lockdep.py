"""Lock-order analysis suite (ISSUE 20): the static whole-program analyzer
(tools/lockdep — caught + allowed case per check, including reconstructions
of the PR 4 watchdog bug and the PR 18 spill/evict inversion), the runtime
LOCALAI_LOCKDEP tripwire (two-thread inversion with both stacks, self-
deadlock, record mode, hold-time trips), and the schedule-perturbing
`races` lane re-running the three hairy lock trios — kvhost spill/evict/
readmit, manager watchdog/supervised/load, engine preempt/cancel/decode —
under seeded sys.setswitchinterval jitter.

Static + runtime units run in tier-1; the trios carry `races` + `slow` and
run in the CI resilience job via -m races.
"""
import textwrap
import threading
import time

import numpy as np
import pytest

from localai_tpu.testing import lockdep as ld

# ------------------------------------------------------------ static helpers


def _analyze(tmp_path, files):
    """Write a throwaway tree and run the static analyzer over it."""
    from tools.lockdep.analysis import run_paths

    for rel, src in files.items():
        p = tmp_path / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(textwrap.dedent(src))
    return run_paths([str(tmp_path)], root=str(tmp_path))


def _rules(vs):
    return sorted(v.rule for v in vs)


# --------------------------------------------------------------- lock-order


ORDER_BAD = """
    from localai_tpu.testing.lockdep import lockdep_lock

    A = lockdep_lock("manager.map")       # rank 20
    B = lockdep_lock("engine.submit")     # rank 40

    def fine():
        with A:
            with B:
                pass

    def inverted():
        with B:
            with A:
                pass
"""


def test_lock_order_inversion_caught(tmp_path):
    vs, _ = _analyze(tmp_path, {"pkg/mod.py": ORDER_BAD})
    assert _rules(vs) == ["lock-order"]
    (v,) = vs
    assert "manager.map" in v.message and "engine.submit" in v.message
    assert "hierarchy" in v.message


def test_lock_order_pragma_allowed(tmp_path):
    src = ORDER_BAD.replace(
        "with A:\n                pass",
        "with A:  # lockdep: allow(lock-order) — test exception\n"
        "                pass")
    vs, _ = _analyze(tmp_path, {"pkg/mod.py": src})
    assert vs == [], [v.render() for v in vs]


def test_pr18_spill_evict_reconstruction_caught(tmp_path):
    """The PR 18 bug class: spill takes pool->digest (the sanctioned
    order), evict takes digest->pool — an ABBA pair the rank check must
    catch from the source alone."""
    vs, _ = _analyze(tmp_path, {"pkg/kv.py": """
        from localai_tpu.testing.lockdep import lockdep_lock

        class Pool:
            def __init__(self):
                self._plock = lockdep_lock("kvhost.pool")      # rank 50
                self._dlock = lockdep_lock("kvhost.digest")    # rank 55

            def spill(self):
                with self._plock:
                    with self._dlock:
                        pass

            def evict(self):
                with self._dlock:
                    with self._plock:
                        pass
    """})
    assert _rules(vs) == ["lock-order"]
    assert "kvhost.pool" in vs[0].message


# ------------------------------------------------------------- lock-blocking


WATCHDOG = """
    from localai_tpu.testing.lockdep import lockdep_lock

    class Manager:
        def __init__(self):
            self._mu = lockdep_lock("manager.map")

        def _reap(self, h):
            h.proc.wait(timeout=10)

        def watchdog(self, h):
            with self._mu:
                {pragma}self._reap(h)
"""


def test_pr4_watchdog_reconstruction_caught(tmp_path):
    """The PR 4 bug class: the watchdog held the map lock across a reap
    whose process wait blocks — invisible to per-function linting, caught
    by the transitive effects summary."""
    vs, _ = _analyze(tmp_path, {"pkg/mgr.py": WATCHDOG.format(pragma="")})
    assert _rules(vs) == ["lock-blocking"]
    assert "manager.map" in vs[0].message and "_reap" in vs[0].message


def test_lock_blocking_pragma_allowed(tmp_path):
    src = WATCHDOG.format(
        pragma="# lockdep: allow(lock-blocking) — test exception\n"
               "                ")
    vs, _ = _analyze(tmp_path, {"pkg/mgr.py": src})
    assert vs == [], [v.render() for v in vs]


def test_direct_blocking_is_lints_not_lockdeps(tmp_path):
    """Blocking in the SAME function as the lock is lint's
    lock-across-blocking; lockdep only owns the transitive case — the
    split keeps one pragma per site, not two."""
    vs, _ = _analyze(tmp_path, {"pkg/d.py": """
        import time
        from localai_tpu.testing.lockdep import lockdep_lock

        MU = lockdep_lock("engine.submit")

        def f(proc):
            with MU:
                proc.wait(timeout=5)
    """})
    assert vs == [], [v.render() for v in vs]


# ----------------------------------------------------------------- lock-self


SELF_DEADLOCK = """
    from localai_tpu.testing.lockdep import lockdep_lock

    class C:
        def __init__(self):
            self._mu = lockdep_lock("engine.submit")

        def outer(self):
            with self._mu:
                {pragma}self.inner()

        def inner(self):
            with self._mu:
                pass
"""


def test_lock_self_caught(tmp_path):
    vs, _ = _analyze(tmp_path,
                     {"pkg/s.py": SELF_DEADLOCK.format(pragma="")})
    assert _rules(vs) == ["lock-self"]
    assert "engine.submit" in vs[0].message


def test_lock_self_pragma_allowed(tmp_path):
    src = SELF_DEADLOCK.format(
        pragma="# lockdep: allow(lock-self) — test exception\n"
               "                ")
    vs, _ = _analyze(tmp_path, {"pkg/s.py": src})
    assert vs == [], [v.render() for v in vs]


# --------------------------------------------------------------- lock-cycle


CYCLE = """
    import threading

    A = threading.Lock()
    B = threading.Lock()

    def f():
        with A:
            with B:
                pass

    def g():
        with B:
            {pragma}with A:
                pass
"""


def test_lock_cycle_unranked_caught(tmp_path):
    """Unranked locks get no rank check; the cycle detector still refuses
    an A->B->A acquired-while-held loop."""
    vs, _ = _analyze(tmp_path, {"pkg/c.py": CYCLE.format(pragma="")})
    assert _rules(vs) == ["lock-cycle"]
    assert "->" in vs[0].message


def test_lock_cycle_pragma_allowed(tmp_path):
    src = CYCLE.format(
        pragma="# lockdep: allow(lock-cycle) — test exception\n"
               "            ")
    vs, _ = _analyze(tmp_path, {"pkg/c.py": src})
    assert vs == [], [v.render() for v in vs]


# ------------------------------------------------------------- unranked-lock


def test_unranked_lock_caught_in_package_only(tmp_path):
    files = {
        "localai_tpu/u.py": """
            import threading
            RAW = threading.Lock()
        """,
        "tools/u.py": """
            import threading
            RAW = threading.Lock()    # host tooling: no rank required
        """,
    }
    vs, _ = _analyze(tmp_path, files)
    assert _rules(vs) == ["unranked-lock"]
    assert vs[0].path == "localai_tpu/u.py"


def test_unranked_lock_unknown_name_and_pragma(tmp_path):
    vs, _ = _analyze(tmp_path, {"localai_tpu/u.py": """
        from localai_tpu.testing.lockdep import lockdep_lock

        N = lockdep_lock("no.such.rank")
        # lockdep: allow(unranked-lock) — test exception
        M = lockdep_lock("also.unranked")
    """})
    assert _rules(vs) == ["unranked-lock"]
    assert "no.such.rank" in vs[0].message


# ------------------------------------------------- pragma hygiene (static)


def test_bad_pragma_and_stale_pragma(tmp_path):
    vs, _ = _analyze(tmp_path, {"pkg/p.py": """
        import threading

        A = threading.Lock()

        def f():
            with A:   # lockdep: allow(not-a-check)
                pass

        def g():
            with A:   # lockdep: allow(lock-order) — nothing to excuse
                pass
    """})
    assert _rules(vs) == ["bad-pragma", "stale-pragma"]
    bad, stale = sorted(vs, key=lambda v: v.rule)
    assert "not-a-check" in bad.message
    assert "allow(lock-order)" in stale.message


def test_used_pragma_is_not_stale(tmp_path):
    src = ORDER_BAD.replace(
        "with A:\n                pass",
        "with A:  # lockdep: allow(lock-order) — used\n"
        "                pass")
    vs, _ = _analyze(tmp_path, {"pkg/mod.py": src})
    assert "stale-pragma" not in _rules(vs)


# -------------------------------------------------------- unknown edges


def test_unresolvable_call_recorded_not_dropped(tmp_path):
    """Calls the resolver cannot pin down while a lock is held must land in
    the unknown-edge ledger (the MCP close-under-lock bug surfaced there),
    never vanish silently."""
    vs, an = _analyze(tmp_path, {"pkg/u.py": """
        from localai_tpu.testing.lockdep import lockdep_lock

        MU = lockdep_lock("http.mcp")

        def f(sessions):
            with MU:
                for s in sessions:
                    s.close()
    """})
    assert vs == []
    assert any(a == "http.mcp" and "close" in b
               for (a, b) in an.unknown_edges)


def test_tree_is_lockdep_clean():
    """The acceptance gate, as a test: the shipped tree passes the
    whole-program analyzer with reasoned pragmas only."""
    from tools.lockdep.analysis import run_paths

    vs, _ = run_paths(["localai_tpu", "tools"])
    assert vs == [], "\n".join(v.render() for v in vs)


# ====================================================== runtime tripwire


def _named_lock(name):
    """Indirection so the static analyzer does not trace these deliberately
    inverted test locks — the runtime tripwire is the thing under test."""
    from localai_tpu.testing.lockdep import lockdep_lock

    return lockdep_lock(name, lock=threading.Lock())


@pytest.fixture
def lockdep_raise():
    ld.set_lockdep_mode("raise")
    ld.reset_lockdep()
    yield ld
    ld.reset_lockdep()
    ld.set_lockdep_mode(None)
    ld.set_hold_threshold_ms(None)


def test_runtime_disabled_returns_raw_lock():
    ld.set_lockdep_mode("")
    try:
        raw = ld.lockdep_lock("engine.submit")
        assert type(raw) is type(threading.Lock())
    finally:
        ld.set_lockdep_mode(None)


def test_runtime_two_thread_inversion_raises_with_both_stacks(lockdep_raise):
    a = _named_lock("t.alpha")
    b = _named_lock("t.beta")

    def establish_alpha_beta():
        with a:
            with b:
                pass

    t = threading.Thread(target=establish_alpha_beta)
    t.start()
    t.join()
    assert ("t.alpha", "t.beta") in ld.order_graph()
    with b:
        with pytest.raises(ld.LockdepViolation) as ei:
            a.acquire()
    assert ei.value.kind == "inversion"
    # the report carries BOTH stacks: this acquire and the thread that
    # first proved the opposite order
    assert "--- this acquisition ---" in ei.value.report
    assert "first observation" in ei.value.report
    assert "establish_alpha_beta" in ei.value.report
    assert not a.locked()      # the refused acquire took nothing


def test_runtime_transitive_inversion(lockdep_raise):
    a, b, c = (_named_lock(f"t.chain{i}") for i in range(3))
    with a:
        with b:
            pass
    with b:
        with c:
            pass
    # a -> b -> c observed; c -> a inverts through the transitive path
    with c:
        with pytest.raises(ld.LockdepViolation):
            a.acquire()


def test_runtime_self_deadlock_raises_even_in_record():
    ld.set_lockdep_mode("record")
    ld.reset_lockdep()
    try:
        a = _named_lock("t.selfdead")
        with a:
            with pytest.raises(ld.LockdepViolation) as ei:
                a.acquire()
        assert ei.value.kind == "self-deadlock"
    finally:
        ld.reset_lockdep()
        ld.set_lockdep_mode(None)


def test_runtime_record_mode_accumulates():
    ld.set_lockdep_mode("record")
    ld.reset_lockdep()
    try:
        a = _named_lock("t.rec.a")
        b = _named_lock("t.rec.b")
        with a:
            with b:
                pass
        with b:
            with a:       # inversion: recorded, not raised
                pass
        vs = ld.violations()
        assert len(vs) == 1 and vs[0]["kind"] == "inversion"
        assert "t.rec.a" in vs[0]["title"]
    finally:
        ld.reset_lockdep()
        ld.set_lockdep_mode(None)


def test_runtime_same_class_instances_never_nest(lockdep_raise):
    k1 = _named_lock("t.perkey")
    k2 = _named_lock("t.perkey")
    with k1:
        with pytest.raises(ld.LockdepViolation) as ei:
            k2.acquire()
    assert "same class" in ei.value.report


def test_runtime_hold_trip_releases_lock_first(lockdep_raise):
    ld.set_hold_threshold_ms(5)
    a = _named_lock("t.hold")
    # lint: allow(acquire-release-finally) — the bare release IS the thing
    # under test: the trip must fire from it without leaking the lock
    a.acquire()
    time.sleep(0.03)
    with pytest.raises(ld.LockdepViolation) as ei:
        a.release()
    assert ei.value.kind == "hold"
    assert "acquired at" in ei.value.report
    # the trip must never leave the real lock held
    assert not a.locked()
    with a:
        pass


def test_perturb_schedule_restores_switch_interval():
    before = __import__("sys").getswitchinterval()
    with ld.perturb_schedule(seed=7) as rng:
        assert __import__("sys").getswitchinterval() != before
        first = rng.random()
    assert __import__("sys").getswitchinterval() == before
    with ld.perturb_schedule(seed=7) as rng:
        assert rng.random() == first      # seeded: same decision stream


# ================================================= schedule-perturbed trios


def _run_trio(fns, timeout=60.0):
    """Run the trio's callables on threads; returns exceptions raised in
    them. A thread still alive after `timeout` means a deadlock — fail
    loudly rather than hang the lane."""
    errs = []

    def wrap(fn):
        def run():
            try:
                fn()
            except Exception as e:    # harness boundary: surface, don't die
                errs.append(e)
        return run

    ts = [threading.Thread(target=wrap(fn), daemon=True) for fn in fns]
    for t in ts:
        t.start()
    for t in ts:
        t.join(timeout)
    assert not any(t.is_alive() for t in ts), "race trio deadlocked"
    return errs


def _blk(seed: int = 0):
    from localai_tpu.engine.kvhost import HostKVBlock

    r = np.random.default_rng(seed)
    return HostKVBlock(
        kq=r.integers(-128, 127, (1, 1, 4, 2)).astype(np.int8),
        ks=r.random((1, 1, 1, 4)).astype(np.float32),
        vq=r.integers(-128, 127, (1, 1, 4, 2)).astype(np.int8),
        vs=r.random((1, 1, 1, 4)).astype(np.float32),
    )


def _h(i: int) -> bytes:
    return i.to_bytes(16, "big")


@pytest.mark.races
@pytest.mark.slow
@pytest.mark.parametrize("seed", range(24))
def test_races_kvhost_spill_evict_readmit(seed, lockdep_raise):
    """PR 18's hairy trio: concurrent spill (put), evict-pressure
    (pin/unpin under a tight budget), and re-admission reads — every lock
    acquisition order-checked and schedule-jittered per seed."""
    from localai_tpu.engine.kvhost import HostKVPool

    pool = HostKVPool(budget_bytes=6 * _blk().nbytes)
    blocks = {i: _blk(i) for i in range(32)}
    with ld.perturb_schedule(seed):
        def spill():
            for i in range(32):
                pool.put(_h(i), blocks[i], group=_h(i % 4))

        def readmit():
            for i in range(32):
                pool.get(_h(i))
                pool.contains(_h(i))

        def evict():
            for i in range(32):
                if pool.pin(_h(i)):
                    pool.unpin(_h(i))
                pool.stats()

        errs = _run_trio([spill, readmit, evict])
    assert errs == [], errs
    assert ld.violations() == []


@pytest.mark.races
@pytest.mark.slow
@pytest.mark.parametrize("seed", range(10))
def test_races_manager_watchdog_supervised_load(seed, monkeypatch,
                                               lockdep_raise):
    """PR 4's hairy trio: the busy-watchdog reaping while supervised
    requests mark handles busy and loads respawn the same models — with
    fake instant backends so only the locking is exercised."""
    from localai_tpu.config import AppConfig, ModelConfig
    from localai_tpu.core.manager import BackendHandle, ModelManager

    class _FakeProc:
        def __init__(self):
            self.rc = None
            self.stdout = None
            self.pid = 0

        def poll(self):
            return self.rc

        def wait(self, timeout=None):
            self.rc = 0
            return 0

        def terminate(self):
            self.rc = 0

        def kill(self):
            self.rc = 0

        def send_signal(self, sig):
            self.rc = 0

    class _FakeClient:
        def health(self, timeout=None):
            return True

        def close(self):
            pass

    def fake_spawn_once(self, cfg):
        return BackendHandle(name=cfg.name, config=cfg, proc=_FakeProc(),
                             client=_FakeClient(), port=0)

    monkeypatch.setattr(ModelManager, "_spawn_once", fake_spawn_once)
    monkeypatch.setattr(ModelManager, "_load_rpc", lambda self, h: None)
    app = AppConfig(watchdog_busy_timeout=0.02, retry_budget=0)
    mgr = ModelManager(app)
    cfg_a = ModelConfig(name="ra")
    cfg_b = ModelConfig(name="rb")
    with ld.perturb_schedule(seed):
        mgr.start_watchdog(interval=0.01)

        def loads():
            for _ in range(12):
                mgr.load(cfg_a)
                mgr.load(cfg_b)
                mgr.stop_model("rb")

        def supervised():
            for _ in range(12):
                mgr.supervised(cfg_a, lambda h: h.name)

        def busy_churn():
            # park handles busy long enough for the watchdog to reap them
            for _ in range(12):
                h = mgr.get("ra")
                if h is not None:
                    # lint: allow(acquire-release-finally) — unguarded on
                    # purpose: the watchdog may reap the handle mid-hold,
                    # exactly the interleaving the trio exists to exercise
                    h.mark_busy()
                    time.sleep(0.03)
                    h.mark_idle()

        errs = _run_trio([loads, supervised, busy_churn])
    mgr.stop_all()
    assert errs == [], errs
    assert ld.violations() == []


TINY = dict(vocab_size=128, hidden_size=64, intermediate_size=128,
            num_layers=2, num_heads=4, num_kv_heads=2, head_dim=16,
            max_position=512, dtype="float32")


@pytest.mark.races
@pytest.mark.slow
def test_races_engine_preempt_cancel_decode():
    """ISSUE 19's hairy trio: a decode loop stepping, a submitter feeding
    it, a canceller evicting mid-flight — then a preempt spill-drain at a
    seed-dependent boundary. One engine, many seeds (construction is the
    expensive part; the races are per-seed)."""
    import jax

    from localai_tpu.engine.engine import Engine, EngineConfig, GenRequest
    from localai_tpu.models.llama import LlamaConfig, init_params
    from localai_tpu.ops.sampling import SamplingParams

    ld.set_lockdep_mode("raise")
    ld.reset_lockdep()
    try:
        cfg = LlamaConfig(**TINY)
        params = init_params(cfg, jax.random.PRNGKey(0))
        eng = Engine(cfg, params, None, EngineConfig(
            max_slots=2, max_context=512, prefill_buckets=(64,),
            prefill_chunk=64, kv_pages=6, prompt_cache=True,
            decode_loop=8, decode_block=4, cache_type="int8",
            kv_host_bytes=1 << 20))
        prompt = [3, 5, 7, 11, 13]
        for seed in range(4):
            with ld.perturb_schedule(seed):
                stop = threading.Event()
                rids = []

                def decode():
                    while not stop.is_set():
                        if not eng.step():
                            time.sleep(0.001)

                def submit():
                    for i in range(4):
                        rid, _out = eng.submit(GenRequest(
                            prompt_ids=list(prompt), max_tokens=32,
                            params=SamplingParams(temperature=0.0),
                            ignore_eos=True))
                        rids.append(rid)
                        time.sleep(0.002)

                def cancel():
                    for _ in range(8):
                        if rids:
                            eng.cancel(rids[len(rids) // 2])
                        time.sleep(0.003)

                t_dec = threading.Thread(target=decode, daemon=True)
                t_dec.start()
                errs = _run_trio([submit, cancel])
                time.sleep(0.02)
                stop.set()
                t_dec.join(60.0)
                assert not t_dec.is_alive(), "decode thread wedged"
                assert errs == [], errs
                eng.preempt()          # spill-drain at this seed's boundary
        # the engine must stay serviceable after every preempt
        rid, out = eng.submit(GenRequest(
            prompt_ids=list(prompt), max_tokens=4,
            params=SamplingParams(temperature=0.0), ignore_eos=True))
        for _ in range(200):
            eng.step()
            if not out.empty() and out.queue[-1].finished:
                break
        assert ld.violations() == []
    finally:
        ld.reset_lockdep()
        ld.set_lockdep_mode(None)
