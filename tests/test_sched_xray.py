"""Scheduler X-ray (ISSUE 13): per-tick pack ledger, fallback reason codes,
and cost-analysis rooflines.

Cheap taxonomy / ledger / roofline / benchdiff units run in tier-1; the
engine-driving scenario streams (grammar overflow, pending admission, KV
demotion, budget cap) are slow-marked. The load-bearing contract tested
here: every reason code an engine site emits is REGISTERED (unregistered is
a hard ValueError), and the dispatch-category counters sum exactly to the
dense (non-ragged) dispatch count — the same quantity bench.py reports as
dense_fallback_dispatches.
"""
import json
import time

import numpy as np
import pytest

from localai_tpu.telemetry import sched as S

pytestmark = pytest.mark.tripwire

TINY = dict(vocab_size=96, hidden_size=32, intermediate_size=64,
            num_layers=2, num_heads=2, num_kv_heads=2, head_dim=16,
            max_position=8192, dtype="float32")


@pytest.fixture(scope="module")
def tiny_parts():
    import jax

    from localai_tpu.models.llama import LlamaConfig, init_params

    cfg = LlamaConfig(**TINY)
    return cfg, init_params(cfg, jax.random.PRNGKey(0))


def _engine(tiny_parts, **kw):
    from localai_tpu.engine.engine import Engine, EngineConfig

    cfg, params = tiny_parts
    return Engine(cfg, params, None, EngineConfig(**kw))


def _req(n=8, max_tokens=8, seed=3, **kw):
    from localai_tpu.engine.engine import GenRequest
    from localai_tpu.ops.sampling import SamplingParams

    rng = np.random.default_rng(seed)
    return GenRequest(rng.integers(1, 90, n).tolist(),
                      SamplingParams(temperature=0.0),
                      max_tokens=max_tokens, ignore_eos=True, **kw)


def _drain(eng, steps=3000):
    for _ in range(steps):
        if not eng.step():
            break


# ------------------------------------------------------------ the taxonomy


def test_unregistered_reason_code_hard_fails():
    led = S.TickLedger()
    with pytest.raises(ValueError, match="unregistered"):
        led.reason("made_up_code")
    # the failure leaves no counter behind
    assert "made_up_code" not in led.counters


def test_registry_shape_is_contractual():
    cats = {"dispatch", "demotion", "admission", "kv", "pack"}
    for code, (cat, desc) in S.REASON_CODES.items():
        assert cat in cats, code
        assert desc and code == code.lower()
    assert set(S.DISPATCH_CODES) == {
        c for c, (cat, _) in S.REASON_CODES.items() if cat == "dispatch"}
    assert "loop_native" in S.DISPATCH_CODES
    assert S.reason_category("budget_cap") == "pack"


def test_sched_gate_and_per_engine_ledgers():
    try:
        S.set_sched_enabled(False)
        assert S.maybe_ledger() is None
        S.set_sched_enabled(True)
        a, b = S.maybe_ledger(), S.maybe_ledger()
        assert a is not None and b is not None and a is not b
    finally:
        S.set_sched_enabled(None)


# ------------------------------------------------------------------ ledger


def test_ledger_flat_snapshot_roundtrip():
    led = S.TickLedger()
    led.begin(1)
    led.reason("pending_admission")
    led.reason("budget_cap", kind="decode_rows")
    led.pack("ragged", decode_rows=3, prefill_tokens=16, pad_rows=5,
             rows_used=24, budget_rows=64, packed=19)
    rec = led.commit(active_slots=3)
    assert rec["tick"] == 1 and rec["active_slots"] == 3
    assert rec["packs"][0]["variant"] == "ragged"
    assert json.loads(json.dumps(rec))  # tick records are JSON-clean

    flat = led.flat()
    assert flat["sched_ticks_total"] == 1.0
    assert flat["sched_reason__pending_admission"] == 1.0
    assert flat["sched_variant__ragged"] == 1.0
    assert flat["sched_pack__prefill_tokens"] == 16.0
    assert flat["sched_budget_utilization"] == pytest.approx(19 / 64)
    assert flat["sched_pad_rows_frac"] == pytest.approx(5 / 24)

    snap = led.snapshot()
    assert snap["reason_counters"]["budget_cap"] == 1
    assert snap["recent_ticks"][-1]["tick"] == 1

    led.rooflines["ragged"] = S.roofline_entry(1e6, 1e6, 1e9, 1e9)
    led.reset()
    # reset drops the stream but keeps the (expensive) cached rooflines
    assert led.n_ticks == 0 and not led.counters
    assert "ragged" in led.rooflines
    assert "sched_roofline__ragged__flops" in led.flat()


def test_tick_rings_wrap():
    led = S.TickLedger(ring=8)
    for i in range(20):
        led.begin(i)
        led.commit()
    assert led.n_ticks == 20 and len(led.ticks) == 8
    assert [r["tick"] for r in led.ticks] == list(range(12, 20))

    from localai_tpu.telemetry.metrics import FlightRecorder

    rec = FlightRecorder(ticks=4)
    for i in range(10):
        rec.record_tick({"tick": i})
    assert [r["tick"] for r in rec.ticks] == [6, 7, 8, 9]


def test_flightrec_events_stamp_current_tick():
    from localai_tpu.telemetry.metrics import FlightRecorder

    rec = FlightRecorder()
    try:
        S.set_current_tick(41)
        rec.record_event("tripwire", detail="x")
        S.set_current_tick(None)
        rec.record_event("breaker_open")
        rec.record_event("explicit", tick=7)
    finally:
        S.set_current_tick(None)
    evs = list(rec.events)
    assert evs[0]["tick"] == 41
    assert "tick" not in evs[1]
    assert evs[2]["tick"] == 7


# --------------------------------------------------------------- rooflines


def test_roofline_entry_attribution():
    # 1 GFLOP against 1 KB on a (1 TF/s, 1 GB/s) device: compute-bound
    e = S.roofline_entry(1e9, 1e3, 1e12, 1e9)
    assert e["bound"] == "compute" and e["mfu"] == pytest.approx(1.0)
    # 1 KFLOP against 1 GB: bandwidth-bound, ceiling well under 1
    e = S.roofline_entry(1e3, 1e9, 1e12, 1e9)
    assert e["bound"] == "bandwidth" and e["mfu"] < 1e-6
    assert e["t_roofline_us"] == pytest.approx(e["t_memory_us"])
    assert S.peak_bandwidth("TPU v6e") > S.peak_bandwidth("TPU v5e")


def test_profiler_cost_backed_mfu_only():
    from localai_tpu.telemetry.profiler import StepProfiler

    p = StepProfiler(fence=False, n_params=1000, peak=1e9, peak_bw=1e9)
    p.record("decode", time.perf_counter() - 0.01, tokens=10)
    r0 = p.report()["stages"]["decode"]
    # cost-backed only (ISSUE 16): None until set_costs, and the analytic
    # legacy key no longer exists anywhere in the report or flat surface
    assert r0["mfu"] is None and "mfu_analytic_legacy" not in r0
    p.set_costs({"decode": {"flops": 1e6, "bytes": 2e6}})
    st = p.report()["stages"]["decode"]
    assert st["mfu"] is not None and st["cost_flops"] == 1e6
    flat = p.flat()
    assert "prof_decode_mfu" in flat
    assert not any("mfu_analytic_legacy" in k for k in flat)


# --------------------------------------------------------------- benchdiff


def _bench_json(tmp_path, name, **fields):
    base = {"metric": "serve tok/s (llama-tiny f32, ragged ...)",
            "value": 100.0, "unit": "tok/s"}
    base.update(fields)
    p = tmp_path / name
    p.write_text(json.dumps(base))
    return str(p)


def test_benchdiff_gates_ratios_not_throughput(tmp_path):
    from tools import benchdiff

    old = _bench_json(tmp_path, "old.json", ragged_over_dense=1.2,
                      compile_count_delta=0)
    # halved raw tok/s is box noise — NOT a regression on its own
    ok = _bench_json(tmp_path, "ok.json", value=55.0,
                     ragged_over_dense=1.18, compile_count_delta=0)
    assert benchdiff.main([old, ok]) == 0
    # a collapsed ratio metric IS a regression
    bad = _bench_json(tmp_path, "bad.json", value=100.0,
                      ragged_over_dense=0.6, compile_count_delta=0)
    assert benchdiff.main([old, bad]) == 1
    # counter invariants regress on ANY growth (new mid-stream compiles)
    grew = _bench_json(tmp_path, "grew.json", ragged_over_dense=1.2,
                       compile_count_delta=2)
    assert benchdiff.main([old, grew]) == 1
    # raw-throughput collapse past the floor fails even with ratios intact
    dead = _bench_json(tmp_path, "dead.json", value=10.0,
                       ragged_over_dense=1.2, compile_count_delta=0)
    assert benchdiff.main([old, dead]) == 1
    assert benchdiff.main([str(tmp_path / "missing.json"), ok]) == 2


def test_benchdiff_picks_latest_two_from_runs_dir(tmp_path):
    import os

    from tools import benchdiff

    for i, stamp in enumerate(["2026-01-01", "2026-01-02", "2026-01-03"]):
        p = _bench_json(tmp_path, f"bench_{i}.json", recorded_at=stamp)
        os.utime(p, (1000 + i, 1000 + i))
    prev, latest = benchdiff.latest_two(str(tmp_path))
    assert prev.endswith("bench_1.json") and latest.endswith("bench_2.json")
    assert benchdiff.main(["--runs-dir", str(tmp_path)]) == 0


# ------------------------------------------------- engine scenario streams


@pytest.mark.slow
def test_dispatch_codes_sum_to_dense_dispatches(tiny_parts):
    """The exactness invariant behind dense_fallback_dispatches: over a
    stream with queued admissions, EVERY dense decode dispatch emits
    exactly one dispatch-category code — the counters sum to
    decode_dispatches - ragged_dispatches, and the pending_admission
    scenario (more requests than slots) appears by name."""
    eng = _engine(tiny_parts, max_slots=2, max_context=128,
                  prefill_buckets=(16,), prompt_cache=False,
                  decode_loop=4)
    assert eng._sched is not None
    # staggered budgets + a 4-step loop window: the short request frees its
    # slot at a loop boundary while its neighbour still decodes, so the
    # next dispatch sees free-slot + queued request simultaneously
    # (_dispatch runs before _prefill_tick each tick) and must fall back
    # dense with the pending_admission code
    for i in range(5):   # 5 requests through 2 slots → queued admissions
        eng.submit(_req(seed=i, max_tokens=4 if i % 2 == 0 else 20))
    _drain(eng)
    sched = eng._sched
    dense = eng.metrics["decode_dispatches"] - \
        eng.metrics.get("ragged_dispatches", 0)
    code_sum = sum(sched.counters.get(c, 0) for c in S.DISPATCH_CODES)
    assert dense > 0 and code_sum == dense, dict(sched.counters)
    assert sched.counters.get("pending_admission", 0) > 0
    # ledger <-> metrics cross-checks on the same stream
    assert sched.n_ticks > 0
    assert sched.n_dispatches == sum(sched.variants.values())
    assert sum(v for k, v in eng.metrics.items()
               if k.startswith("tokens_by_path__")) == \
        eng.metrics["tokens_generated"]
    flat = sched.flat()
    assert flat["sched_ticks_total"] == float(sched.n_ticks)
    # tick records reached the flight recorder ring with full pack detail
    if eng._flightrec is not None:
        recs = [r for r in eng._flightrec.ticks if "packs" in r]
        assert recs and any(r["packs"] for r in recs)


@pytest.mark.slow
def test_budget_cap_reason_under_tiny_ragged_budget(tiny_parts):
    """A 16-row token budget holds ONE decode q-block (cap = T - QBLK):
    three concurrent decodes must trip the decode_rows budget cap, and the
    ragged pack must report meaningful budget utilization."""
    eng = _engine(tiny_parts, max_slots=3, max_context=128,
                  prefill_buckets=(16,), prefill_chunk=16, kv_pages=16,
                  prompt_cache=False, ragged_token_budget=16)
    for i in range(3):
        eng.submit(_req(seed=10 + i, max_tokens=6))
    _drain(eng)
    sched = eng._sched
    assert sched.counters.get("budget_cap", 0) > 0, dict(sched.counters)
    assert eng.metrics["ragged_dispatches"] > 0
    assert 0.0 < sched.budget_utilization() <= 1.0
    assert eng.metrics["budget_utilization"] > 0.0
    # the committed tick records carry the machine-readable kind field
    kinds = {r.get("kind") for rec in sched.ticks
             for r in rec["reasons"] if isinstance(r, dict)}
    assert "decode_rows" in kinds


@pytest.mark.slow
def test_kv_policy_demotion_reason_matches_metric(tiny_parts):
    """A full-attention request too big for the compact windowed pool is
    demoted at admission: the engine metric and the reason-code counter
    move in lockstep."""
    eng = _engine(tiny_parts, max_slots=1, max_context=4096,
                  prefill_buckets=(16,), kv_pages=24,
                  kv_policy="sink_window(sinks=256, window=512)")
    eng.submit(_req(n=39, max_tokens=3900, kv_policy="full"))
    for _ in range(30):
        eng.step()
    assert eng.metrics["kv_policy_demotions"] >= 1
    assert eng._sched.counters.get("kv_policy_demotion", 0) == \
        eng.metrics["kv_policy_demotions"]


@pytest.mark.slow
def test_grammar_overflow_reason_and_hostonly_dispatches(tmp_path_factory):
    """A 1-state table cap overflows on any real grammar: the admission
    emits grammar_table_overflow, and every dense dispatch while that slot
    lives carries the grammar_hostonly dispatch code."""
    from fixtures import tiny_checkpoint
    from localai_tpu.engine import (
        Engine, EngineConfig, GenRequest, Tokenizer, load_config,
        load_params,
    )
    from localai_tpu.functions.grammars import json_schema_grammar
    from localai_tpu.ops.sampling import SamplingParams

    ckpt = tiny_checkpoint(tmp_path_factory)
    cfg = load_config(ckpt, dtype="float32")
    params = load_params(ckpt, cfg)
    tok = Tokenizer.from_dir(ckpt)
    eng = Engine(cfg, params, tok, EngineConfig(
        max_slots=2, max_context=128, prefill_buckets=(16,),
        prompt_cache=False, grammar_table_states=1))
    schema = {"type": "object",
              "properties": {"a": {"type": "integer"}}, "required": ["a"]}
    eng.submit(GenRequest(tok.encode("emit json:"),
                          SamplingParams(temperature=0.0), max_tokens=12,
                          grammar=json_schema_grammar(schema)))
    _drain(eng)
    sched = eng._sched
    assert sched.counters.get("grammar_table_overflow", 0) >= 1
    assert sched.counters.get("grammar_hostonly", 0) > 0
    assert eng.metrics.get("grammar_table_overflows", 0) >= 1


@pytest.mark.slow
def test_rooflines_cost_variants_without_new_compiles(tiny_parts):
    """engine.rooflines() AOT-costs every dispatched variant (real XLA
    cost_analysis FLOPs/bytes) and must not add jit-cache compiles — the
    compile-count tripwire quantity stays frozen."""
    from localai_tpu.testing.tripwires import decode_compile_count

    eng = _engine(tiny_parts, max_slots=2, max_context=128,
                  prefill_buckets=(16,), prompt_cache=False)
    for i in range(2):
        eng.submit(_req(seed=20 + i))
    _drain(eng)
    before = decode_compile_count(eng)
    roofs = eng.rooflines(force=True)
    assert roofs, "no variant was costed"
    for name, e in roofs.items():
        assert e["cost_flops"] > 0 and e["cost_bytes"] > 0, name
        assert e["bound"] in ("compute", "bandwidth")
        assert 0.0 < e["mfu"] <= 1.0
    assert decode_compile_count(eng) == before
    # costed variant names match the dispatched-variant ledger names
    assert set(roofs) <= set(eng._sched.variants) | set(roofs)
    snap = eng.sched_snapshot()
    assert snap["rooflines"] and snap["recent_ticks"]
    assert set(snap["rooflines"]) == set(roofs)
