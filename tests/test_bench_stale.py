"""bench.py scoreboard-truthfulness tests (ROADMAP open item #1): a host
with no reachable TPU but an archived on-chip artifact must emit THAT
artifact (device: TPU, stale: true), never a CPU number; plus the --trace
surface the CI asserts on.
"""
import json
import os
import sys

import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import bench  # noqa: E402


def _write(dirpath, name, data):
    os.makedirs(dirpath, exist_ok=True)
    path = os.path.join(dirpath, name)
    with open(path, "w") as fh:
        json.dump(data, fh)
    return path


def test_latest_tpu_artifact_prefers_newest_tpu(tmp_path):
    d = str(tmp_path / "runs")
    assert bench.latest_tpu_artifact(d) is None   # missing dir is fine
    _write(d, "a_cpu.json", {"device": "cpu", "value": 99.0,
                             "recorded_at": "2026-08-04T12:00:00"})
    _write(d, "b_old.json", {"device": "TPU v5e", "value": 600.0,
                             "recorded_at": "2026-07-01T00:00:00"})
    newest = _write(d, "c_new.json", {"device": "TPU v5e", "value": 726.7,
                                      "recorded_at": "2026-07-30T00:00:00"})
    _write(d, "junk.json", {"not": "a result"})
    with open(os.path.join(d, "broken.json"), "w") as fh:
        fh.write("{nope")
    art, path = bench.latest_tpu_artifact(d)
    assert path == newest
    assert art["value"] == 726.7
    assert bench._is_tpu_device(art["device"])


def test_save_artifact_skips_cpu_and_roundtrips(tmp_path):
    d = str(tmp_path / "runs")
    assert bench.save_artifact({"device": "cpu", "value": 1.0}, d) is None
    p = bench.save_artifact({"device": "TPU v5 lite", "value": 700.0}, d)
    assert p and os.path.exists(p)
    art, path = bench.latest_tpu_artifact(d)
    assert path == p and art["recorded_at"]


def test_main_emits_stale_tpu_artifact_on_probe_failure(tmp_path, capsys,
                                                        monkeypatch):
    """The acceptance path: probe finds no TPU → the scoreboard line is the
    archived on-chip artifact with stale: true, never device: cpu."""
    d = str(tmp_path / "runs")
    _write(d, "chip.json", {
        "metric": "decode tok/s/chip (llama-8b int8, serve path)",
        "value": 726.7, "unit": "tok/s", "device": "TPU v5e",
        "mfu": 0.059, "recorded_at": "2026-07-30T10:00:00",
    })
    monkeypatch.setattr(bench, "probe_accelerator",
                        lambda args: (True, "init timed out after 60s", "cpu"))
    rc = bench.main(["--runs-dir", d])
    assert rc == 0
    out = capsys.readouterr().out.strip().splitlines()[-1]
    result = json.loads(out)
    assert result["stale"] is True
    assert result["device"] == "TPU v5e"
    assert result["value"] == 726.7
    assert result["recorded_at"] == "2026-07-30T10:00:00"
    assert result["stale_source"] == "chip.json"
    assert "probe_error" in result
    assert "cpu" not in str(result["device"]).lower()


def test_main_cpu_fallback_flag_still_runs_cpu(tmp_path, capsys, monkeypatch):
    """--allow-cpu-fallback opts back into the CPU smoke even with an
    archived artifact present (CI harness validation)."""
    d = str(tmp_path / "runs")
    _write(d, "chip.json", {"device": "TPU v5e", "value": 726.7,
                            "recorded_at": "2026-07-30T10:00:00"})
    monkeypatch.setattr(bench, "probe_accelerator",
                        lambda args: (True, "no tpu", "cpu"))
    monkeypatch.setattr(bench, "bench_serve",
                        lambda args, size, on_cpu: (123.0, 5.0, 1024,
                                                    "float32", {}))
    rc = bench.main(["--runs-dir", d, "--allow-cpu-fallback"])
    assert rc == 0
    result = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert result["device"] == "cpu" and result.get("stale") is None
    # and the CPU smoke never overwrites the archive
    art, _ = bench.latest_tpu_artifact(d)
    assert art["value"] == 726.7


def test_explicit_cpu_run_skips_stale_path(tmp_path, capsys, monkeypatch):
    """--cpu is an explicit request for the local smoke — no stale swap."""
    d = str(tmp_path / "runs")
    _write(d, "chip.json", {"device": "TPU v5e", "value": 726.7})
    monkeypatch.setattr(bench, "bench_serve",
                        lambda args, size, on_cpu: (50.0, 9.0, 1024,
                                                    "float32", {}))
    rc = bench.main(["--cpu", "--runs-dir", d])
    assert rc == 0
    result = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert result["device"] == "cpu" and "stale" not in result


def test_bench_help_exposes_trace_flag():
    """The CI scoreboard-path assertion: bench.py --help names --trace and
    the tier modes (longctx, soup)."""
    help_text = bench.build_parser().format_help()
    for flag in ("--trace", "--trace-out", "--runs-dir",
                 "--allow-cpu-fallback", "longctx", "soup"):
        assert flag in help_text
