"""Launcher (GUI-launcher role): start/stop/health/log-tail around a real
server process."""
import os
import subprocess
import sys


def test_launcher_lifecycle(tmp_path, monkeypatch):
    from localai_tpu.launcher import Launcher

    import socket

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    models = tmp_path / "models"
    models.mkdir()
    monkeypatch.setenv("LOCALAI_JAX_PLATFORM", "cpu")
    l = Launcher(address=f"127.0.0.1:{port}", models_path=str(models))
    assert not l.running
    assert l.start()
    try:
        assert l.wait_healthy(attempts=100)
        assert l.running and l.healthy()
        assert l.webui_url.endswith(f":{port}/")
        assert any("serving" in line for line in l.tail(50))
    finally:
        l.stop()
    assert not l.running
    assert not l.healthy()


def test_launcher_repl_commands(tmp_path):
    """Drive the interactive REPL over stdin (health + webui + quit without
    starting a server)."""
    env = dict(os.environ)
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    out = subprocess.run(
        [sys.executable, "-m", "localai_tpu.cli", "launcher",
         "--address", "127.0.0.1:1", "--models-path", str(tmp_path)],
        input="h\nw\nbogus\nq\n", capture_output=True, text=True,
        timeout=60, env=env)
    assert out.returncode == 0
    assert "not running" in out.stdout
    assert "http://127.0.0.1:1/" in out.stdout
    assert "unknown command" in out.stdout
