"""Whisper JAX vs HF torch parity on a locally-built tiny random checkpoint,
plus mel-spectrogram parity with WhisperFeatureExtractor."""
import json
import os

import numpy as np
import pytest


@pytest.fixture(scope="module")
def whisper_ckpt(tmp_path_factory):
    import torch
    from transformers import WhisperConfig, WhisperForConditionalGeneration

    d = str(tmp_path_factory.mktemp("whisper"))
    torch.manual_seed(0)
    cfg = WhisperConfig(
        vocab_size=51865, d_model=64, encoder_layers=2, decoder_layers=2,
        encoder_attention_heads=4, decoder_attention_heads=4,
        encoder_ffn_dim=128, decoder_ffn_dim=128, num_mel_bins=80,
        max_source_positions=1500, max_target_positions=64,
    )
    m = WhisperForConditionalGeneration(cfg)
    m.eval()
    m.generation_config.forced_decoder_ids = None
    m.generation_config.suppress_tokens = None
    m.generation_config.begin_suppress_tokens = None
    m.save_pretrained(d, safe_serialization=True)
    return d


@pytest.fixture(scope="module")
def audio():
    rng = np.random.default_rng(0)
    t = np.arange(16000 * 2) / 16000.0
    sig = 0.3 * np.sin(2 * np.pi * 440 * t) + 0.05 * rng.normal(size=t.shape)
    return sig.astype(np.float32)


def test_mel_matches_hf_feature_extractor(audio):
    from transformers import WhisperFeatureExtractor

    from localai_tpu.audio.mel import log_mel_spectrogram

    fe = WhisperFeatureExtractor()
    ref = fe(audio, sampling_rate=16000, return_tensors="np").input_features[0]
    ours = log_mel_spectrogram(audio)
    assert ours.shape == ref.shape
    np.testing.assert_allclose(ours, ref, rtol=2e-4, atol=2e-4)


def test_encoder_parity(whisper_ckpt, audio):
    import torch
    from transformers import WhisperForConditionalGeneration

    from localai_tpu.audio.mel import log_mel_spectrogram
    from localai_tpu.models import whisper as W

    cfg = W.load_config(whisper_ckpt)
    params = W.load_params(whisper_ckpt, cfg)
    mel = log_mel_spectrogram(audio)[None]

    hf = WhisperForConditionalGeneration.from_pretrained(whisper_ckpt)
    hf.eval()
    with torch.no_grad():
        ref = hf.model.encoder(torch.tensor(mel)).last_hidden_state.numpy()
    import jax.numpy as jnp

    ours = np.asarray(W.encode(params, cfg, jnp.asarray(mel)))
    np.testing.assert_allclose(ours, ref, rtol=2e-4, atol=2e-4)


def test_greedy_transcription_parity(whisper_ckpt, audio):
    import torch
    from transformers import WhisperForConditionalGeneration

    from localai_tpu.audio.mel import log_mel_spectrogram
    from localai_tpu.models.whisper import WhisperModel

    wm = WhisperModel(whisper_ckpt)
    # pin to pure greedy: the default strategy is now beam-5 + fallback
    ours = wm.transcribe_tokens(audio, max_tokens=12, beam_size=1,
                                temperatures=(0.0,),
                                logprob_threshold=-1e9)

    hf = WhisperForConditionalGeneration.from_pretrained(whisper_ckpt)
    hf.eval()
    mel = log_mel_spectrogram(audio)[None]
    with torch.no_grad():
        ref = hf.generate(torch.tensor(mel), max_new_tokens=12,
                          do_sample=False)[0].tolist()
    # strip decoder_start + trailing eos from the HF output
    start = wm.cfg.decoder_start_token_id
    ref = [t for t in ref if t != start and t != wm.cfg.eos_token_id]
    assert ours[: len(ref)] == ref[: len(ours)]
    assert len(ours) > 0


def test_vad_segments():
    from localai_tpu.audio.vad import detect_segments

    rng = np.random.default_rng(1)
    rate = 16000
    silence = 0.001 * rng.normal(size=rate)          # 1 s noise floor
    tone = 0.5 * np.sin(2 * np.pi * 300 * np.arange(rate) / rate)
    audio = np.concatenate([silence, tone, silence, tone, silence]).astype(np.float32)
    segs = detect_segments(audio)
    assert len(segs) == 2
    assert abs(segs[0][0] - 1.0) < 0.2 and abs(segs[0][1] - 2.0) < 0.25
    assert abs(segs[1][0] - 3.0) < 0.2 and abs(segs[1][1] - 4.0) < 0.25
    assert detect_segments(silence.astype(np.float32)) == []


def test_wav_roundtrip(tmp_path):
    from localai_tpu.audio.pcm import read_wav, write_wav

    audio = (0.5 * np.sin(2 * np.pi * 440 * np.arange(8000) / 16000)
             ).astype(np.float32)
    p = str(tmp_path / "t.wav")
    write_wav(p, audio, 16000)
    back, rate = read_wav(p)
    assert rate == 16000
    np.testing.assert_allclose(back, audio, atol=1e-3)
    # resample path
    back8, rate8 = read_wav(p, target_rate=8000)
    assert rate8 == 8000 and abs(len(back8) - 4000) <= 4


def test_beam_matches_hf_num_beams(whisper_ckpt, audio):
    """Beam search (the whisper.cpp/faster-whisper decode strategy) against
    HF generate(num_beams=...) on the same tiny checkpoint."""
    import torch
    from transformers import WhisperForConditionalGeneration, WhisperProcessor

    from localai_tpu.models.whisper import WhisperModel

    m = WhisperModel(whisper_ckpt)
    ck_model = WhisperForConditionalGeneration.from_pretrained(whisper_ckpt)
    ck_model.eval()

    from localai_tpu.audio.mel import log_mel_spectrogram
    feats = torch.tensor(log_mel_spectrogram(audio)[None])

    with torch.no_grad():
        ref = ck_model.generate(
            feats, num_beams=3, max_new_tokens=16, do_sample=False,
            early_stopping=False, length_penalty=1.0)
    ours = m.transcribe_tokens(audio, max_tokens=16, beam_size=3,
                               temperatures=(0.0,),
                               logprob_threshold=-1e9)
    ref_ids = [t for t in ref[0].tolist()
               if t not in (m.cfg.decoder_start_token_id,
                            m.cfg.eos_token_id)]
    # allow HF's leading forced tokens bookkeeping to differ; the decoded
    # content must match
    assert ours == ref_ids, (ours, ref_ids)


def test_beam_size_one_equals_greedy(whisper_ckpt, audio):
    from localai_tpu.models.whisper import WhisperModel

    m = WhisperModel(whisper_ckpt)
    greedy = m.transcribe_tokens(audio, max_tokens=12, beam_size=1,
                                 temperatures=(0.0,),
                                 logprob_threshold=-1e9)
    beam1 = m.transcribe_tokens(audio, max_tokens=12, beam_size=2,
                                temperatures=(0.0,), logprob_threshold=-1e9)
    assert isinstance(greedy, list) and isinstance(beam1, list)
    assert len(greedy) > 0 and len(beam1) > 0


def test_temperature_fallback_runs(whisper_ckpt, audio):
    """An impossible logprob threshold forces the fallback ladder through
    sampling temperatures; the final attempt's result is returned."""
    from localai_tpu.models.whisper import WhisperModel

    m = WhisperModel(whisper_ckpt)
    out = m.transcribe_tokens(audio, max_tokens=8, beam_size=2,
                              temperatures=(0.0, 0.7),
                              logprob_threshold=1e9)
    assert isinstance(out, list) and len(out) > 0
