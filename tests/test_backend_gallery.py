"""Backend gallery: registry index, meta-backend capability resolution,
install payload kinds, external run.sh spawn, and the /backends HTTP family."""
import json
import os
import tarfile

import pytest
import yaml


@pytest.fixture()
def index(tmp_path):
    """Local registry index: a meta backend + two concrete candidates
    (dir payload + tarball payload)."""
    cpu_payload = tmp_path / "payload-cpu"
    cpu_payload.mkdir()
    (cpu_payload / "run.sh").write_text("#!/bin/sh\necho cpu backend\n")
    tpu_payload = tmp_path / "payload-tpu"
    tpu_payload.mkdir()
    (tpu_payload / "run.sh").write_text("#!/bin/sh\necho tpu backend\n")
    tarball = tmp_path / "tool.tar.gz"
    with tarfile.open(tarball, "w:gz") as tf:
        tf.add(str(cpu_payload / "run.sh"), arcname="run.sh")
    idx = tmp_path / "index.yaml"
    idx.write_text(yaml.safe_dump([
        {"name": "fastllm", "alias": "fast",
         "description": "meta backend",
         "capabilities": {"default": "cpu-fastllm",
                          "tpu-v5e": "tpu-fastllm"}},
        {"name": "cpu-fastllm", "uri": f"file://{cpu_payload}"},
        {"name": "tpu-fastllm", "uri": f"file://{tpu_payload}"},
        {"name": "tool", "uri": str(tarball)},
    ]))
    return str(idx)


def test_index_parse_and_meta(index):
    from localai_tpu.services.backend_gallery import BackendGallery

    g = BackendGallery([index])
    assert set(g.backends()) == {"fastllm", "cpu-fastllm", "tpu-fastllm",
                                 "tool"}
    assert g.get("fastllm").is_meta
    assert not g.get("tool").is_meta


def test_meta_resolution_by_capability(index):
    from localai_tpu.services.backend_gallery import (
        BackendGallery, resolve_meta,
    )

    g = BackendGallery([index])
    meta = g.get("fastllm")
    assert resolve_meta(g, meta, "tpu-v5e").name == "tpu-fastllm"
    assert resolve_meta(g, meta, "weird-hw").name == "cpu-fastllm"


def test_install_meta_creates_alias_dir(index, tmp_path):
    from localai_tpu.services.backend_gallery import (
        BackendGallery, install_backend, list_system_backends,
    )

    bp = str(tmp_path / "backends")
    g = BackendGallery([index])
    dest = install_backend(g, "fastllm", bp, capability="tpu-v5e")
    assert dest.endswith("tpu-fastllm")
    assert os.path.isfile(os.path.join(dest, "run.sh"))
    meta = json.load(open(os.path.join(bp, "fastllm", "metadata.json")))
    assert meta["meta_backend_for"] == "tpu-fastllm"
    names = {b["name"]: b for b in list_system_backends(bp)}
    assert "tpu-fastllm" in names and "fastllm" in names
    assert names["llm"]["system"] is True   # in-tree roles listed too


def test_install_tarball_and_idempotence(index, tmp_path):
    from localai_tpu.services.backend_gallery import (
        BackendGallery, install_backend,
    )

    bp = str(tmp_path / "backends")
    g = BackendGallery([index])
    dest = install_backend(g, "tool", bp)
    assert os.path.isfile(os.path.join(dest, "run.sh"))
    marker = os.path.join(dest, "marker")
    open(marker, "w").write("1")
    install_backend(g, "tool", bp)          # idempotent: no reinstall
    assert os.path.exists(marker)


def test_install_oci_payload(tmp_path):
    """Backend shipped as an OCI image (the reference's actual distribution
    channel, backends.go + index.yaml uri: oci://...)."""
    from test_oci import _FakeRegistry, _tar_layer

    from localai_tpu.services.backend_gallery import (
        BackendGallery, install_backend,
    )

    reg = _FakeRegistry()
    srv = reg.serve()
    host = f"127.0.0.1:{srv.server_address[1]}"
    try:
        layer = _tar_layer({"run.sh": b"#!/bin/sh\necho oci\n"})
        reg.add_image("org/b", "v1", [
            (layer, "application/vnd.oci.image.layer.v1.tar+gzip")])
        idx = tmp_path / "idx.yaml"
        idx.write_text(yaml.safe_dump([
            {"name": "ocib", "uri": f"oci://{host}/org/b:v1"}]))
        bp = str(tmp_path / "backends")
        dest = install_backend(BackendGallery([str(idx)]), "ocib", bp)
        assert open(os.path.join(dest, "run.sh")).read().startswith("#!/bin")
    finally:
        srv.shutdown()


def test_delete_backend(index, tmp_path):
    from localai_tpu.services.backend_gallery import (
        BackendGallery, delete_backend, install_backend,
        list_system_backends,
    )

    bp = str(tmp_path / "backends")
    g = BackendGallery([index])
    install_backend(g, "fastllm", bp, capability="tpu-v5e")
    delete_backend(bp, "fastllm")
    names = {b["name"] for b in list_system_backends(bp)
             if not b.get("system")}
    assert names == set()


def test_resolve_backend_dir_alias_and_meta(index, tmp_path):
    from localai_tpu.services.backend_gallery import (
        BackendGallery, install_backend, resolve_backend_dir,
    )

    bp = str(tmp_path / "backends")
    g = BackendGallery([index])
    install_backend(g, "cpu-fastllm", bp)
    # alias defined on the concrete entry's metadata
    meta_path = os.path.join(bp, "cpu-fastllm", "metadata.json")
    meta = json.load(open(meta_path))
    meta["alias"] = "fast"
    json.dump(meta, open(meta_path, "w"))
    assert resolve_backend_dir(bp, "cpu-fastllm").endswith("cpu-fastllm")
    assert resolve_backend_dir(bp, "fast").endswith("cpu-fastllm")
    assert resolve_backend_dir(bp, "llm") is None  # in-tree role


def test_manager_spawns_external_backend(tmp_path):
    """A gallery-installed backend whose run.sh execs a real gRPC server must
    pass the manager's health/load cycle (initializers.go:50-99 contract)."""
    import sys

    from localai_tpu.config import AppConfig, ModelConfig
    from localai_tpu.core.manager import ModelManager

    bp = tmp_path / "backends"
    bdir = bp / "echo-store"
    bdir.mkdir(parents=True)
    (bdir / "metadata.json").write_text(json.dumps({"name": "echo-store"}))
    (bdir / "run.sh").write_text(
        f"#!/bin/sh\nexec {sys.executable} -m localai_tpu.backend "
        "--backend store \"$@\"\n")
    store_dir = tmp_path / "store-data"
    store_dir.mkdir()
    os.environ["LOCALAI_JAX_PLATFORM"] = "cpu"
    app = AppConfig(models_path=str(tmp_path), backends_path=str(bp))
    mgr = ModelManager(app)
    cfg = ModelConfig.from_dict({
        "name": "ext", "backend": "echo-store",
        "parameters": {"model": str(store_dir)}})
    try:
        h = mgr.load(cfg)
        assert h.client.health()
    finally:
        mgr.stop_all()


def test_backends_http_family(index, tmp_path):
    """GET /backends, /backends/available, POST /backends/apply + job poll,
    POST /backends/delete through the real aiohttp app."""
    import asyncio
    import socket
    import threading
    import time

    import requests
    from aiohttp import web

    from localai_tpu.config import AppConfig, ModelConfigLoader
    from localai_tpu.core.manager import ModelManager
    from localai_tpu.server.http import API
    from localai_tpu.services.backend_gallery import (
        BackendGallery, BackendGalleryService,
    )

    bp = str(tmp_path / "backends")
    models = tmp_path / "models"
    models.mkdir()
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    app_cfg = AppConfig(address=f"127.0.0.1:{port}",
                        models_path=str(models), backends_path=bp)
    api = API(app_cfg, ModelConfigLoader(str(models)), ModelManager(app_cfg))
    svc = BackendGalleryService(BackendGallery([index]), bp)
    svc.start()
    api.backend_gallery_service = svc
    loop = asyncio.new_event_loop()

    def run():
        asyncio.set_event_loop(loop)
        runner = web.AppRunner(api.app)
        loop.run_until_complete(runner.setup())
        site = web.TCPSite(runner, "127.0.0.1", port)
        loop.run_until_complete(site.start())
        loop.run_forever()

    threading.Thread(target=run, daemon=True).start()
    base = f"http://127.0.0.1:{port}"
    for _ in range(50):
        try:
            requests.get(base + "/healthz", timeout=1)
            break
        except requests.ConnectionError:
            time.sleep(0.1)
    try:
        avail = requests.get(base + "/backends/available", timeout=10).json()
        assert {b["name"] for b in avail} >= {"fastllm", "tool"}
        sysb = requests.get(base + "/backends", timeout=10).json()
        assert any(b["name"] == "llm" and b["system"] for b in sysb)
        gals = requests.get(base + "/backends/galleries", timeout=10).json()
        assert gals == [{"url": index}]

        os.environ["LOCALAI_FORCE_CAPABILITY"] = "tpu-v5e"
        try:
            job = requests.post(base + "/backends/apply",
                                json={"name": "fastllm"}, timeout=10).json()
            for _ in range(100):
                st = requests.get(base + f"/backends/jobs/{job['uuid']}",
                                  timeout=10).json()
                if st["state"] in ("done", "error"):
                    break
                time.sleep(0.1)
            assert st["state"] == "done", st
        finally:
            os.environ.pop("LOCALAI_FORCE_CAPABILITY", None)
        installed = requests.get(base + "/backends", timeout=10).json()
        assert any(b["name"] == "tpu-fastllm" for b in installed)

        r = requests.post(base + "/backends/delete/fastllm", timeout=10)
        assert r.status_code == 200
        installed = requests.get(base + "/backends", timeout=10).json()
        assert not any(b["name"] == "tpu-fastllm" for b in installed)
    finally:
        svc.stop()
        loop.call_soon_threadsafe(loop.stop)
