"""Paged KV cache (ops/paged.py + engine kv_pages): parity with the dense
cache, block-table kernel indirection, reservation admission, and prefix
reuse through retained blocks.

Reference role: llama.cpp's unified KV cells across slots
(/root/reference/backend/cpp/llama-cpp/grpc-server.cpp:311-318); design per
SURVEY hard-part #1 / PAPERS.md ragged paged attention.
"""
import threading

import numpy as np
import pytest

from fixtures import tiny_checkpoint
from localai_tpu.engine import Engine, EngineConfig, GenRequest, Tokenizer, load_config, load_params
from localai_tpu.ops.sampling import SamplingParams


@pytest.fixture(scope="module")
def loaded(tmp_path_factory):
    ckpt = tiny_checkpoint(tmp_path_factory)
    cfg = load_config(ckpt, dtype="float32")
    params = load_params(ckpt, cfg)
    tok = Tokenizer.from_dir(ckpt)
    return cfg, params, tok


def _collect(eng, reqs):
    """Run requests through the serving loop; returns {i: [token ids]}."""
    eng.start()
    outs = {}

    def run(i, req):
        rid, q = eng.submit(req)
        ids = []
        while True:
            o = q.get(timeout=120)
            if o.token_id >= 0:
                ids.append(o.token_id)
            if o.finished:
                outs[i] = (ids, o.finish_reason)
                return

    ths = [threading.Thread(target=run, args=(i, r))
           for i, r in enumerate(reqs)]
    [t.start() for t in ths]
    [t.join(timeout=240) for t in ths]
    eng.stop()
    return outs


def _reqs(tok, n=3, max_tokens=24):
    prompts = ["the quick brown fox", "hello world", "pack my box with"]
    return [GenRequest(tok.encode(prompts[i % len(prompts)]),
                       SamplingParams(temperature=0.8, seed=100 + i),
                       max_tokens=max_tokens, ignore_eos=True)
            for i in range(n)]


@pytest.mark.parametrize("cache_type", ["", "int8"])
def test_paged_matches_dense(loaded, cache_type):
    """Same seeds, same prompts → identical token streams paged vs dense."""
    cfg, params, tok = loaded
    ec = dict(max_slots=3, max_context=256, prefill_buckets=(32,),
              cache_type=cache_type, decode_block=4)
    dense = Engine(cfg, params, tok, EngineConfig(**ec))
    ref = _collect(dense, _reqs(tok))
    paged = Engine(cfg, params, tok, EngineConfig(kv_pages=8, **ec))
    got = _collect(paged, _reqs(tok))
    assert set(ref) == set(got) == {0, 1, 2}
    for i in ref:
        assert got[i] == ref[i], f"request {i} diverged (cache={cache_type})"


def test_paged_pallas_interpret_matches_dense(loaded, monkeypatch):
    """Force the Pallas kernels (interpreter mode on CPU) through the paged
    table path and compare with the XLA dense reference."""
    cfg, params, tok = loaded
    monkeypatch.setenv("LOCALAI_FORCE_PALLAS", "1")
    ec = dict(max_slots=2, max_context=256, prefill_buckets=(32,),
              decode_block=4)
    paged = Engine(cfg, params, tok, EngineConfig(kv_pages=6, **ec))
    got = _collect(paged, _reqs(tok, n=2, max_tokens=12))
    monkeypatch.delenv("LOCALAI_FORCE_PALLAS")
    dense = Engine(cfg, params, tok, EngineConfig(**ec))
    ref = _collect(dense, _reqs(tok, n=2, max_tokens=12))
    for i in ref:
        assert got[i] == ref[i]


def test_kernel_table_indirection():
    """ragged_decode through a shuffled block table == attention over the
    logically-contiguous cache."""
    import jax
    import jax.numpy as jnp

    from localai_tpu.ops.attention import mha_decode
    from localai_tpu.ops.pallas import ragged_decode

    B, H, KVH, D, BS = 2, 4, 2, 64, 128
    MAXB, NB = 3, 8
    rng = np.random.default_rng(0)
    q = jnp.asarray(rng.normal(size=(B, 1, H, D)), jnp.float32)
    pool_k = jnp.asarray(rng.normal(size=(NB, KVH, BS, D)), jnp.float32)
    pool_v = jnp.asarray(rng.normal(size=(NB, KVH, BS, D)), jnp.float32)
    table = jnp.asarray([[3, 5, 1], [7, 2, 6]], jnp.int32)
    lengths = jnp.asarray([300, 140], jnp.int32)

    out = ragged_decode(q, pool_k, pool_v, lengths, table=table)

    # reference: gather the virtual view and run the dense XLA decode
    def view(pool):
        g = pool[table]                       # [B, MAXB, KVH, BS, D]
        return g.transpose(0, 2, 1, 3, 4).reshape(B, KVH, MAXB * BS, D)

    ref = mha_decode(q, view(pool_k), view(pool_v), lengths)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_reservation_defers_until_blocks_free(loaded):
    """A pool too small for two concurrent requests serves them one after the
    other instead of failing (reservation admission + FIFO deferral)."""
    cfg, params, tok = loaded
    # each request: ~4-token prompt + 120 max_tokens + margin ≈ 2 blocks;
    # pool of 3 (1 trash + 2 usable) fits exactly one at a time
    eng = Engine(cfg, params, tok, EngineConfig(
        max_slots=2, max_context=256, prefill_buckets=(32,), kv_pages=3,
        decode_block=4))
    reqs = [GenRequest(tok.encode("hi there"),
                       SamplingParams(temperature=0.0, seed=i),
                       max_tokens=100, ignore_eos=True) for i in range(2)]
    outs = _collect(eng, reqs)
    assert sorted(outs) == [0, 1]
    for ids, reason in outs.values():
        assert reason == "length" and len(ids) == 100


def test_oversized_request_rejected(loaded):
    cfg, params, tok = loaded
    eng = Engine(cfg, params, tok, EngineConfig(
        max_slots=1, max_context=256, prefill_buckets=(32,), kv_pages=2))
    with pytest.raises(ValueError, match="KV blocks"):
        eng.submit(GenRequest(tok.encode("hello"), SamplingParams(),
                              max_tokens=250))


def test_paged_prefix_reuse(loaded):
    """A released slot's retained blocks serve a shared-prefix follow-up
    (prompt_cache_hits > 0) and still match a cold engine's output."""
    cfg, params, tok = loaded
    long_prefix = "the quick brown fox jumps over the lazy dog " * 4
    p1 = tok.encode(long_prefix + "first")
    p2 = tok.encode(long_prefix + "second question")
    ec = dict(max_slots=2, max_context=256, prefill_buckets=(32,),
              prompt_cache_min=8, decode_block=4)
    eng = Engine(cfg, params, tok, EngineConfig(kv_pages=10, **ec))
    r1 = _collect(eng, [GenRequest(p1, SamplingParams(temperature=0.0),
                                   max_tokens=8, ignore_eos=True)])
    eng2 = Engine(cfg, params, tok, EngineConfig(kv_pages=10, **ec))
    # warm: run p1, then p2 reuses the prefix
    eng2.start()
    rid, q = eng2.submit(GenRequest(p1, SamplingParams(temperature=0.0),
                                    max_tokens=8, ignore_eos=True))
    while not q.get(timeout=120).finished:
        pass
    rid, q = eng2.submit(GenRequest(p2, SamplingParams(temperature=0.0),
                                    max_tokens=8, ignore_eos=True))
    warm_ids = []
    while True:
        o = q.get(timeout=120)
        if o.token_id >= 0:
            warm_ids.append(o.token_id)
        if o.finished:
            break
    eng2.stop()
    assert eng2.metrics["prompt_cache_hits"] >= 1
    # cold reference for p2
    eng3 = Engine(cfg, params, tok, EngineConfig(kv_pages=10, **ec))
    cold = _collect(eng3, [GenRequest(p2, SamplingParams(temperature=0.0),
                                      max_tokens=8, ignore_eos=True)])
    assert warm_ids == cold[0][0]


def test_paged_under_mesh_matches_dense(loaded):
    """Paged KV under a TP mesh (block pool replicated over the block axis,
    KV heads sharded on 'model' via the XLA gather path) must produce the
    same streams as the unmeshed dense engine."""
    import jax

    from localai_tpu.models.llama import param_specs
    from localai_tpu.parallel.mesh import MeshConfig, build_mesh, shard_params

    cfg, params, tok = loaded
    ec = dict(max_slots=3, max_context=256, prefill_buckets=(32,),
              decode_block=4)
    dense = Engine(cfg, params, tok, EngineConfig(**ec))
    ref = _collect(dense, _reqs(tok))

    mesh = build_mesh(MeshConfig(data=1, model=2), jax.devices()[:2])
    sp = shard_params(params, param_specs(cfg), mesh)
    paged = Engine(cfg, sp, tok, EngineConfig(kv_pages=8, mesh=mesh, **ec))
    got = _collect(paged, _reqs(tok))
    assert set(ref) == set(got) == {0, 1, 2}
    for i in ref:
        assert got[i] == ref[i], f"request {i} diverged under mesh"


def test_paged_context_shift_rotation_unit():
    """cache_shift_paged mechanics: a K row at virtual position p in a tail
    block must, after the shift, equal the raw vector re-roped at
    p - discard_blocks*128; sink blocks stay untouched; non-slot pool blocks
    stay untouched."""
    import jax
    import jax.numpy as jnp

    from localai_tpu.models.llama import LlamaConfig, cache_shift_paged
    from localai_tpu.ops.paged import BLOCK, init_paged
    from localai_tpu.ops.rope import apply_rope, rope_table

    cfg = LlamaConfig(vocab_size=64, hidden_size=16, intermediate_size=32,
                      num_layers=2, num_heads=2, num_kv_heads=2, head_dim=8,
                      max_position=512, dtype="float32")
    L, KVH, D, MAXB = 2, 2, 8, 4
    kb_keep, db = 1, 1
    T = MAXB * BLOCK
    cos, sin = rope_table(cfg.rope, T)

    # pool with 6 physical blocks; slot uses physicals [1, 3, 4, 2]
    kpool, _ = init_paged(L, 6, KVH, D, dtype=jnp.float32)
    table = np.asarray([1, 3, 4, 2], np.int32)
    raw = np.asarray(jax.random.normal(jax.random.PRNGKey(0),
                                       (L, KVH, T, D)), np.float32)
    roped = apply_rope(
        jnp.asarray(raw).transpose(0, 2, 1, 3).reshape(L, T, KVH, D),
        cos, sin, jnp.arange(T)[None, :].repeat(L, 0),
    ).transpose(0, 2, 1, 3)                                  # [L, KVH, T, D]
    # lay the roped rows into the pool through the table
    kp = np.zeros((L, 6, KVH, BLOCK, D), np.float32)
    for vb in range(MAXB):
        kp[:, table[vb]] = np.asarray(
            roped[:, :, vb * BLOCK:(vb + 1) * BLOCK]).transpose(0, 1, 2, 3)
    sentinel = np.random.default_rng(0).standard_normal(
        (L, KVH, BLOCK, D)).astype(np.float32)
    kp[:, 5] = sentinel                                       # foreign block

    out = np.asarray(cache_shift_paged(
        cfg, jnp.asarray(kp), jnp.asarray(table),
        keep_blocks=kb_keep, discard_blocks=db))

    # sink block (virtual 0 -> physical 1) untouched
    np.testing.assert_allclose(out[:, 1], kp[:, 1], rtol=1e-6)
    # foreign physical block untouched
    np.testing.assert_allclose(out[:, 5], sentinel, rtol=1e-6)
    # tail blocks re-roped at position - db*BLOCK
    expect = apply_rope(
        jnp.asarray(raw).transpose(0, 2, 1, 3).reshape(L, T, KVH, D),
        cos, sin,
        (jnp.arange(T) - db * BLOCK)[None, :].repeat(L, 0) % T,
    ).transpose(0, 2, 1, 3)
    for vb in range(kb_keep + db, MAXB):
        np.testing.assert_allclose(
            out[:, table[vb]],
            np.asarray(expect[:, :, vb * BLOCK:(vb + 1) * BLOCK]),
            rtol=1e-4, atol=1e-5)


@pytest.mark.parametrize("cache_type", ["", "int8"])
def test_paged_context_shift_generation_crosses_limit(tmp_path_factory, cache_type):
    """A context_shift request on a PAGED engine sails past the context cap
    (block-granular eviction) while a plain request dies at it — the paged
    twin of test_engine.test_context_shift_generation_crosses_limit."""
    ckpt = tiny_checkpoint(tmp_path_factory, max_position=512)
    cfg = load_config(ckpt, dtype="float32")
    params = load_params(ckpt, cfg)
    tok = Tokenizer.from_dir(ckpt)
    ctx = 512                      # 4 blocks: keepb=1, discb=1 → the shift
    #                                permutes the table and rotates 2 tail
    #                                blocks (the REAL path, not the no-tail
    #                                degenerate)
    prompt = tok.encode("the quick brown fox jumps over the lazy dog")
    n = len(prompt)

    def run(shift):
        eng = Engine(cfg, params, tok, EngineConfig(
            max_slots=2, max_context=ctx, prefill_buckets=(64,),
            prefill_chunk=64, kv_pages=12, cache_type=cache_type))
        req = GenRequest(list(prompt), SamplingParams(temperature=0.0),
                         max_tokens=2 * ctx, ignore_eos=True,
                         context_shift=shift)
        _, out = eng.submit(req)
        for _ in range(6000):
            if not eng.step():
                break
        outs = []
        while not out.empty():
            outs.append(out.get())
        return outs

    plain = run(False)
    assert plain[-1].finish_reason == "length"
    assert plain[-1].generated_tokens <= ctx - n

    shifted = run(True)
    assert shifted[-1].finish_reason == "length"
    assert shifted[-1].generated_tokens == 2 * ctx


def test_paged_context_shift_rejected_on_tiny_context(loaded):
    """maxb <= keep+discard blocks cannot evict block-granularly — submit
    rejects instead of corrupting lengths."""
    cfg, params, tok = loaded
    eng = Engine(cfg, params, tok, EngineConfig(
        max_slots=2, max_context=128, prefill_buckets=(64,),
        prefill_chunk=64, kv_pages=6))
    with pytest.raises(ValueError, match="context_shift with paged"):
        eng.submit(GenRequest(tok.encode("hello"),
                              SamplingParams(temperature=0.0),
                              max_tokens=400, ignore_eos=True,
                              context_shift=True))
