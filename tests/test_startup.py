"""Startup helpers: .env loading, preload, config watcher (reference:
cmd/local-ai/main.go:26-42, core/application/startup.go:65-105,
core/config/config_file_watcher.go:29-126)."""
import os
import time

import yaml

from localai_tpu.core.startup import (
    ConfigWatcher, load_env_files, preload_models,
)


def test_load_env_files(tmp_path, monkeypatch):
    envf = tmp_path / ".env"
    envf.write_text("# comment\nexport FOO_X=1\nBAR_Y='two'\nEXISTING=new\n")
    monkeypatch.setenv("EXISTING", "old")
    monkeypatch.delenv("FOO_X", raising=False)
    monkeypatch.delenv("BAR_Y", raising=False)
    applied = load_env_files([str(envf)])
    assert applied == [str(envf)]
    assert os.environ["FOO_X"] == "1"
    assert os.environ["BAR_Y"] == "two"
    assert os.environ["EXISTING"] == "old"  # existing vars win (godotenv)
    monkeypatch.delenv("FOO_X")
    monkeypatch.delenv("BAR_Y")


def test_load_env_files_missing_ok(tmp_path):
    assert load_env_files([str(tmp_path / "nope.env")]) == []


def test_load_env_inline_comments_and_quotes(tmp_path, monkeypatch):
    envf = tmp_path / ".env"
    envf.write_text('PORT_Z=8080 # default\nQUOTED_Z="a # not-comment"\n')
    monkeypatch.delenv("PORT_Z", raising=False)
    monkeypatch.delenv("QUOTED_Z", raising=False)
    load_env_files([str(envf)])
    assert os.environ["PORT_Z"] == "8080"
    assert os.environ["QUOTED_Z"] == "a # not-comment"
    monkeypatch.delenv("PORT_Z")
    monkeypatch.delenv("QUOTED_Z")


class _FakeManager:
    def __init__(self):
        self.loaded = []

    def load(self, cfg):
        self.loaded.append(cfg.name)


def test_preload_models(tmp_path):
    from localai_tpu.config import ModelConfigLoader

    (tmp_path / "m1.yaml").write_text(yaml.safe_dump(
        {"name": "m1", "backend": "llm"}))
    configs = ModelConfigLoader(str(tmp_path))
    mgr = _FakeManager()
    preload_models(["m1", "missing"], configs, mgr)
    assert mgr.loaded == ["m1"]  # missing one warns and continues


def test_config_watcher_hot_reload(tmp_path):
    from localai_tpu.config import ModelConfigLoader

    (tmp_path / "a.yaml").write_text(yaml.safe_dump(
        {"name": "a", "backend": "llm"}))
    configs = ModelConfigLoader(str(tmp_path))
    assert configs.names() == ["a"]
    w = ConfigWatcher(configs, interval=0.1).start()
    try:
        (tmp_path / "b.yaml").write_text(yaml.safe_dump(
            {"name": "b", "backend": "llm"}))
        deadline = time.time() + 5
        while time.time() < deadline and "b" not in configs.names():
            time.sleep(0.05)
        assert sorted(configs.names()) == ["a", "b"]
        os.unlink(tmp_path / "a.yaml")
        deadline = time.time() + 5
        while time.time() < deadline and "a" in configs.names():
            time.sleep(0.05)
        assert configs.names() == ["b"]
    finally:
        w.stop()
