"""Out-of-distribution validation of the learned VAD (silero-vad role).

The shipped nvad model trains on positives from audio/tts.py's additive-sine
formant synthesizer. Real recorded speech cannot exist in this zero-egress
image, so these tests do the next-strongest thing: a SECOND speech
synthesizer, implemented here with a disjoint algorithm — a Rosenberg glottal
pulse train with jitter/shimmer/vibrato driven through cascaded second-order
IIR formant resonators, with aspiration noise and fricative segments — plus
hard negatives (sweeps, DTMF, AM hum) outside the training negative set.
A detector that only memorised its training synth fails these; one that
learned speech structure (harmonic source + moving formants + syllable
rhythm) passes.
"""
import numpy as np
import pytest

RATE = 16000


# ------------------------------------------------------ independent synth

def _glottal_source(f0_track: np.ndarray, rng) -> np.ndarray:
    """Rosenberg-style glottal pulse train from a per-sample F0 contour,
    with per-period jitter (pitch perturbation) and shimmer (amplitude)."""
    n = len(f0_track)
    out = np.zeros(n, np.float32)
    i = 0
    while i < n:
        f0 = f0_track[i] * (1.0 + 0.02 * rng.standard_normal())  # jitter
        period = max(16, int(RATE / max(f0, 40.0)))
        # Rosenberg pulse: rising half-cosine open phase, sharp closure
        opn = int(0.6 * period)
        pulse = np.zeros(period, np.float32)
        pulse[:opn] = 0.5 * (1 - np.cos(np.pi * np.arange(opn) / opn))
        pulse[opn:] = np.maximum(
            0.0, 1.0 - 3.0 * np.arange(period - opn) / max(1, period - opn))
        amp = 1.0 + 0.1 * rng.standard_normal()                  # shimmer
        end = min(n, i + period)
        out[i:end] = (amp * pulse[: end - i])
        i += period
    # differentiate: glottal flow derivative is what reaches the tract
    return np.diff(out, prepend=0.0).astype(np.float32)


def _resonator(x: np.ndarray, freq: float, bw: float) -> np.ndarray:
    """Second-order IIR formant resonator (digital resonator, Klatt-style)."""
    from scipy.signal import lfilter

    r = np.exp(-np.pi * bw / RATE)
    theta = 2 * np.pi * freq / RATE
    b0 = 1 - 2 * r * np.cos(theta) + r * r
    return lfilter([b0], [1.0, -2 * r * np.cos(theta), r * r],
                   x).astype(np.float32)


def _vowel_glide(dur: float, f0: float, fmts_a, fmts_b, rng) -> np.ndarray:
    """Voiced segment gliding between two formant targets (diphthong)."""
    n = int(dur * RATE)
    t = np.arange(n) / RATE
    # F0 contour: declination + 5 Hz vibrato
    f0_track = (f0 * (1.0 - 0.15 * t / max(dur, 1e-3))
                * (1.0 + 0.03 * np.sin(2 * np.pi * 5.0 * t)))
    src = _glottal_source(f0_track.astype(np.float32), rng)
    src += 0.03 * rng.standard_normal(n).astype(np.float32)  # aspiration
    # piecewise-stationary formant glide: filter short hops at interpolated
    # formant targets (IIR per hop keeps this O(n) and audibly gliding)
    hop = int(0.02 * RATE)
    out = np.zeros(n, np.float32)
    for s in range(0, n, hop):
        frac = s / max(1, n - 1)
        seg = src[s: s + hop]
        acc = np.zeros_like(seg)
        for (fa, ba), (fb, bb) in zip(fmts_a, fmts_b):
            f = fa + (fb - fa) * frac
            b = ba + (bb - ba) * frac
            acc += _resonator(seg, f, b)
        out[s: s + hop] = acc
    return out


def _fricative(dur: float, center: float, rng) -> np.ndarray:
    """Unvoiced segment: noise through a single broad resonance."""
    n = int(dur * RATE)
    noise = rng.standard_normal(n).astype(np.float32)
    return _resonator(noise, center, 1200.0) * 0.15


def klatt_like_speech(seed: int = 0, seconds: float = 2.2) -> np.ndarray:
    """Speech-like utterance from the independent synthesizer: syllables of
    fricative onsets + vowel glides at ~4 Hz rhythm, separated by brief
    closures — none of it produced by the training synthesizer's code."""
    rng = np.random.default_rng(seed)
    # (F, BW) targets for a handful of vowels (public formant tables)
    vowels = [
        [(730, 90), (1090, 110), (2440, 170)],   # /a/
        [(270, 60), (2290, 100), (3010, 170)],   # /i/
        [(300, 70), (870, 100), (2240, 170)],    # /u/
        [(530, 80), (1840, 110), (2480, 170)],   # /e/
    ]
    f0 = float(rng.uniform(95, 180))
    parts = [np.zeros(int(0.15 * RATE), np.float32)]
    tgt = rng.choice(len(vowels))
    total = 0.15
    while total < seconds - 0.3:
        if rng.uniform() < 0.5:
            d = float(rng.uniform(0.04, 0.09))
            parts.append(_fricative(d, float(rng.uniform(2500, 6000)), rng))
            total += d
        nxt = rng.choice(len(vowels))
        d = float(rng.uniform(0.1, 0.22))
        parts.append(_vowel_glide(d, f0, vowels[tgt], vowels[nxt], rng))
        tgt = nxt
        total += d
        gap = float(rng.uniform(0.02, 0.07))      # closure
        parts.append(np.zeros(int(gap * RATE), np.float32))
        total += gap
    parts.append(np.zeros(int(0.15 * RATE), np.float32))
    audio = np.concatenate(parts)
    return (0.7 * audio / max(np.abs(audio).max(), 1e-6)).astype(np.float32)


# ----------------------------------------------------------------- tests

@pytest.fixture(scope="module")
def vad_params():
    from localai_tpu.audio.nvad import load_params

    params = load_params()
    assert params is not None, "vad_model.npz missing"
    return params


@pytest.mark.parametrize("seed", [0, 1, 2])
def test_detects_independent_synth_speech(vad_params, seed):
    from localai_tpu.audio.nvad import detect_segments_model

    audio = klatt_like_speech(seed)
    segs = detect_segments_model(audio, params=vad_params)
    assert segs, "no speech detected in speech-like OOD signal"
    voiced = sum(e - s for s, e in segs)
    dur = len(audio) / RATE
    # most of the utterance is speech; the leading silence must be excluded
    # (the trailing one may be swallowed by the 240 ms hangover)
    assert voiced > 0.35 * dur
    assert segs[0][0] > 0.02
    assert segs[-1][1] <= dur + 1e-6


def test_rejects_ood_nonspeech(vad_params):
    """Negatives outside the training negative families: a slow sine sweep,
    a DTMF digit pair, and 50 Hz mains hum with AM flutter."""
    from localai_tpu.audio.nvad import detect_segments_model

    n = int(1.5 * RATE)
    t = np.arange(n) / RATE

    sweep = 0.4 * np.sin(2 * np.pi * (200 + 1400 * t / t[-1]) * t)
    dtmf = 0.25 * (np.sin(2 * np.pi * 770 * t) + np.sin(2 * np.pi * 1336 * t))
    hum = (0.4 * np.sin(2 * np.pi * 50 * t)
           * (1.0 + 0.3 * np.sin(2 * np.pi * 3 * t)))
    for name, sig in [("sweep", sweep), ("dtmf", dtmf), ("hum", hum)]:
        segs = detect_segments_model(sig.astype(np.float32),
                                     params=vad_params)
        voiced = sum(e - s for s, e in segs)
        assert voiced < 0.15 * (n / RATE), f"{name} misdetected: {segs}"


def test_speech_in_noise(vad_params):
    """OOD speech at ~10 dB SNR over pink noise must still be found."""
    from localai_tpu.audio.nvad import detect_segments_model

    audio = klatt_like_speech(3)
    rng = np.random.default_rng(9)
    # pink-ish noise: cumulative-sum-filtered white, normalized
    w = rng.standard_normal(len(audio)).astype(np.float32)
    pink = np.convolve(w, np.ones(8) / 8.0, mode="same")
    pink *= (np.std(audio) / (np.std(pink) * 3.2))   # ~10 dB SNR
    segs = detect_segments_model(audio + pink, params=vad_params)
    assert segs, "speech at 10 dB SNR missed"
    voiced = sum(e - s for s, e in segs)
    assert voiced > 0.25 * len(audio) / RATE
