"""Prompt templating (reference: /root/reference/core/templates/
evaluator.go:58-230 — Go text/template per-model .tmpl files).

TPU-native equivalent uses jinja2 (already the chat-template language of the
HF ecosystem): a template is either an inline string in the model YAML or a
`<name>.tmpl` file next to the model config. Per-message `chat_message`
template renders each message, results are joined and fed to the `chat`
template as `{{ input }}` — the reference's two-stage evaluation
(evaluator.go:96-230).
"""
from __future__ import annotations

import functools
import os

import jinja2

_env = jinja2.Environment(trim_blocks=True, lstrip_blocks=True,
                          keep_trailing_newline=True)


@functools.lru_cache(maxsize=256)
def _compile(src: str) -> jinja2.Template:
    return _env.from_string(src)


def _resolve_source(cfg, name_or_body: str) -> str:
    """Inline body if it looks like a template, else `<stem>.tmpl` file next
    to the model's YAML (evaluator.go template-file lookup)."""
    if "{{" in name_or_body or "\n" in name_or_body:
        return name_or_body
    base = os.path.dirname(cfg.config_file) if cfg.config_file else "."
    path = os.path.join(base, name_or_body + ".tmpl")
    if os.path.exists(path):
        with open(path) as f:
            return f.read()
    return name_or_body  # literal passthrough


def evaluate_chat(cfg, messages: list[dict]) -> str:
    """Render messages with chat_message (if set) then the chat template."""
    rendered = []
    msg_tmpl = cfg.template.chat_message
    for i, m in enumerate(messages):
        content = m.get("content") or ""
        if isinstance(content, list):  # OpenAI multimodal content parts
            content = "".join(p.get("text", "") for p in content
                              if isinstance(p, dict))
        if msg_tmpl:
            rendered.append(_compile(_resolve_source(cfg, msg_tmpl)).render(
                role=m.get("role", "user"), content=content, index=i,
                message=m))
        else:
            rendered.append(f"{m.get('role', 'user')}: {content}")
    joined = "\n".join(rendered)
    chat_tmpl = cfg.template.chat
    if not chat_tmpl:
        return joined
    return _compile(_resolve_source(cfg, chat_tmpl)).render(
        input=joined, messages=messages, model=cfg.name)


def evaluate_completion(cfg, prompt: str) -> str:
    tmpl = cfg.template.completion
    if not tmpl:
        return prompt
    return _compile(_resolve_source(cfg, tmpl)).render(
        input=prompt, model=cfg.name)
