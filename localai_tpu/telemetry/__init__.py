"""Telemetry subsystem: end-to-end request tracing + device-step profiling.

Three pieces (see ISSUE 2 / ROADMAP open item #1 — the 33 ms decode step has
never been decomposed):

- `trace`: a lock-free ring-buffer span tracer with request-id propagation
  HTTP middleware → gRPC metadata → engine, exported as Chrome-trace JSON
  (`/debug/trace`, `local-ai util trace`, `bench.py --trace`).
- `profiler`: opt-in `block_until_ready`-fenced per-stage timing of the
  engine's device dispatches (admit / prefill / decode block / sample /
  shift), accumulated into histograms with tokens/s + MFU estimates
  (`/debug/profile`, GetMetrics `prof_*` keys, Prometheus series).
- exporters live with their surfaces: the HTTP server merges spans across
  processes via the backend GetTrace RPC.

- `metrics` (ISSUE 11): the serving SLO layer — per-request phase-timeline
  histograms (TTFT/TPOT/queue wait/prefill/e2e, labeled by decode path)
  exported via GetMetrics `hist_*` keys, true Prometheus histogram series,
  and `/debug/slo`; plus the crash/tripwire flight recorder
  (`/debug/flightrec`, auto post-mortem dumps).

- `sched` (ISSUE 13): the scheduler X-ray — a per-tick pack ledger with a
  registered reason-code taxonomy for every admission/fallback/demotion
  decision, plus XLA cost-analysis rooflines per compiled decode variant
  (`/debug/sched`, GetMetrics `sched_*` keys, `local-ai util sched`).

Enable with `LOCALAI_TRACE=1` (spans) and `LOCALAI_PROFILE=1` (fenced stage
timing). Both default off; the serving hot path is untouched when disabled.
SLO metrics default ON (`LOCALAI_METRICS=0` disables); the tick ledger
rides the same gate (`LOCALAI_SCHED=0` disables it alone).
"""
from localai_tpu.telemetry.trace import (  # noqa: F401
    Tracer,
    chrome_events,
    chrome_trace,
    current_request_id,
    maybe_tracer,
    new_request_id,
    reset_request_id,
    set_request_id,
    set_trace_enabled,
    span,
    trace_enabled,
    tracer,
)
from localai_tpu.telemetry.profiler import (  # noqa: F401
    StepProfiler,
    engine_profiler,
    peak_flops,
    profile_enabled,
    set_profile_enabled,
)
from localai_tpu.telemetry.metrics import (  # noqa: F401
    BUCKETS_S,
    FlightRecorder,
    Hist,
    SLORegistry,
    flightrec,
    maybe_slo,
    metrics_enabled,
    parse_flat,
    reset_flightrec,
    set_metrics_enabled,
    snapshot_from_hists,
)
from localai_tpu.telemetry.sched import (  # noqa: F401
    DISPATCH_CODES,
    REASON_CODES,
    TickLedger,
    current_tick,
    maybe_ledger,
    peak_bandwidth,
    reason_category,
    roofline_entry,
    sched_enabled,
    set_current_tick,
    set_sched_enabled,
)
