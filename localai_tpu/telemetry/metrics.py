"""Serving SLO layer (ISSUE 11): streaming latency histograms + a crash
flight recorder.

Two pieces, both process-wide singletons the way `trace.py`'s tracer is:

- `SLORegistry`: lock-cheap streaming histograms over the profiler's
  log-spaced buckets (`BUCKETS_S`), keyed (metric, path). The engine feeds
  TTFT / inter-token latency (TPOT) / queue wait / prefill time / e2e per
  request, labeled by the decode path that served it (loop / dense / ragged
  / spec). Observations are plain int increments under the GIL — no lock on
  the hot path; snapshot readers (GetMetrics scrape, /debug/slo) tolerate a
  half-landed observation the same way the span ring does. Percentiles come
  from the bucket upper bounds (coarse but free, same trade as
  profiler._Stage.p50_s). The whole registry flattens onto the GetMetrics
  str→double surface (`hist_<metric>__<path>__{bN,count,sum}`) so the HTTP
  layer can rebuild TRUE Prometheus histogram series (_bucket/_sum/_count)
  and percentile snapshots across the process boundary without a proto
  change.

- `FlightRecorder`: bounded rings of recent request timelines, engine-tick
  summaries, and tripwire/breaker/supervision events. Always recording (a
  deque append per rare event; request records ride the same enable gate as
  the histograms), dumpable via /debug/flightrec and `local-ai util
  flightrec`, and auto-dumped to a post-mortem JSON file when a tripwire
  trips, a breaker opens, a backend is reaped, or the engine loop dies —
  the black-box readout for "what was in flight when it crashed".

Enable gate: `LOCALAI_METRICS` (default ON — unlike trace/profile this layer
is the serving SLO surface; set 0 to disable). Disabled cost in the engine
is one attribute load + branch, mirroring `_obs`.
"""
from __future__ import annotations

import collections
import json
import math
import os
import tempfile
import threading
import time

from localai_tpu.telemetry.profiler import BUCKETS_S
from localai_tpu.testing.lockdep import lockdep_lock

# SLO metric names the engine records (seconds); the fixed set keeps the
# flat()/parse round-trip unambiguous and the exposition surfaces stable
METRICS = ("ttft", "tpot", "queue_wait", "prefill", "e2e")

_FORCED: bool | None = None


def metrics_enabled() -> bool:
    if _FORCED is not None:
        return _FORCED
    return os.environ.get("LOCALAI_METRICS", "1") not in ("", "0")


def set_metrics_enabled(value: bool | None) -> None:
    """Test hook mirroring set_trace_enabled: True/False force, None =
    re-read the environment."""
    global _FORCED, _SLO
    _FORCED = value
    _SLO = None   # next maybe_slo() re-resolves against the new gate


class Hist:
    """One streaming histogram over BUCKETS_S (seconds). `observe` is a few
    int/float increments under the GIL — deliberately lock-free; snapshot
    readers may see a sample's bucket before its sum (harmless skew)."""

    __slots__ = ("counts", "count", "sum")

    def __init__(self):
        self.counts = [0] * len(BUCKETS_S)
        self.count = 0
        self.sum = 0.0

    def observe(self, v: float, n: int = 1):
        """Record `n` samples of value `v` (weighted observe: the fused
        decode loop delivers token bursts whose amortized inter-token gap is
        one value covering many tokens)."""
        for i, ub in enumerate(BUCKETS_S):
            if v <= ub:
                self.counts[i] += n
                break
        self.count += n
        self.sum += v * n

    def percentile(self, q: float) -> float:
        """Value at quantile `q` (0..1) from the bucket upper bounds. The
        open-ended bucket reports its lower bound (the last finite edge) —
        an honest floor rather than an invented ceiling."""
        if self.count <= 0:
            return 0.0
        target = q * self.count
        acc = 0
        for i, n in enumerate(self.counts):
            acc += n
            if acc >= target and n:
                if math.isfinite(BUCKETS_S[i]):
                    return BUCKETS_S[i]
                return BUCKETS_S[i - 1] if i else 0.0
        return BUCKETS_S[-2]

    def merge(self, other: "Hist"):
        for i, n in enumerate(other.counts):
            self.counts[i] += n
        self.count += other.count
        self.sum += other.sum


class SLORegistry:
    """Histograms keyed (metric, path). The creation path takes a lock once
    per new key; established keys observe lock-free."""

    def __init__(self):
        self._hists: dict[tuple[str, str], Hist] = {}
        self._lock = lockdep_lock("telemetry.slo")

    def observe(self, metric: str, path: str, v: float, n: int = 1):
        h = self._hists.get((metric, path))
        if h is None:
            with self._lock:
                h = self._hists.setdefault((metric, path), Hist())
        h.observe(v, n)

    def reset(self):
        """Drop all samples (after warmup/prewarm, whose synthetic requests
        would pollute the serving percentiles)."""
        with self._lock:
            self._hists.clear()

    def merged(self, metric: str) -> Hist:
        """All paths of one metric folded together (the headline numbers)."""
        out = Hist()
        for (m, _), h in list(self._hists.items()):
            if m == metric:
                out.merge(h)
        return out

    def flat(self) -> dict[str, float]:
        """Flatten onto the GetMetrics str→double surface. Key scheme
        `hist_<metric>__<path>__{b<i>,count,sum}` (double underscores so
        `parse_flat` splits unambiguously); zero buckets are skipped to keep
        the map small. Plus derived headline keys the satellite requires:
        ttft_ms_p50 / ttft_ms_p95 from the merged TTFT histogram."""
        out: dict[str, float] = {}
        for (metric, path), h in list(self._hists.items()):
            base = f"hist_{metric}__{path}__"
            for i, n in enumerate(h.counts):
                if n:
                    out[base + f"b{i}"] = float(n)
            out[base + "count"] = float(h.count)
            out[base + "sum"] = h.sum
        ttft = self.merged("ttft")
        if ttft.count:
            out["ttft_ms_p50"] = ttft.percentile(0.50) * 1e3
            out["ttft_ms_p95"] = ttft.percentile(0.95) * 1e3
        return out

    def snapshot(self) -> dict:
        """Structured percentile snapshot for /debug/slo: per (metric, path)
        and per-metric merged p50/p95/p99 + count + mean, in ms."""
        return snapshot_from_hists(dict(self._hists))


# ------------------------------------------------------- flat round-trip

def parse_flat(metrics: dict[str, float]) -> dict[tuple[str, str], Hist]:
    """Rebuild (metric, path) → Hist from a GetMetrics map containing
    `hist_*` keys (the scrape side of the process boundary)."""
    hists: dict[tuple[str, str], Hist] = {}
    for key, v in metrics.items():
        if not key.startswith("hist_"):
            continue
        parts = key[5:].split("__")
        if len(parts) != 3:
            continue
        metric, path, kind = parts
        h = hists.setdefault((metric, path), Hist())
        if kind == "count":
            h.count = int(v)
        elif kind == "sum":
            h.sum = float(v)
        elif kind.startswith("b"):
            try:
                i = int(kind[1:])
            except ValueError:
                continue
            if 0 <= i < len(BUCKETS_S):
                h.counts[i] = int(v)
    return hists


def snapshot_from_hists(hists: dict[tuple[str, str], Hist]) -> dict:
    """Percentile snapshot (ms) from a (metric, path) → Hist map — shared by
    the in-process registry and the scrape-side /debug/slo handler."""
    out: dict = {}
    for metric in METRICS:
        merged = Hist()
        paths = {}
        for (m, path), h in hists.items():
            if m != metric or not h.count:
                continue
            merged.merge(h)
            paths[path] = _quantiles_ms(h)
        if not merged.count:
            continue
        entry = _quantiles_ms(merged)
        if paths:
            entry["by_path"] = paths
        out[metric] = entry
    return out


def _quantiles_ms(h: Hist) -> dict:
    return {
        "count": h.count,
        "mean_ms": (h.sum / h.count) * 1e3 if h.count else 0.0,
        "p50_ms": h.percentile(0.50) * 1e3,
        "p95_ms": h.percentile(0.95) * 1e3,
        "p99_ms": h.percentile(0.99) * 1e3,
    }


# ------------------------------------------------------- process singleton

_SLO: SLORegistry | None = None
_SLO_LOCK = lockdep_lock("telemetry.slo_init")


def maybe_slo() -> SLORegistry | None:
    """The process-wide SLO registry, or None when disabled — the engine
    stores the result once so its hot path pays one attribute load."""
    global _SLO
    if not metrics_enabled():
        return None
    if _SLO is None:
        with _SLO_LOCK:
            if _SLO is None:
                _SLO = SLORegistry()
    return _SLO


# ----------------------------------------------------------- flight recorder

class FlightRecorder:
    """Bounded rings of recent serving history + auto post-mortem dumps.

    Three rings (deque appends are GIL-atomic; the lock guards only dump
    composition): `requests` — finished request timelines; `ticks` —
    coarse engine-tick summaries; `events` — tripwire / breaker /
    supervision / fatal events. `auto_dump` writes the whole state to
    LOCALAI_FLIGHTREC_DIR (default: the system temp dir), capped so a
    crash loop can't fill the disk."""

    MAX_AUTO_DUMPS = 8

    def __init__(self, requests: int = 256, ticks: int = 256,
                 events: int = 512):
        self.requests: collections.deque = collections.deque(maxlen=requests)
        self.ticks: collections.deque = collections.deque(maxlen=ticks)
        self.events: collections.deque = collections.deque(maxlen=events)
        self._lock = lockdep_lock("telemetry.flightrec")
        self._dumps = 0
        self.last_dump_path = ""

    def record_request(self, timeline: dict):
        self.requests.append(timeline)

    def record_tick(self, summary: dict):
        self.ticks.append(summary)

    def record_event(self, kind: str, **fields):
        e = {"kind": kind, "t_wall": time.time(), **fields}
        if "tick" not in e:
            # stamp the live engine's tick id (ISSUE 13) so breaker/reap/
            # tripwire events correlate with the scheduler tick stream; the
            # import is deferred — sched imports this module
            from localai_tpu.telemetry.sched import current_tick

            tick = current_tick()
            if tick is not None:
                e["tick"] = tick
        self.events.append(e)

    def dump(self) -> dict:
        with self._lock:
            return {
                "pid": os.getpid(),
                "t_wall": time.time(),
                "requests": list(self.requests),
                "ticks": list(self.ticks),
                "events": list(self.events),
                "auto_dumps": self._dumps,
                "last_dump_path": self.last_dump_path,
            }

    def auto_dump(self, reason: str) -> str:
        """Write a post-mortem JSON file; returns its path ("" when the cap
        is hit or the write fails — a dying process must not die harder
        because its black box couldn't be written)."""
        with self._lock:
            if self._dumps >= self.MAX_AUTO_DUMPS:
                return ""
            self._dumps += 1
            n = self._dumps
        d = os.environ.get("LOCALAI_FLIGHTREC_DIR") or tempfile.gettempdir()
        path = os.path.join(
            d, f"localai_flightrec_{os.getpid()}_{n}_{reason}.json")
        payload = self.dump()
        payload["reason"] = reason
        try:
            os.makedirs(d, exist_ok=True)
            with open(path, "w") as fh:
                json.dump(payload, fh, default=str)
        except OSError:
            return ""
        self.last_dump_path = path
        return path


_FLIGHTREC: FlightRecorder | None = None
_FLIGHTREC_LOCK = lockdep_lock("telemetry.flightrec_init")


def flightrec() -> FlightRecorder:
    """The process-wide flight recorder (always available — event recording
    is a deque append on rare paths; request/tick recording is gated by the
    callers on the same enable flag as the histograms)."""
    global _FLIGHTREC
    if _FLIGHTREC is None:
        with _FLIGHTREC_LOCK:
            if _FLIGHTREC is None:
                _FLIGHTREC = FlightRecorder()
    return _FLIGHTREC


def reset_flightrec() -> None:
    """Test hook: fresh recorder (ring contents and the auto-dump cap are
    process-global otherwise)."""
    global _FLIGHTREC
    _FLIGHTREC = None
