"""Device-step profiler: per-stage time histograms, tokens/s, MFU estimate.

The engine's dispatches are asynchronous — a plain wall-clock around a jitted
call times only the Python enqueue. With `LOCALAI_PROFILE` set the engine
passes each dispatch's output through `record(..., fence=...)`, which calls
`jax.block_until_ready` before reading the clock: the measured interval is
the real host+device cost of that stage (and the pipeline is deliberately
serialized — profiling is a measurement mode, not a serving mode).

Stage samples accumulate into log-spaced histograms so one snapshot answers
"where do the milliseconds of a decode step go" (the Kernel Looping /
PRESERVE-style per-stage attribution the 33 ms step needs): count, total,
min/max, p50 (from the histogram), tokens/s, and one MFU number per stage:
`mfu`, backed by XLA's per-program cost analysis when the engine has fed
per-stage FLOP counts via set_costs() (ISSUE 13); None until then. The old
2·N·tokens analytic approximation (`mfu_analytic_legacy`) was kept one
release for scoreboard continuity and removed in ISSUE 16 — it overstated
stages that don't run the full forward and knew nothing about bandwidth.

Everything here is jax-free until a fence is actually requested, so the
module can load in processes that never touch the accelerator.
"""
from __future__ import annotations

import math
import os
import threading
import time

from localai_tpu.testing.lockdep import lockdep_lock

# histogram bucket upper bounds, in seconds (log-spaced 50 µs … 5 s + inf)
BUCKETS_S: tuple[float, ...] = (
    50e-6, 100e-6, 200e-6, 500e-6, 1e-3, 2e-3, 5e-3, 10e-3, 20e-3, 50e-3,
    100e-3, 200e-3, 500e-3, 1.0, 2.0, 5.0, math.inf,
)

_FORCED: bool | None = None


def profile_enabled() -> bool:
    if _FORCED is not None:
        return _FORCED
    return os.environ.get("LOCALAI_PROFILE", "") not in ("", "0")


def set_profile_enabled(value: bool | None) -> None:
    global _FORCED
    _FORCED = value


def peak_flops(device_kind: str) -> float:
    """bf16 peak for the accelerator kind (v5e 197 TF/s, v6e 918; CPU gets a
    nominal 100 GF/s so MFU stays meaningful in smoke runs)."""
    kind = (device_kind or "").lower()
    if "v6" in kind:
        return 918e12
    if "v5p" in kind:
        return 459e12
    if "v5" in kind:
        return 197e12
    if "v4" in kind:
        return 275e12
    if "cpu" in kind:
        return 100e9
    return 197e12


class _Stage:
    __slots__ = ("count", "total_s", "min_s", "max_s", "tokens", "hist")

    def __init__(self):
        self.count = 0
        self.total_s = 0.0
        self.min_s = math.inf
        self.max_s = 0.0
        self.tokens = 0
        self.hist = [0] * len(BUCKETS_S)

    def add(self, dt: float, tokens: int):
        self.count += 1
        self.total_s += dt
        self.min_s = min(self.min_s, dt)
        self.max_s = max(self.max_s, dt)
        self.tokens += tokens
        for i, ub in enumerate(BUCKETS_S):
            if dt <= ub:
                self.hist[i] += 1
                break

    def p50_s(self) -> float:
        """Median from the histogram (bucket upper bound — coarse but free)."""
        if not self.count:
            return 0.0
        half, acc = self.count / 2, 0
        for i, n in enumerate(self.hist):
            acc += n
            if acc >= half:
                return BUCKETS_S[i] if math.isfinite(BUCKETS_S[i]) \
                    else self.max_s
        return self.max_s


class StepProfiler:
    """Accumulates fenced stage timings; shared between the engine loop and
    concurrent GetTrace/GetMetrics readers (hence the lock — profiling mode
    already pays a fence per dispatch, a mutex is noise)."""

    def __init__(self, fence: bool = True, n_params: int = 0,
                 peak: float = 0.0, mesh: dict | None = None,
                 peak_bw: float = 0.0):
        """`mesh` is the serving mesh shape ({'data': d, 'model': m, ...},
        None for single chip). It is recorded in every report and scales the
        MFU denominator by the chip count, so a TP profile can never be
        scoreboard-read as a single-chip one."""
        self.fence = fence
        self.n_params = n_params
        self.peak = peak
        self.peak_bw = peak_bw
        self.mesh = dict(mesh) if mesh else None
        self.chips = 1
        for size in (mesh or {}).values():
            self.chips *= max(int(size), 1)
        self._stages: dict[str, _Stage] = {}
        self._gauges: dict[str, float] = {}
        self._costs: dict[str, dict] = {}
        self._lock = lockdep_lock("telemetry.profiler")
        self._first_t: float | None = None
        self._last_t: float = 0.0

    def set_costs(self, costs: dict[str, dict]) -> None:
        """Per-stage XLA cost analysis (ISSUE 13): stage name → {"flops":
        per-dispatch FLOPs, "bytes": per-dispatch bytes accessed}, from
        `jit(...).lower().compile().cost_analysis()` on the stage's compiled
        program. Once set, report()/flat() emit the cost-backed `mfu`
        (measured dispatch time against real FLOPs) beside the legacy
        2·N·tokens estimate."""
        with self._lock:
            for stage, c in costs.items():
                self._costs[stage] = {
                    "flops": float(c.get("flops", 0.0)),
                    "bytes": float(c.get("bytes", 0.0))}

    def set_gauges(self, **gauges: float) -> None:
        """Scalar engine-level gauges (dispatch-fusing telemetry: decode
        dispatch count, steps/dispatch, host-sync wait per token). Surfaced
        through report()["gauges"] and as bare prof_<name> GetMetrics keys
        so the bench scoreboard and Prometheus layer can gate on them."""
        with self._lock:
            for k, v in gauges.items():
                self._gauges[k] = float(v)

    def record(self, stage: str, t0: float, tokens: int = 0,
               fence=None) -> float:
        """Close a stage interval opened at perf_counter() `t0`; when `fence`
        is given (any pytree of device arrays) the device work is awaited
        first so the sample covers compute, not enqueue. Returns the
        duration in seconds."""
        if fence is not None and self.fence:
            import jax

            jax.block_until_ready(fence)
        now = time.perf_counter()
        dt = max(now - t0, 0.0)
        with self._lock:
            st = self._stages.get(stage)
            if st is None:
                st = self._stages[stage] = _Stage()
            st.add(dt, tokens)
            if self._first_t is None:
                self._first_t = t0
            self._last_t = now
        return dt

    # ------------------------------------------------------------- export

    def report(self) -> dict:
        """Full stage breakdown: per-stage stats + busy-window coverage
        (sum of stage time / first-to-last-sample wall time)."""
        with self._lock:
            wall = (self._last_t - self._first_t) if self._first_t else 0.0
            stages = {}
            total = 0.0
            for name, st in self._stages.items():
                total += st.total_s
                # cost-backed MFU (ISSUE 13): the stage's real compiled
                # FLOPs per dispatch, over measured dispatch time and the
                # mesh's peak — None until the engine feeds set_costs()
                mfu = None
                cost = self._costs.get(name)
                if cost and cost["flops"] and self.peak and st.total_s > 0:
                    mfu = (cost["flops"] * st.count
                           / (st.total_s * self.peak * self.chips))
                stages[name] = {
                    "count": st.count,
                    "total_ms": st.total_s * 1e3,
                    "mean_ms": st.total_s / st.count * 1e3,
                    "p50_ms": st.p50_s() * 1e3,
                    "min_ms": st.min_s * 1e3,
                    "max_ms": st.max_s * 1e3,
                    "tokens": st.tokens,
                    "tok_s": (st.tokens / st.total_s
                              if st.total_s > 0 else 0.0),
                    "mfu": mfu,
                    **({"cost_flops": cost["flops"],
                        "cost_bytes": cost["bytes"]} if cost else {}),
                    "hist_bucket_upper_ms": [
                        b * 1e3 if math.isfinite(b) else None
                        for b in BUCKETS_S],
                    "hist": list(st.hist),
                }
        for s in stages.values():
            s["share"] = s["total_ms"] / (total * 1e3) if total else 0.0
        return {
            "stages": stages,
            "gauges": dict(self._gauges),
            "wall_ms": wall * 1e3,
            "busy_ms": total * 1e3,
            "coverage": (total / wall) if wall > 0 else 0.0,
            "fenced": self.fence,
            "n_params": self.n_params,
            "peak_flops": self.peak,
            "mesh": self.mesh,
            "chips": self.chips,
        }

    def flat(self, prefix: str = "prof_") -> dict[str, float]:
        """Flattened floats for the GetMetrics map (the str→double proto
        surface every dashboard already scrapes)."""
        out: dict[str, float] = {}
        with self._lock:
            for name, st in self._stages.items():
                out[f"{prefix}{name}_count"] = float(st.count)
                out[f"{prefix}{name}_total_ms"] = st.total_s * 1e3
                out[f"{prefix}{name}_p50_ms"] = st.p50_s() * 1e3
                if st.tokens and st.total_s > 0:
                    out[f"{prefix}{name}_tok_s"] = st.tokens / st.total_s
                cost = self._costs.get(name)
                if cost and cost["flops"] and self.peak and st.total_s > 0:
                    out[f"{prefix}{name}_mfu"] = (
                        cost["flops"] * st.count
                        / (st.total_s * self.peak * self.chips))
            for name, v in self._gauges.items():
                out[f"{prefix}{name}"] = v
        return out


def engine_profiler(cfg=None, mesh=None) -> StepProfiler | None:
    """Build the engine's profiler when LOCALAI_PROFILE is set (else None —
    the engine's gate for keeping the hot path fence-free). `cfg` is a
    LlamaConfig used for the MFU param count; `mesh` is the engine's
    jax Mesh (or an axis-shape dict) — recorded in the artifacts."""
    if not profile_enabled():
        return None
    shape = mesh if isinstance(mesh, dict) or mesh is None else None
    if shape is None and mesh is not None:
        from localai_tpu.parallel.mesh import mesh_shape

        shape = mesh_shape(mesh)
    n_params = 0
    if cfg is not None:
        try:
            from localai_tpu.system.memory import param_count

            n_params = param_count(cfg)
        except Exception:
            n_params = 0
    kind = ""
    try:
        import jax

        d = jax.devices()[0]
        kind = getattr(d, "device_kind", d.platform)
    except Exception:
        pass
    from localai_tpu.telemetry.sched import peak_bandwidth

    return StepProfiler(fence=True, n_params=n_params, peak=peak_flops(kind),
                        mesh=shape, peak_bw=peak_bandwidth(kind))
