"""Scheduler X-ray (ISSUE 13): per-tick pack ledger, reason codes, rooflines.

Three pieces, all riding the LOCALAI_METRICS default-ON gate:

- `REASON_CODES`: the single registered taxonomy for every admission /
  fallback / demotion decision the engine makes. This is a STABLE CONTRACT
  (README "Scheduler X-ray"): codes are only ever added, never renamed or
  removed, and an unregistered code is a hard failure — a new fallback site
  that forgets to register its reason fails the tripwire test, not a
  dashboard query six weeks later. The "dispatch" category has an exactness
  invariant: every dense (non-ragged) decode dispatch emits EXACTLY ONE
  dispatch-category code, so the per-code counters sum to
  `decode_dispatches - ragged_dispatches` — the same quantity bench.py
  reports as `dense_fallback_dispatches`.

- `TickLedger`: per-engine ring of tick records. Each tick collects the
  pack composition of every dispatch (decode rows, prefill-chunk tokens,
  spec verify windows, mm inject rows, pad/dead rows, token-budget rows)
  plus the tick's reason codes, and commits one record — the record also
  feeds the flight recorder's tick ring, so a post-mortem shows the last N
  *scheduling decisions*, not just dispatch counts. Disabled
  (LOCALAI_SCHED=0 or LOCALAI_METRICS=0) the engine keeps one attribute
  load + branch per tick (the `_obs` contract).

- roofline helpers: fold XLA's `lower().compile().cost_analysis()` FLOPs +
  bytes into compute- vs bandwidth-bound attribution per compiled program
  variant. `peak_bandwidth` mirrors profiler.peak_flops; the ridge point
  (peak_flops / peak_bw) splits the two regimes, and the per-variant `mfu`
  is the roofline model's ceiling for that program — what the dispatch
  could reach if it ran exactly at the limiting resource's peak.
"""
from __future__ import annotations

import os
import threading
import time
from collections import deque

from localai_tpu.telemetry.metrics import metrics_enabled
from localai_tpu.testing.lockdep import lockdep_lock

# --------------------------------------------------------------- reason codes
# code -> (category, description). Categories:
#   dispatch  — why a dense decode dispatch ran instead of the fused
#               while-loop (exactly ONE per dense dispatch; sums to
#               dense_fallback_dispatches)
#   demotion  — a fused block stepped DOWN the power-of-two ladder or was
#               forced to a single step (may co-occur with a dispatch code)
#   admission — a request was demoted/deferred/degraded at admission time
#   kv        — KV lifecycle tier actions (per block)
#   pack      — a ragged/spec pack hit its token-budget row cap
REASON_CODES: dict[str, tuple[str, str]] = {
    "loop_native": (
        "dispatch", "fused while-loop dispatch (the fast path, not a "
        "fallback — recorded so dispatch attribution is exhaustive)"),
    "loop_disabled": (
        "dispatch", "no while-loop program built (decode_loop=0 config)"),
    "draft_engine": (
        "dispatch", "speculative engine: the draft+verify program replaces "
        "the loop"),
    "grammar_hostonly": (
        "dispatch", "a live grammar overflowed the device tables and needs "
        "per-token host masks"),
    "pending_prefill": (
        "dispatch", "chunked prefill in flight: admission must not wait "
        "out a whole loop"),
    "pending_admission": (
        "dispatch", "queued request + free slot: per-token host decision "
        "pending"),
    "stop_string": (
        "dispatch", "an active slot has stop strings (per-token host scan)"),
    "spec_dense": (
        "dispatch", "dense speculative dispatch (draft engine without "
        "ragged packing)"),
    "context_margin": (
        "demotion", "a slot within 2*block of its context limit forced "
        "single-step dispatches"),
    "max_tokens_ladder": (
        "demotion", "a slot near max_tokens stepped the fused block down "
        "the power-of-two ladder"),
    "grammar_table_overflow": (
        "admission", "an automaton didn't fit the shared device grammar "
        "tables; the slot keeps per-token host masks"),
    "kv_policy_demotion": (
        "admission", "a full-attention request demoted to the windowed KV "
        "policy (compact table or low free pool)"),
    "kv_pool_exhausted": (
        "admission", "KV pool exhausted after reclaim: the request was "
        "deferred until blocks free"),
    "kv_eviction": (
        "kv", "a window-exited block was dropped (ring overwrite or full "
        "cold pool)"),
    "kv_cold_demotion": (
        "kv", "a window-exited block was quantized into the int8 cold "
        "pool"),
    "kv_host_spill": (
        "kv", "a dying device block (slot reclaim, prefix-cache rewrite, "
        "or kvtier eviction) was spilled to the host-RAM KV tier"),
    "kv_host_readmit": (
        "kv", "a host-tier block was re-admitted H2D during admission, "
        "extending the device prefix-cache hit"),
    "kv_host_miss_reprefill": (
        "kv", "device and host tiers both missed a full prefix block; the "
        "uncovered prefix falls back to re-prefill"),
    "kv_host_evict_budget": (
        "kv", "host-tier blocks were dropped (LRU over sessions) to "
        "respect the --kv-host-bytes budget"),
    "preempt_spill": (
        "kv", "a live slot's KV chain was force-spilled to the host tier "
        "by a preemption spill-drain (one count per frozen slot)"),
    "resume_readmit": (
        "admission", "a preempted request was re-admitted with its full-"
        "block KV prefix covered by the device/host caches (fast resume)"),
    "resume_reprefill": (
        "admission", "a preempted request resumed without KV coverage "
        "(host pool disabled, evicted, or budget too small) and fell back "
        "to re-prefilling prompt+emitted"),
    "budget_cap": (
        "pack", "the ragged token budget filled; remaining decode rows or "
        "prefill chunks wait for the next tick"),
    "loop_early_exit_finish": (
        "pack", "a fused ragged loop exited because a decode slot finished "
        "(EOS/max_tokens/context) — the host admits into the freed slot "
        "immediately instead of waiting out the step cap"),
    "loop_early_exit_prefill": (
        "pack", "a fused ragged loop ran a single iteration because the "
        "host flagged pending prefill/admission work at dispatch time"),
    "loop_early_exit_host_arbitration": (
        "pack", "a fused-capable ragged tick fell back to a single-step "
        "dispatch: a live slot needs per-token host decisions (host-only "
        "grammar masks or stop-string scans)"),
    "loop_early_exit_steps_cap": (
        "pack", "a fused ragged loop ran its full ragged_loop_steps budget "
        "with no early-exit condition"),
}

DISPATCH_CODES: tuple[str, ...] = tuple(
    c for c, (cat, _) in REASON_CODES.items() if cat == "dispatch")


def reason_category(code: str) -> str:
    return REASON_CODES[code][0]


# ----------------------------------------------------------------- enablement
_FORCED: bool | None = None


def sched_enabled() -> bool:
    """Tick ledger gate: ON by default, off when LOCALAI_SCHED=0 or the
    whole metrics layer is disabled (LOCALAI_METRICS=0)."""
    if _FORCED is not None:
        return _FORCED
    if os.environ.get("LOCALAI_SCHED", "1") in ("", "0"):
        return False
    return metrics_enabled()


def set_sched_enabled(value: bool | None) -> None:
    """Test hook: force the gate on/off (None = back to the env)."""
    global _FORCED
    _FORCED = value


def maybe_ledger() -> "TickLedger | None":
    """Per-engine ledger (one fresh instance per call — bench runs several
    engines in one process and their streams must not mix), or None when
    disabled so the engine hot path stays one attribute load + branch."""
    return TickLedger() if sched_enabled() else None


# ----------------------------------------------------------------- tick ident
# the most recent engine tick id, process-wide: FlightRecorder.record_event
# stamps it into every event (breaker opens, reaps, tripwires) so post-
# mortems correlate with the scheduling stream. With several engines in one
# process the last to tick wins — events still land within one tick of the
# stream that produced them.
_CURRENT_TICK: int | None = None


def set_current_tick(n: int | None) -> None:
    global _CURRENT_TICK
    _CURRENT_TICK = n


def current_tick() -> int | None:
    return _CURRENT_TICK


# ------------------------------------------------------------------ rooflines
def peak_bandwidth(device_kind: str) -> float:
    """HBM peak bytes/s for the accelerator kind (v5e 819 GB/s, v6e 1640;
    CPU gets a nominal 50 GB/s so roofline attribution stays meaningful in
    smoke runs). Mirrors profiler.peak_flops."""
    kind = (device_kind or "").lower()
    if "v6" in kind:
        return 1640e9
    if "v5p" in kind:
        return 2765e9
    if "v5" in kind:
        return 819e9
    if "v4" in kind:
        return 1228e9
    if "cpu" in kind:
        return 50e9
    return 819e9


def roofline_entry(flops: float, bytes_: float, peak_flops: float,
                   peak_bw: float) -> dict:
    """Fold one program's XLA cost analysis into roofline attribution.

    `mfu` here is the roofline-model CEILING for the program: the fraction
    of peak FLOP/s it could sustain if it ran exactly at the limiting
    resource's peak (1.0 when compute-bound, intensity/ridge when
    bandwidth-bound). Measured MFU can only be lower."""
    t_c = flops / peak_flops if peak_flops > 0 else 0.0
    t_m = bytes_ / peak_bw if peak_bw > 0 else 0.0
    t = max(t_c, t_m)
    return {
        "cost_flops": flops,
        "cost_bytes": bytes_,
        "intensity_flops_per_byte": (flops / bytes_) if bytes_ > 0 else 0.0,
        "ridge_flops_per_byte": (peak_flops / peak_bw) if peak_bw > 0
        else 0.0,
        "bound": "compute" if t_c >= t_m else "bandwidth",
        "t_compute_us": t_c * 1e6,
        "t_memory_us": t_m * 1e6,
        "t_roofline_us": t * 1e6,
        "mfu": (t_c / t) if t > 0 else 0.0,
    }


# ----------------------------------------------------------------- the ledger
_PACK_FIELDS = ("decode_rows", "prefill_tokens", "spec_windows", "mm_rows",
                "pad_rows", "rows_used", "budget_rows", "packed",
                "budget_packed")


class TickLedger:
    """Per-engine tick ledger. The engine drives it:

        ledger.begin(tick_n)
        ledger.reason("pending_admission")        # any decision site
        ledger.pack("ragged", decode_rows=..., ...)  # each dispatch
        rec = ledger.commit(active_slots=..., queued=...)

    and hands the committed record to the flight recorder's tick ring.
    Counters/totals are cumulative since the last reset() (warmup resets so
    bench/production streams start clean); the ring keeps the last `ring`
    full tick records for /debug/sched. A lock guards only the snapshot
    path — begin/reason/pack/commit run on the single engine thread."""

    def __init__(self, ring: int = 256):
        self.ticks: deque = deque(maxlen=ring)
        self.counters: dict[str, int] = {}
        self.variants: dict[str, int] = {}
        self.totals: dict[str, int] = dict.fromkeys(_PACK_FIELDS, 0)
        self.n_ticks = 0
        self.n_dispatches = 0
        # per-variant roofline entries (engine.rooflines() fills this after
        # the AOT cost-analysis pass; flat()/snapshot() then export them)
        self.rooflines: dict[str, dict] = {}
        self._cur: dict | None = None
        self._lock = lockdep_lock("telemetry.sched")

    def reset(self) -> None:
        """Drop accumulated ticks/counters (NOT the cached rooflines) — the
        engine calls this after warmup so compile-burst dispatches don't
        pollute the serving stream."""
        with self._lock:
            self.ticks.clear()
            self.counters.clear()
            self.variants.clear()
            self.totals = dict.fromkeys(_PACK_FIELDS, 0)
            self.n_ticks = 0
            self.n_dispatches = 0
            self._cur = None

    # ------------------------------------------------------------ recording

    def begin(self, tick: int) -> None:
        self._cur = {"tick": tick, "reasons": [], "packs": []}

    def reason(self, code: str, **fields) -> None:
        """Record one scheduling decision. Unregistered codes raise — the
        taxonomy is the contract, and a site inventing a code must fail in
        tests, not ship an unqueryable string."""
        if code not in REASON_CODES:
            raise ValueError(
                f"unregistered scheduler reason code {code!r} — add it to "
                f"localai_tpu.telemetry.sched.REASON_CODES (stable "
                f"contract: codes are only ever added)")
        self.counters[code] = self.counters.get(code, 0) + 1
        cur = self._cur
        if cur is not None:
            cur["reasons"].append(
                dict(fields, code=code) if fields else code)

    def pack(self, variant: str, *, decode_rows: int = 0,
             prefill_tokens: int = 0, spec_windows: int = 0,
             mm_rows: int = 0, pad_rows: int = 0, rows_used: int = 0,
             budget_rows: int = 0, packed: int = 0) -> None:
        """Record one dispatch's pack composition under its compiled program
        variant name (the same name engine.rooflines() costs)."""
        self.n_dispatches += 1
        self.variants[variant] = self.variants.get(variant, 0) + 1
        comp = {"decode_rows": decode_rows, "prefill_tokens": prefill_tokens,
                "spec_windows": spec_windows, "mm_rows": mm_rows,
                "pad_rows": pad_rows, "rows_used": rows_used,
                "budget_rows": budget_rows, "packed": packed,
                # only budget-carrying dispatches feed the utilization ratio
                # — a dense fallback's rows have no budget to utilize
                "budget_packed": packed if budget_rows > 0 else 0}
        t = self.totals
        for k, v in comp.items():
            t[k] += v
        cur = self._cur
        if cur is not None:
            cur["packs"].append(dict(comp, variant=variant))

    def commit(self, **meta) -> dict:
        """Seal the current tick record (begin() must have run) and append
        it to the ring. Returns the record — the engine forwards it to the
        flight recorder's tick ring verbatim."""
        rec = self._cur or {"tick": -1, "reasons": [], "packs": []}
        self._cur = None
        rec["t_wall"] = time.time()
        rec.update(meta)
        with self._lock:
            self.n_ticks += 1
            self.ticks.append(rec)
        return rec

    # -------------------------------------------------------------- export

    def budget_utilization(self) -> float:
        """Fraction of the ragged/spec token budget carrying live tokens
        (dense dispatches have no budget rows and don't dilute this; 0.0
        when no budget-carrying dispatch ran — dense-only engines)."""
        if self.totals["budget_rows"] <= 0:
            return 0.0
        return self.totals["budget_packed"] / self.totals["budget_rows"]

    def pad_rows_frac(self) -> float:
        """Fraction of ALLOCATED q rows that were QBLK-alignment padding —
        the cost of the one-row-per-decode-slot layout contract."""
        return self.totals["pad_rows"] / max(self.totals["rows_used"], 1)

    def flat(self, prefix: str = "sched_") -> dict[str, float]:
        """Flattened floats for the GetMetrics str→double surface. Only
        CACHED roofline entries are exported — this never compiles."""
        with self._lock:
            out: dict[str, float] = {
                f"{prefix}ticks_total": float(self.n_ticks),
                f"{prefix}dispatches_total": float(self.n_dispatches),
            }
            for code, n in self.counters.items():
                out[f"{prefix}reason__{code}"] = float(n)
            for name, n in self.variants.items():
                out[f"{prefix}variant__{name}"] = float(n)
            for k, v in self.totals.items():
                out[f"{prefix}pack__{k}"] = float(v)
            if self.totals["budget_rows"]:
                out[f"{prefix}budget_utilization"] = \
                    self.budget_utilization()
            out[f"{prefix}pad_rows_frac"] = self.pad_rows_frac()
            for name, e in self.rooflines.items():
                out[f"{prefix}roofline__{name}__flops"] = e["cost_flops"]
                out[f"{prefix}roofline__{name}__bytes"] = e["cost_bytes"]
                out[f"{prefix}roofline__{name}__mfu"] = e["mfu"]
        return out

    def snapshot(self, last: int = 64) -> dict:
        """Structured export for /debug/sched and GetTrace."""
        with self._lock:
            return {
                "ticks_total": self.n_ticks,
                "dispatches_total": self.n_dispatches,
                "reason_counters": dict(self.counters),
                "variants": dict(self.variants),
                "pack_totals": dict(self.totals),
                "budget_utilization": (self.budget_utilization()
                                       if self.totals["budget_rows"]
                                       else None),
                "pad_rows_frac": self.pad_rows_frac(),
                "rooflines": {k: dict(v)
                              for k, v in self.rooflines.items()},
                "recent_ticks": list(self.ticks)[-last:],
            }
