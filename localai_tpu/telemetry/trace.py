"""Low-overhead span tracer — the request-path observability core.

Spans are monotonic-clock intervals with ids/parents, recorded into a
preallocated ring buffer. Writes are lock-free: the ring index comes from
`itertools.count()` (whose `__next__` is atomic under the GIL) and each slot
assignment is a single list store, so the engine loop, gRPC handler threads
and the asyncio HTTP process can all record concurrently without contention.
A full ring overwrites the oldest spans — tracing never blocks or grows.

Everything is opt-in: with `LOCALAI_TRACE` unset the recording calls are
never reached (callers gate on `trace_enabled()` / a cached tracer handle),
so the serving hot path stays untouched.

The export format is Chrome-trace/Perfetto "trace event" JSON (`ph: "X"`
complete events): load the dump at chrome://tracing or ui.perfetto.dev.
Timestamps are perf_counter-based but rebased onto the wall clock at module
import, so spans recorded by different processes (HTTP server + backend
subprocesses) merge into one coherent timeline.

Request-id propagation: `new_request_id()` in the HTTP middleware →
`set_request_id()` contextvar → `current_request_id()` read by the gRPC
client when attaching `x-localai-request-id` metadata → the backend servicer
hands it to the engine via `GenRequest.trace_id` — every layer's spans carry
the same `request_id` arg.
"""
from __future__ import annotations

import contextlib
import contextvars
import itertools
import os
import threading
import time
import uuid

from localai_tpu.testing.lockdep import lockdep_lock

# perf_counter → wall-clock rebasing (one constant per process): Chrome-trace
# `ts` fields from different processes line up on the same timeline
_EPOCH_US = time.time_ns() // 1000 - time.perf_counter_ns() // 1000

_REQUEST_ID: contextvars.ContextVar[str] = contextvars.ContextVar(
    "localai_request_id", default="")
_CURRENT_SPAN: contextvars.ContextVar["OpenSpan | None"] = \
    contextvars.ContextVar("localai_current_span", default=None)

# None = follow the environment; set_trace_enabled() overrides (tests, bench)
_FORCED: bool | None = None


def trace_enabled() -> bool:
    if _FORCED is not None:
        return _FORCED
    return os.environ.get("LOCALAI_TRACE", "") not in ("", "0")


def set_trace_enabled(value: bool | None) -> None:
    """Force tracing on/off in-process (None = back to the env var)."""
    global _FORCED
    _FORCED = value


def new_request_id() -> str:
    return "req-" + uuid.uuid4().hex[:16]


def set_request_id(rid: str):
    """Bind `rid` to the current context; returns the reset token."""
    return _REQUEST_ID.set(rid)


def reset_request_id(token) -> None:
    _REQUEST_ID.reset(token)


def current_request_id() -> str:
    return _REQUEST_ID.get()


class OpenSpan:
    """A begun-but-unfinished span (finish() writes the ring event)."""
    __slots__ = ("sid", "name", "cat", "t0_ns", "parent_id", "args", "tid")

    def __init__(self, sid, name, cat, t0_ns, parent_id, args, tid):
        self.sid = sid
        self.name = name
        self.cat = cat
        self.t0_ns = t0_ns
        self.parent_id = parent_id
        self.args = args
        self.tid = tid


class Tracer:
    """Ring-buffer span recorder; one instance per process (see tracer())."""

    def __init__(self, capacity: int = 16384):
        self.capacity = max(64, capacity)
        self._ring: list[dict | None] = [None] * self.capacity
        self._slot = itertools.count()   # lock-free ring cursor
        self._ids = itertools.count(1)   # span ids (0 = no parent)
        self.pid = os.getpid()

    # ---------------------------------------------------------- recording

    def begin(self, name: str, cat: str = "", parent_id: int | None = None,
              args: dict | None = None) -> OpenSpan:
        if parent_id is None:
            cur = _CURRENT_SPAN.get()
            parent_id = cur.sid if cur is not None else 0
        return OpenSpan(next(self._ids), name, cat, time.perf_counter_ns(),
                        parent_id, dict(args) if args else {},
                        threading.get_native_id())

    def finish(self, span: OpenSpan, **extra) -> None:
        now = time.perf_counter_ns()
        if extra:
            span.args.update(extra)
        self._write(span.name, span.cat, span.t0_ns, now - span.t0_ns,
                    span.sid, span.parent_id, span.args, span.tid)

    def add_complete(self, name: str, t0: float, dur_s: float | None = None,
                     cat: str = "", parent_id: int = 0,
                     args: dict | None = None) -> None:
        """Record a finished interval from a perf_counter() start time."""
        t0_ns = int(t0 * 1e9)
        dur_ns = (time.perf_counter_ns() - t0_ns if dur_s is None
                  else int(dur_s * 1e9))
        self._write(name, cat, t0_ns, dur_ns, next(self._ids), parent_id,
                    dict(args) if args else {}, threading.get_native_id())

    def _write(self, name, cat, t0_ns, dur_ns, sid, parent_id, args, tid):
        args["span_id"] = sid
        if parent_id:
            args["parent_id"] = parent_id
        rid = _REQUEST_ID.get()
        if rid and "request_id" not in args:
            args["request_id"] = rid
        event = {
            "name": name, "cat": cat or "localai", "ph": "X",
            "ts": t0_ns // 1000 + _EPOCH_US,
            "dur": max(dur_ns // 1000, 0),
            "pid": self.pid, "tid": tid, "args": args,
        }
        self._ring[next(self._slot) % self.capacity] = event

    @contextlib.contextmanager
    def span(self, name: str, cat: str = "", **args):
        """Context manager: nested spans parent automatically (contextvar)."""
        s = self.begin(name, cat, args=args)
        token = _CURRENT_SPAN.set(s)
        try:
            yield s
        finally:
            _CURRENT_SPAN.reset(token)
            self.finish(s)

    # ------------------------------------------------------------- export

    def events(self) -> list[dict]:
        """Snapshot the ring as Chrome-trace events, oldest first."""
        out = [e for e in list(self._ring) if e is not None]
        out.sort(key=lambda e: e["ts"])
        return out

    def clear(self) -> None:
        self._ring = [None] * self.capacity


_TRACER: Tracer | None = None
_TRACER_LOCK = lockdep_lock("telemetry.tracer_init")


def tracer() -> Tracer:
    """The process-wide tracer (created on first use)."""
    global _TRACER
    if _TRACER is None:
        with _TRACER_LOCK:
            if _TRACER is None:
                cap = int(os.environ.get("LOCALAI_TRACE_BUFFER", "16384"))
                _TRACER = Tracer(cap)
    return _TRACER


def maybe_tracer() -> Tracer | None:
    """tracer() when tracing is enabled, else None — the cheap gate callers
    cache so a disabled build never constructs or touches the ring."""
    return tracer() if trace_enabled() else None


@contextlib.contextmanager
def span(name: str, cat: str = "", **args):
    """Module-level convenience: no-op when tracing is disabled."""
    t = maybe_tracer()
    if t is None:
        yield None
        return
    with t.span(name, cat, **args) as s:
        yield s


def chrome_events() -> list[dict]:
    """This process's recorded spans (empty when tracing never started)."""
    return _TRACER.events() if _TRACER is not None else []


def chrome_trace(events: list[dict],
                 process_names: dict[int, str] | None = None) -> dict:
    """Wrap events into a self-contained Chrome-trace JSON object."""
    meta = []
    for pid, pname in (process_names or {}).items():
        meta.append({"name": "process_name", "ph": "M", "pid": pid, "tid": 0,
                     "args": {"name": pname}})
    return {"traceEvents": meta + events, "displayTimeUnit": "ms"}
