# Lazy re-exports (PEP 562): backend.client imports core.resilience for
# deadline propagation, and manager imports backend.client — an eager
# manager import here would close that loop into a cycle.


def __getattr__(name):
    if name in ("ModelManager", "BackendHandle"):
        from localai_tpu.core import manager

        return getattr(manager, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
