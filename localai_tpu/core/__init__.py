from localai_tpu.core.manager import ModelManager, BackendHandle  # noqa: F401
