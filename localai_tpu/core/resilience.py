"""Fault-tolerance primitives shared by the serving stack (ISSUE 4).

Three small pieces, deliberately dependency-free so every layer can import
them:

- `CircuitBreaker`: closed/open/half-open per-backend (and, in federation,
  per-worker) failure gate — stops respawn storms when a model is genuinely
  broken instead of hammering a crashing subprocess in a tight loop.
- deadline propagation: a per-request budget minted by the HTTP middleware
  lives in a contextvar (asyncio.to_thread copies the context, same as the
  request-id propagation in telemetry/trace.py), so the gRPC client can
  shrink its timeouts to the remaining budget and ship it to the engine.
- typed serving errors carrying an HTTP status + Retry-After hint, so the
  middleware can translate supervisor/admission failures into the right
  client-visible responses (429/503/504) instead of a raw 500.
"""
from __future__ import annotations

import contextvars
import threading
import time

from localai_tpu.testing.lockdep import lockdep_lock


# --------------------------------------------------------------- errors

class ResilienceError(RuntimeError):
    """Base for serving-path failures with a definite HTTP translation."""
    status = 500
    retry_after: float | None = None

    def __init__(self, message: str, retry_after: float | None = None):
        super().__init__(message)
        if retry_after is not None:
            self.retry_after = retry_after


class BackendUnavailable(ResilienceError):
    """Backend dead / unreachable / circuit broken — retriable later (503)."""
    status = 503
    retry_after = 1.0


class WatchdogReaped(ResilienceError):
    """The busy-watchdog deliberately killed the backend serving this
    request — a gateway-timeout, not a generic RPC failure (504)."""
    status = 504


class DeadlineExceeded(ResilienceError):
    """The request's deadline budget ran out (504)."""
    status = 504


class RequestShed(ResilienceError):
    """Admission control refused the request: in-flight + wait queue full
    (429) or the server is draining (503). `model`/`reason` feed the
    localai_shed_total counter."""
    status = 429
    retry_after = 1.0

    def __init__(self, message: str, model: str = "", reason: str = "",
                 status: int = 429, retry_after: float | None = None):
        super().__init__(message, retry_after=retry_after)
        self.model = model
        self.reason = reason
        self.status = status


# --------------------------------------------------------------- breaker

class CircuitBreaker:
    """Per-backend closed → open → half-open failure gate.

    closed: requests flow; `threshold` consecutive failures trip it open.
    open: `allow()` is False (fail fast) until `cooldown` elapses.
    half-open: the next caller(s) probe the backend; one success closes the
    breaker, one failure re-opens it for another cooldown. The half-open
    admit is deliberately not single-flighted — a raced extra probe costs
    one RPC, while a probe token lost to a crashed caller would wedge the
    breaker open forever.
    """

    CLOSED, OPEN, HALF_OPEN = "closed", "open", "half_open"

    def __init__(self, threshold: int = 3, cooldown: float = 15.0,
                 clock=time.monotonic, name: str = ""):
        self.threshold = max(1, int(threshold))
        self.cooldown = float(cooldown)
        self.name = name            # flight-recorder label ("" = anonymous)
        self._clock = clock
        self._lock = lockdep_lock("breaker")
        self._state = self.CLOSED
        self._failures = 0
        self._opened_at = 0.0

    @property
    def state(self) -> str:
        with self._lock:
            if (self._state == self.OPEN
                    and self._clock() - self._opened_at >= self.cooldown):
                self._state = self.HALF_OPEN
            return self._state

    def allow(self) -> bool:
        return self.state != self.OPEN

    def retry_after(self) -> float:
        """Seconds until the next half-open probe would be admitted."""
        with self._lock:
            if self._state != self.OPEN:
                return 0.0
            return max(0.0, self.cooldown - (self._clock() - self._opened_at))

    def record_success(self):
        with self._lock:
            self._state = self.CLOSED
            self._failures = 0

    def record_failure(self):
        with self._lock:
            self._failures += 1
            failures = self._failures
            trip = (self._state == self.HALF_OPEN
                    or failures >= self.threshold)
            opened = trip and self._state != self.OPEN
            if trip:
                self._state = self.OPEN
                self._opened_at = self._clock()
        if opened:
            # post-mortem hook (ISSUE 11): a breaker transitioning to OPEN
            # means a backend is failing repeatedly — snapshot the recent
            # request/event history while it is still in the ring. Outside
            # the lock: auto_dump does file I/O.
            from localai_tpu import telemetry

            rec = telemetry.flightrec()
            rec.record_event("breaker_open", name=self.name,
                             failures=failures)
            rec.auto_dump(f"breaker_open:{self.name or 'anon'}")


def backoff(attempt: int, base: float = 0.25, cap: float = 2.0) -> float:
    """Capped exponential backoff delay for retry `attempt` (1-based)."""
    return min(base * (2 ** max(attempt - 1, 0)), cap)


# --------------------------------------------------------------- deadline

# absolute time.monotonic() instant the current request's budget expires;
# None = no deadline bound (non-request contexts, tests)
_deadline: contextvars.ContextVar[float | None] = contextvars.ContextVar(
    "localai_deadline", default=None)


def set_deadline(budget_s: float):
    """Bind the current context to `now + budget_s`; returns the reset
    token. Call from the HTTP middleware only — everything downstream
    (thread pool included: to_thread copies the context) reads it."""
    return _deadline.set(time.monotonic() + budget_s)


def reset_deadline(token):
    _deadline.reset(token)


def deadline_remaining() -> float | None:
    """Seconds left in this request's budget (may be <= 0), or None."""
    d = _deadline.get()
    return None if d is None else d - time.monotonic()


def deadline_expired() -> bool:
    rem = deadline_remaining()
    return rem is not None and rem <= 0
