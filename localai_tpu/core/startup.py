"""Startup helpers: .env loading, model preload, dynamic config watching.

Reference parity:
- .env loading — /root/reference/cmd/local-ai/main.go:26-42 (godotenv over
  .env/.env.local before flag parsing).
- startup preload — /root/reference/core/application/startup.go:65-105
  (InstallModels over the CLI positional model list, then warm the backends).
- dynamic config watcher — /root/reference/core/config/config_file_watcher.go
  :29-126 (fsnotify on the models dir → hot reload). Here a polling watcher:
  no inotify dependency, identical observable behavior (new/changed/removed
  YAML become servable without restart), 2s granularity.
"""
from __future__ import annotations

import logging
import os
import threading

log = logging.getLogger("localai_tpu.startup")


def load_env_files(paths: list[str] | None = None) -> list[str]:
    """Load KEY=VALUE lines from .env files into os.environ (existing vars
    win, matching godotenv.Load semantics). Returns the files applied."""
    candidates = paths if paths else [".env", ".env.local"]
    applied = []
    for path in candidates:
        if not path or not os.path.isfile(path):
            continue
        try:
            with open(path, encoding="utf-8") as fh:
                for line in fh:
                    line = line.strip()
                    if not line or line.startswith("#") or "=" not in line:
                        continue
                    if line.startswith("export "):
                        line = line[len("export "):]
                    key, _, value = line.partition("=")
                    key = key.strip()
                    value = value.strip()
                    # quoted values keep their content verbatim; unquoted
                    # values lose trailing inline comments (godotenv rules)
                    if len(value) >= 2 and value[0] == value[-1] and \
                            value[0] in "'\"":
                        value = value[1:-1]
                    elif "#" in value:
                        value = value.split("#", 1)[0].strip()
                    if key and key not in os.environ:
                        os.environ[key] = value
            applied.append(path)
        except OSError as e:
            log.warning(".env load failed for %s: %s", path, e)
    return applied


def preload_models(names: list[str], configs, manager,
                   gallery_service=None, install_timeout: float = 900.0) -> None:
    """Resolve + warm the CLI's positional model list (startup.go:65-105).

    Each entry is either a configured model name (→ spawn its backend now so
    the first request doesn't pay the load) or a gallery name/URI (→ install
    through the gallery service, then warm). Failures log and continue —
    startup must not die on one bad preload, matching the reference's
    warn-and-continue loop.
    """
    for name in names:
        cfg = configs.get(name)
        if cfg is None and gallery_service is not None:
            try:
                import time

                job = gallery_service.submit(name)
                deadline = time.monotonic() + install_timeout
                while gallery_service.status[job]["state"] in ("queued",
                                                               "processing"):
                    if time.monotonic() > deadline:
                        raise TimeoutError(
                            f"install still {gallery_service.status[job]['state']} "
                            f"after {install_timeout:.0f}s")
                    time.sleep(0.2)
                if gallery_service.status[job]["state"] == "error":
                    raise RuntimeError(gallery_service.status[job]["error"])
                configs.reload()
                cfg = configs.get(name)
            except Exception as e:
                log.warning("preload: install of %r failed: %s", name, e)
        if cfg is None:
            log.warning("preload: model %r not found in %s", name,
                        configs.models_path)
            continue
        try:
            manager.load(cfg)
            log.info("preload: %s ready", name)
        except Exception as e:
            log.warning("preload: backend for %r failed to start: %s", name, e)


class ConfigWatcher:
    """Poll the models dir for YAML add/change/remove → hot reload
    (config_file_watcher.go role, poll-based)."""

    def __init__(self, configs, interval: float = 2.0):
        self.configs = configs
        self.interval = interval
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self._snapshot = self._scan()

    def _scan(self) -> dict[str, float]:
        snap: dict[str, float] = {}
        root = self.configs.models_path
        try:
            for entry in os.listdir(root):
                if entry.endswith((".yaml", ".yml")):
                    p = os.path.join(root, entry)
                    try:
                        snap[entry] = os.stat(p).st_mtime
                    except OSError:
                        pass
        except OSError:
            pass
        return snap

    def start(self):
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="config-watcher")
        self._thread.start()
        return self

    def stop(self):
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=self.interval + 1)

    def _run(self):
        while not self._stop.wait(self.interval):
            snap = self._scan()
            if snap != self._snapshot:
                self._snapshot = snap
                try:
                    self.configs.reload()
                    log.info("config watcher: models dir changed, reloaded "
                             "(%d configs)", len(self.configs.names()))
                except Exception as e:
                    log.warning("config watcher: reload failed: %s", e)
