"""Model lifecycle: spawn backend processes, health-poll, load, reap, watchdog.

The reference's L3 (/root/reference/pkg/model): mutex-guarded model map
(loader.go:22-41), spawn on a free localhost port + health poll + LoadModel
RPC (process.go:93-160, initializers.go:50-154), dead-process reap on cache
hit (loader.go:191-225), busy/idle watchdog (watchdog.go:19-49), single-active
-backend serialization (initializers.go:205-226).

Resilience layer (ISSUE 4): loads serialize per MODEL (a 120 s spawn of model
A no longer freezes model B), dead children are detected immediately and
respawned on a fresh port (the free_port TOCTOU race), a per-backend circuit
breaker stops respawn storms, and `supervised()` retries request-time
UNAVAILABLE/dead-backend failures with capped backoff — translating watchdog
reaps and breaker rejections into typed errors the HTTP layer maps to
504/503.
"""
from __future__ import annotations

import collections
import json
import os
import socket
import subprocess
import sys
import threading
import time
from dataclasses import dataclass, field

import grpc

from localai_tpu.backend.client import BackendClient
from localai_tpu.config import AppConfig, ModelConfig
from localai_tpu.core import resilience
from localai_tpu.core.resilience import (
    BackendUnavailable, CircuitBreaker, DeadlineExceeded, WatchdogReaped,
    backoff,
)
from localai_tpu.testing.lockdep import lockdep_lock


def free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


class SpawnCrashed(RuntimeError):
    """The backend child exited before ever answering health — either it
    crashed at startup or lost the free_port TOCTOU race (another process
    bound the port between close() and the child's bind). Retriable on a
    fresh port without burning the whole health budget."""


@dataclass
class BackendHandle:
    name: str
    config: ModelConfig
    proc: subprocess.Popen
    client: BackendClient
    port: int
    busy: int = 0                 # in-flight requests
    last_used: float = field(default_factory=time.monotonic)
    busy_since: float = 0.0
    poisoned: str = ""            # terminal reason stamped by the reaper —
                                  # in-flight requests that now fail their
                                  # RPC surface THIS instead of a raw
                                  # severed-channel grpc error
    _lock: threading.Lock = field(
        default_factory=lambda: lockdep_lock("manager.handle"))

    def alive(self) -> bool:
        return self.proc.poll() is None

    def poison(self, reason: str):
        if reason and not self.poisoned:
            self.poisoned = reason

    def mark_busy(self):
        with self._lock:
            if self.busy == 0:
                self.busy_since = time.monotonic()
            self.busy += 1

    def mark_idle(self):
        with self._lock:
            self.busy = max(0, self.busy - 1)
            self.last_used = time.monotonic()


class ModelManager:
    """name → running backend process; the control plane's only way to reach
    model compute."""

    def __init__(self, app: AppConfig):
        self.app = app
        self._models: dict[str, BackendHandle] = {}
        self._lock = lockdep_lock("manager.map")  # guards the maps only —
                                               # never held across
                                               # spawn/health/RPC
        self._model_locks: dict[str, threading.Lock] = {}
        self._breakers: dict[str, CircuitBreaker] = {}
        # supervision telemetry: (model, event) → count, scraped into the
        # localai_backend_supervision_total Prometheus gauge
        self.events: collections.Counter = collections.Counter()
        self._watchdog: threading.Thread | None = None
        self._stop = threading.Event()

    def _model_lock(self, name: str) -> threading.Lock:
        with self._lock:
            lk = self._model_locks.get(name)
            if lk is None:
                lk = self._model_locks[name] = lockdep_lock(
                    "manager.model", per_key=True)
            return lk

    def breaker(self, name: str) -> CircuitBreaker:
        with self._lock:
            br = self._breakers.get(name)
            if br is None:
                br = self._breakers[name] = CircuitBreaker(
                    threshold=getattr(self.app, "breaker_threshold", 3),
                    cooldown=getattr(self.app, "breaker_cooldown", 15.0),
                    name=name)
            return br

    # ------------------------------------------------------------ spawn/load

    def _spawn_once(self, cfg: ModelConfig) -> BackendHandle:
        port = free_port()
        env = dict(os.environ)
        # child must import localai_tpu regardless of the parent's cwd, and
        # existing PYTHONPATH entries (e.g. a site hook registering the TPU
        # PJRT plugin) must survive — prepend, never replace
        pkg_root = os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))))
        parts = [pkg_root] + [
            p for p in env.get("PYTHONPATH", "").split(os.pathsep) if p]
        env["PYTHONPATH"] = os.pathsep.join(dict.fromkeys(parts))
        # chaos-harness targeting: fault specs may scope to one model name
        # (localai_tpu/testing/faults.py) — stamp the child so they can
        env["LOCALAI_FAULT_MODEL"] = cfg.name
        # preemption grace (ISSUE 19): how long the backend's SIGTERM
        # fast-path lets live slots run before force-freezing them
        grace = getattr(self.app, "preempt_grace", 0.0) or 0.0
        if grace:
            env["LOCALAI_PREEMPT_GRACE"] = str(grace)
        # gallery-installed external backend? its run.sh owns the process
        # (reference initializers.go:50-99 — external backends launch from
        # the backends dir); in-tree roles spawn the python module
        external = None
        if self.app.backends_path:
            from localai_tpu.services.backend_gallery import (
                resolve_backend_dir,
            )

            external = resolve_backend_dir(self.app.backends_path,
                                           cfg.backend)
        if external is not None:
            argv = ["/bin/sh", os.path.join(external, "run.sh"),
                    "--addr", f"127.0.0.1:{port}"]
            cwd = external
        else:
            argv = [sys.executable, "-m", "localai_tpu.backend",
                    "--addr", f"127.0.0.1:{port}", "--backend", cfg.backend]
            # inherit the parent's cwd: a relative --models-path must resolve
            # against the launch dir, not the backends dir
            cwd = None
        proc = subprocess.Popen(
            argv,
            env=env,
            cwd=cwd,
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        )
        # tail child output into our log (reference process.go:140-157)
        threading.Thread(target=self._tail, args=(cfg.name, proc),
                         daemon=True).start()
        client = BackendClient(f"127.0.0.1:{port}")
        budget = getattr(self.app, "spawn_timeout", 120.0) or 120.0
        deadline = time.monotonic() + budget
        ready = False
        while time.monotonic() < deadline:
            if client.health(timeout=2.0, wait=True):
                ready = True
                break
            if proc.poll() is not None:
                # dead child: don't sit out the rest of the health budget —
                # either a startup crash or the port TOCTOU race; the caller
                # retries on a fresh port
                client.close()
                raise SpawnCrashed(
                    f"backend for {cfg.name} exited rc={proc.returncode} "
                    f"before becoming healthy (port {port})")
            time.sleep(0.25)
        if not ready:
            client.close()
            proc.terminate()
            raise RuntimeError(
                f"backend for {cfg.name} never became healthy "
                f"within {budget:.0f}s")
        return BackendHandle(name=cfg.name, config=cfg, proc=proc,
                             client=client, port=port)

    def _spawn(self, cfg: ModelConfig) -> BackendHandle:
        """Spawn with fresh-port retries when the child dies before health —
        a crashing backend fails in seconds, not spawn_timeout."""
        retries = max(0, getattr(self.app, "spawn_retries", 2))
        last: Exception | None = None
        for attempt in range(retries + 1):
            try:
                return self._spawn_once(cfg)
            except SpawnCrashed as e:
                last = e
                if attempt < retries:
                    self.events[(cfg.name, "spawn_retry")] += 1
        raise last

    @staticmethod
    def _tail(name: str, proc: subprocess.Popen):
        for line in proc.stdout or []:
            # stderr, not stdout: tools with a machine-readable stdout
            # contract (bench.py's one-JSON-line output) embed the manager
            print(f"[backend:{name}] {line.rstrip()}", file=sys.stderr,
                  flush=True)

    def _load_rpc(self, handle: BackendHandle):
        cfg = self.app
        m = handle.config
        # fields without a proto slot ride the ModelOptions.options JSON
        # blob (the hfapi backend's endpoint override uses the same lane)
        opts = {}
        kv_policy = m.kv_policy
        if not kv_policy and cfg.kv_window:
            # app-wide --kv-window default for models without their own
            # kv_policy (per-model YAML wins)
            kv_policy = (f"sink_window(sinks={cfg.kv_sinks}, "
                         f"window={cfg.kv_window})")
        if kv_policy:
            opts["kv_policy"] = kv_policy
        if m.kv_cold_pages:
            opts["kv_cold_pages"] = m.kv_cold_pages
        kv_host_bytes = m.kv_host_bytes or cfg.kv_host_bytes
        if kv_host_bytes:
            opts["kv_host_bytes"] = kv_host_bytes
        r = handle.client.load_model(
            options=json.dumps(opts) if opts else "",
            model=m.model_dir(cfg.models_path),
            context_size=m.context_size or cfg.context_size,
            parallel=m.parallel or cfg.parallel_requests,
            dtype=m.dtype,
            prefill_buckets=m.prefill_buckets,
            mesh_data=m.mesh.data,
            # per-model YAML mesh wins; else the app-wide --tensor-parallel
            # degree (0 = backend auto-TP over every divisible device)
            mesh_model=m.mesh.model or cfg.tensor_parallel,
            embeddings=m.embeddings or m.backend == "embedding",
            draft_model=(m.draft_model if not m.draft_model
                         or os.path.isabs(m.draft_model)
                         else os.path.join(cfg.models_path, m.draft_model)),
            n_draft=m.n_draft,
            cache_type_key=m.cache_type_k,
            cache_type_value=m.cache_type_v,
            kv_pages=m.kv_pages,
        )
        if not r.success:
            raise RuntimeError(f"LoadModel({m.name}) failed: {r.message}")

    # ------------------------------------------------------------ public api

    def load(self, cfg: ModelConfig) -> BackendHandle:
        """Get-or-start the backend for a model config. Health-rechecks cached
        processes and reaps+respawns dead ones (loader.go:191-225).

        Serialization is per model: concurrent loads of the SAME model share
        one spawn; a load of model B proceeds while model A is mid-spawn
        (the seed held one global lock through the whole 120 s health wait).
        The circuit breaker fails fast once a model has proven broken."""
        h = self.get(cfg.name)
        if h is not None and h.alive() and h.client.health(timeout=5.0):
            h.last_used = time.monotonic()
            return h
        br = self.breaker(cfg.name)
        if not br.allow():
            self.events[(cfg.name, "breaker_reject")] += 1
            raise BackendUnavailable(
                f"circuit breaker open for {cfg.name!r} after repeated "
                f"backend failures; next probe in {br.retry_after():.1f}s",
                retry_after=max(br.retry_after(), 0.1))
        with self._model_lock(cfg.name):
            # somebody may have finished the same load while we waited
            h = self.get(cfg.name)
            if h is not None:
                # lint: allow(lock-across-blocking) — the per-MODEL lock is
                # the load-serialization point by design (PR 4): it blocks
                # only same-model loads; the map lock is never held here
                if h.alive() and h.client.health(timeout=5.0):
                    h.last_used = time.monotonic()
                    br.record_success()
                    return h
                # lockdep: allow(lock-blocking) — reap of the dead handle
                # (proc.wait) stays under the per-MODEL lock so the respawn
                # below can't race a half-dead predecessor
                self._reap(h, reason="dead backend found at load")
                self.events[(cfg.name, "reap_dead")] += 1
            if self.app.single_active_backend:
                with self._lock:
                    others = [o for o in self._models.values()
                              if o.name != cfg.name]
                for other in others:
                    # lockdep: allow(lock-blocking) — evicting the previous
                    # backend (proc.wait) must finish before this model's
                    # load proceeds; only same-model loads wait on us
                    self._reap(other, reason="single_active_backend")
            h = None
            try:
                # lockdep: allow(lock-blocking) — spawn + health poll + the
                # load RPC run under the per-MODEL lock on purpose: this IS
                # the load-serialization point (PR 4 moved the blocking off
                # the map lock, not off this one)
                h = self._spawn(cfg)
                # lockdep: allow(lock-blocking) — same: load RPC serialized
                # per model by design
                self._load_rpc(h)
            except Exception:
                br.record_failure()
                if h is not None:
                    # lockdep: allow(lock-blocking) — reaping the failed
                    # spawn (proc.wait) before releasing the load lock keeps
                    # the port/process accounting consistent for the retry
                    self._reap(h, reason="load failed")
                raise
            br.record_success()
            with self._lock:
                self._models[cfg.name] = h
            return h

    def get(self, name: str) -> BackendHandle | None:
        with self._lock:
            return self._models.get(name)

    def loaded(self) -> list[str]:
        with self._lock:
            return sorted(self._models)

    # reap reasons that are routine lifecycle, not failures — they go in the
    # flight-recorder ring but do not trigger a post-mortem dump
    _GRACEFUL_REAPS = ("stopped by request", "drained for shutdown",
                      "server shutdown", "single_active_backend", "preempted")

    def _reap(self, h: BackendHandle, reason: str = ""):
        """Remove (if current) + terminate one backend. Safe to call from any
        thread; never holds the map lock across the process wait."""
        from localai_tpu import telemetry

        rec = telemetry.flightrec()
        rec.record_event("backend_reaped", model=h.name, reason=reason)
        if not reason.startswith(self._GRACEFUL_REAPS):
            rec.auto_dump(f"backend_reaped:{h.name}")
        with self._lock:
            if self._models.get(h.name) is h:
                del self._models[h.name]
        h.poison(reason)
        h.client.close()
        if h.alive():
            h.proc.terminate()
            try:
                h.proc.wait(timeout=10)
            except subprocess.TimeoutExpired:
                h.proc.kill()  # forced-shutdown escape hatch (process.go:29-43)

    def stop_model(self, name: str) -> bool:
        h = self.get(name)
        if h is None:
            return False
        self._reap(h, reason="stopped by request")
        return True

    def preempt_model(self, name: str, grace: float | None = None) -> bool:
        """Preemption notice (ISSUE 19): SIGTERM the backend so its server
        runs the spill-drain fast-path — live slots freeze into ResumeTokens
        that flush through their open streams — then reap. Unlike
        `drain_model` this does NOT wait for requests to finish: the point
        is to checkpoint them mid-flight."""
        import signal as _signal

        h = self.get(name)
        if h is None:
            return False
        if grace is None:
            grace = getattr(self.app, "preempt_grace", 0.0) or 0.0
        from localai_tpu import telemetry

        telemetry.flightrec().record_event("backend_preempt", model=name,
                                           grace=grace)
        self.events[(name, "preempt")] += 1
        if h.alive():
            h.proc.send_signal(_signal.SIGTERM)
            try:
                # spill-drain budget: the grace window plus headroom for the
                # D2H spills themselves; a wedged child falls through to the
                # reap's terminate/kill escalation
                h.proc.wait(timeout=grace + 30.0)
            except subprocess.TimeoutExpired:
                pass
        self._reap(h, reason="preempted")
        return True

    def drain_model(self, name: str, timeout: float = 30.0) -> bool:
        """Graceful stop: wait for the backend's in-flight requests to finish
        (up to `timeout`), then reap — instead of severing mid-generation."""
        h = self.get(name)
        if h is None:
            return False
        deadline = time.monotonic() + max(timeout, 0.0)
        while h.busy > 0 and time.monotonic() < deadline and h.alive():
            time.sleep(0.05)
        self._reap(h, reason="drained for shutdown")
        return True

    def stop_all(self):
        self._stop.set()
        with self._lock:
            handles = list(self._models.values())
        for h in handles:
            self._reap(h, reason="server shutdown")

    # ------------------------------------------------------------ supervision

    def classify_failure(self, handle: BackendHandle,
                         exc: Exception) -> tuple[bool, Exception]:
        """Turn a request-time failure into (retriable?, translated error).

        Poisoned handle (watchdog/shutdown reap) → the reap reason as a 504,
        never retried: the reaper acted deliberately and a retry would just
        stall again. Dead process → reap + retriable 503 (the next load()
        respawns). Live backend returning UNAVAILABLE → retriable 503.
        Everything else passes through untranslated."""
        code = exc.code() if isinstance(exc, grpc.RpcError) else None
        if handle.poisoned:
            return False, WatchdogReaped(
                f"backend for {handle.name!r} was reaped mid-request "
                f"({handle.poisoned})")
        dead = not handle.alive()
        if not dead and code == grpc.StatusCode.UNAVAILABLE:
            # a severed channel can surface UNAVAILABLE before the child's
            # death is observable (Popen.poll even reports None while
            # another thread holds the wait lock) — give the process table
            # a grace beat before classifying the backend as alive
            deadline = time.monotonic() + 0.5
            while not dead and time.monotonic() < deadline:
                time.sleep(0.05)
                dead = not handle.alive()
        if dead:
            self._reap(handle, reason="died mid-request")
            self.events[(handle.name, "died_midrequest")] += 1
            return True, BackendUnavailable(
                f"backend for {handle.name!r} died mid-request "
                f"(rc={handle.proc.returncode})")
        if code == grpc.StatusCode.UNAVAILABLE:
            self.events[(handle.name, "unavailable_alive")] += 1
            self.breaker(handle.name).record_failure()
            return True, BackendUnavailable(
                f"backend for {handle.name!r} unavailable: "
                f"{exc.details() if hasattr(exc, 'details') else exc}")
        if code == grpc.StatusCode.DEADLINE_EXCEEDED:
            return False, DeadlineExceeded(
                f"backend call for {handle.name!r} exceeded the request "
                f"deadline")
        return False, exc

    def supervised(self, cfg: ModelConfig, op, *, retries: int | None = None):
        """Run `op(handle)` against a live backend, transparently respawning
        and retrying on dead/UNAVAILABLE backends with capped exponential
        backoff — the request-time half of backend supervision. Only safe
        for calls that have produced no client-visible bytes yet (unary RPCs
        and stream OPENS; the HTTP stream bridge enforces the no-bytes rule
        for streams). Busy accounting is owned here: every attempt is
        mark_busy/try/finally mark_idle."""
        if retries is None:
            retries = max(0, getattr(self.app, "retry_budget", 1))
        last: Exception | None = None
        for attempt in range(retries + 1):
            if attempt:
                time.sleep(backoff(attempt))
            rem = resilience.deadline_remaining()
            if rem is not None and rem <= 0:
                # the budget died (possibly mid-retry): a 504 tells the
                # client the truth — their deadline ran out — regardless of
                # what the last backend failure looked like
                raise DeadlineExceeded(
                    "request deadline exhausted before the backend call"
                    + (f" (last failure: {last})" if last else "")) from last
            handle = self.load(cfg)
            handle.mark_busy()
            try:
                return op(handle)
            except grpc.RpcError as e:
                retriable, err = self.classify_failure(handle, e)
                if not retriable or attempt >= retries:
                    raise err from e
                self.events[(cfg.name, "request_retry")] += 1
                last = err
            finally:
                handle.mark_idle()
        raise last  # pragma: no cover - loop always returns or raises

    # ------------------------------------------------------------ watchdog

    def start_watchdog(self, interval: float = 5.0):
        """Kill backends busy or idle past thresholds (watchdog.go:19-49)."""
        if self._watchdog or not (self.app.watchdog_idle_timeout
                                  or self.app.watchdog_busy_timeout):
            return
        self._watchdog = threading.Thread(
            target=self._watchdog_loop, args=(interval,), daemon=True)
        self._watchdog.start()

    def _watchdog_loop(self, interval: float):
        idle_t = self.app.watchdog_idle_timeout
        busy_t = self.app.watchdog_busy_timeout
        while not self._stop.wait(interval):
            now = time.monotonic()
            with self._lock:
                handles = list(self._models.values())
            for h in handles:
                if (busy_t and h.busy > 0
                        and now - h.busy_since > busy_t):
                    print(f"[watchdog] {h.name} busy > {busy_t}s — reaping",
                          flush=True)
                    self.events[(h.name, "watchdog_busy_reap")] += 1
                    # poison BEFORE the channel dies so in-flight requests
                    # fail with the watchdog named, not a raw RpcError
                    self._reap(h, reason=f"busy-watchdog: backend busy "
                                         f"longer than {busy_t:.0f}s")
                elif (idle_t and h.busy == 0
                        and now - h.last_used > idle_t):
                    print(f"[watchdog] {h.name} idle > {idle_t}s — reaping",
                          flush=True)
                    self.events[(h.name, "watchdog_idle_reap")] += 1
                    self._reap(h, reason=f"idle-watchdog: backend idle "
                                         f"longer than {idle_t:.0f}s")
