"""Model lifecycle: spawn backend processes, health-poll, load, reap, watchdog.

The reference's L3 (/root/reference/pkg/model): mutex-guarded model map
(loader.go:22-41), spawn on a free localhost port + health poll + LoadModel
RPC (process.go:93-160, initializers.go:50-154), dead-process reap on cache
hit (loader.go:191-225), busy/idle watchdog (watchdog.go:19-49), single-active
-backend serialization (initializers.go:205-226).
"""
from __future__ import annotations

import os
import socket
import subprocess
import sys
import threading
import time
from dataclasses import dataclass, field

from localai_tpu.backend.client import BackendClient
from localai_tpu.config import AppConfig, ModelConfig


def free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


@dataclass
class BackendHandle:
    name: str
    config: ModelConfig
    proc: subprocess.Popen
    client: BackendClient
    port: int
    busy: int = 0                 # in-flight requests
    last_used: float = field(default_factory=time.monotonic)
    busy_since: float = 0.0
    _lock: threading.Lock = field(default_factory=threading.Lock)

    def alive(self) -> bool:
        return self.proc.poll() is None

    def mark_busy(self):
        with self._lock:
            if self.busy == 0:
                self.busy_since = time.monotonic()
            self.busy += 1

    def mark_idle(self):
        with self._lock:
            self.busy = max(0, self.busy - 1)
            self.last_used = time.monotonic()


class ModelManager:
    """name → running backend process; the control plane's only way to reach
    model compute."""

    def __init__(self, app: AppConfig):
        self.app = app
        self._models: dict[str, BackendHandle] = {}
        self._lock = threading.Lock()
        self._watchdog: threading.Thread | None = None
        self._stop = threading.Event()

    # ------------------------------------------------------------ spawn/load

    def _spawn(self, cfg: ModelConfig) -> BackendHandle:
        port = free_port()
        env = dict(os.environ)
        # child must import localai_tpu regardless of the parent's cwd, and
        # existing PYTHONPATH entries (e.g. a site hook registering the TPU
        # PJRT plugin) must survive — prepend, never replace
        pkg_root = os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))))
        parts = [pkg_root] + [
            p for p in env.get("PYTHONPATH", "").split(os.pathsep) if p]
        env["PYTHONPATH"] = os.pathsep.join(dict.fromkeys(parts))
        # gallery-installed external backend? its run.sh owns the process
        # (reference initializers.go:50-99 — external backends launch from
        # the backends dir); in-tree roles spawn the python module
        external = None
        if self.app.backends_path:
            from localai_tpu.services.backend_gallery import (
                resolve_backend_dir,
            )

            external = resolve_backend_dir(self.app.backends_path,
                                           cfg.backend)
        if external is not None:
            argv = ["/bin/sh", os.path.join(external, "run.sh"),
                    "--addr", f"127.0.0.1:{port}"]
            cwd = external
        else:
            argv = [sys.executable, "-m", "localai_tpu.backend",
                    "--addr", f"127.0.0.1:{port}", "--backend", cfg.backend]
            # inherit the parent's cwd: a relative --models-path must resolve
            # against the launch dir, not the backends dir
            cwd = None
        proc = subprocess.Popen(
            argv,
            env=env,
            cwd=cwd,
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        )
        # tail child output into our log (reference process.go:140-157)
        threading.Thread(target=self._tail, args=(cfg.name, proc),
                         daemon=True).start()
        client = BackendClient(f"127.0.0.1:{port}")
        if not client.wait_ready(attempts=240, sleep=0.5):
            proc.terminate()
            raise RuntimeError(f"backend for {cfg.name} never became healthy")
        return BackendHandle(name=cfg.name, config=cfg, proc=proc,
                             client=client, port=port)

    @staticmethod
    def _tail(name: str, proc: subprocess.Popen):
        for line in proc.stdout or []:
            # stderr, not stdout: tools with a machine-readable stdout
            # contract (bench.py's one-JSON-line output) embed the manager
            print(f"[backend:{name}] {line.rstrip()}", file=sys.stderr,
                  flush=True)

    def _load_rpc(self, handle: BackendHandle):
        cfg = self.app
        m = handle.config
        r = handle.client.load_model(
            model=m.model_dir(cfg.models_path),
            context_size=m.context_size or cfg.context_size,
            parallel=m.parallel or cfg.parallel_requests,
            dtype=m.dtype,
            prefill_buckets=m.prefill_buckets,
            mesh_data=m.mesh.data,
            # per-model YAML mesh wins; else the app-wide --tensor-parallel
            # degree (0 = backend auto-TP over every divisible device)
            mesh_model=m.mesh.model or cfg.tensor_parallel,
            embeddings=m.embeddings or m.backend == "embedding",
            draft_model=(m.draft_model if not m.draft_model
                         or os.path.isabs(m.draft_model)
                         else os.path.join(cfg.models_path, m.draft_model)),
            n_draft=m.n_draft,
            cache_type_key=m.cache_type_k,
            cache_type_value=m.cache_type_v,
            kv_pages=m.kv_pages,
        )
        if not r.success:
            raise RuntimeError(f"LoadModel({m.name}) failed: {r.message}")

    # ------------------------------------------------------------ public api

    def load(self, cfg: ModelConfig) -> BackendHandle:
        """Get-or-start the backend for a model config. Health-rechecks cached
        processes and reaps+respawns dead ones (loader.go:191-225)."""
        with self._lock:
            h = self._models.get(cfg.name)
            if h is not None:
                if h.alive() and h.client.health(timeout=5.0):
                    h.last_used = time.monotonic()
                    return h
                self._reap_locked(h)
            if self.app.single_active_backend:
                for other in list(self._models.values()):
                    self._reap_locked(other)
            h = self._spawn(cfg)
            try:
                self._load_rpc(h)
            except Exception:
                self._reap_locked(h)
                raise
            self._models[cfg.name] = h
            return h

    def get(self, name: str) -> BackendHandle | None:
        with self._lock:
            return self._models.get(name)

    def loaded(self) -> list[str]:
        with self._lock:
            return sorted(self._models)

    def _reap_locked(self, h: BackendHandle):
        self._models.pop(h.name, None)
        h.client.close()
        if h.alive():
            h.proc.terminate()
            try:
                h.proc.wait(timeout=10)
            except subprocess.TimeoutExpired:
                h.proc.kill()  # forced-shutdown escape hatch (process.go:29-43)

    def stop_model(self, name: str) -> bool:
        with self._lock:
            h = self._models.get(name)
            if h is None:
                return False
            self._reap_locked(h)
            return True

    def stop_all(self):
        self._stop.set()
        with self._lock:
            for h in list(self._models.values()):
                self._reap_locked(h)

    # ------------------------------------------------------------ watchdog

    def start_watchdog(self, interval: float = 5.0):
        """Kill backends busy or idle past thresholds (watchdog.go:19-49)."""
        if self._watchdog or not (self.app.watchdog_idle_timeout
                                  or self.app.watchdog_busy_timeout):
            return
        self._watchdog = threading.Thread(
            target=self._watchdog_loop, args=(interval,), daemon=True)
        self._watchdog.start()

    def _watchdog_loop(self, interval: float):
        idle_t = self.app.watchdog_idle_timeout
        busy_t = self.app.watchdog_busy_timeout
        while not self._stop.wait(interval):
            now = time.monotonic()
            with self._lock:
                for h in list(self._models.values()):
                    if (busy_t and h.busy > 0
                            and now - h.busy_since > busy_t):
                        print(f"[watchdog] {h.name} busy > {busy_t}s — reaping",
                              flush=True)
                        self._reap_locked(h)
                    elif (idle_t and h.busy == 0
                            and now - h.last_used > idle_t):
                        print(f"[watchdog] {h.name} idle > {idle_t}s — reaping",
                              flush=True)
                        self._reap_locked(h)
