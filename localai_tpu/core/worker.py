"""Multi-host worker entrypoint — the `local-ai worker` role.

Reference parity: worker_llamacpp.go:66-92 starts an RPC server that lends
its devices to a master llama.cpp instance; grpc-server.cpp:256-278 registers
those remote devices. TPU-native version: every host joins one
jax.distributed job; the model is sharded over the GLOBAL mesh; rank 0 runs
the serving engine + gRPC backend; other ranks replay rank 0's dispatch
stream (parallel/distributed.py) so the SPMD programs stay in lockstep.

Topology flags mirror jax.distributed.initialize: --coordinator host:port,
--num-processes, --process-id. All ranks run the SAME command (different
--process-id), pointing at the SAME model directory.
"""
from __future__ import annotations

import logging

log = logging.getLogger("localai_tpu.worker")


def run_worker(args) -> int:
    from localai_tpu.parallel.distributed import (
        Follower, Replicator, init_distributed,
    )

    import os

    init_distributed(args.coordinator, args.num_processes, args.process_id)
    import jax

    # topology truth comes from the initialized runtime, not the CLI — the
    # LOCALAI_* env path configures jax.distributed without any flags
    rank = jax.process_index()
    coordinator = args.coordinator or os.environ.get("LOCALAI_COORDINATOR")

    from localai_tpu.engine import Engine, EngineConfig
    from localai_tpu.engine.loader import (
        load_config, load_params, load_tokenizer,
    )
    from localai_tpu.models.llama import max_model_axis
    from localai_tpu.parallel.mesh import MeshConfig, build_mesh

    n_proc = jax.process_count()
    devices = jax.devices()
    cfg = load_config(args.model, dtype=args.dtype or None)
    if args.mesh_data or args.mesh_model:
        data = args.mesh_data or 1
        model = args.mesh_model or (len(devices) // data)
    else:
        model = max_model_axis(cfg, len(devices))
        data = len(devices) // model
    mesh = build_mesh(MeshConfig(data=data, model=model),
                      devices[: data * model])
    log.info("rank %d/%d: %d global devices, mesh data=%d model=%d",
             rank, n_proc, len(devices), data, model)

    params = load_params(args.model, cfg, dtype=args.dtype or None, mesh=mesh)
    tok = load_tokenizer(args.model)
    context = args.context_size or min(2048, cfg.max_position)
    chunk = min(512, context)
    buckets = tuple(b for b in (64, 256, 512) if b <= chunk) or (chunk,)

    replicator = None
    if rank == 0 and n_proc > 1:
        replicator = Replicator(args.replicate_port, n_proc - 1,
                                token=coordinator)

    eng = Engine(cfg, params, tok, EngineConfig(
        max_slots=args.parallel, max_context=context,
        prefill_buckets=buckets, prefill_chunk=chunk, mesh=mesh,
        replicator=replicator,
    ))

    if rank == 0:
        if replicator is not None:
            log.info("waiting for %d follower(s) on port %d...",
                     n_proc - 1, replicator.port)
            replicator.wait_for_followers()
        from localai_tpu.backend.llm import LLMServicer
        from localai_tpu.backend.server import serve_preloaded

        eng.start()
        servicer = LLMServicer(preloaded=(eng, cfg, tok, args.model))
        try:
            return serve_preloaded(args.addr, servicer)
        finally:
            if replicator is not None:
                replicator.close()
    else:
        host = (coordinator or "127.0.0.1").rsplit(":", 1)[0]
        chan = Follower(f"{host}:{args.replicate_port}", token=coordinator)
        log.info("rank %d following %s:%d", rank, host, args.replicate_port)
        eng.follow(chan)
        chan.close()
        return 0
