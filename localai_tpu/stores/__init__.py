"""Vector store: Python wrapper over the native C++ cosine-top-k store
(reference client role: /root/reference/pkg/store/client.go:15-130)."""
from __future__ import annotations

import ctypes
import threading

import numpy as np

from localai_tpu.native import build_and_load


def _lib():
    lib = build_and_load("store")
    lib.st_new.restype = ctypes.c_void_p
    lib.st_new.argtypes = [ctypes.c_int]
    lib.st_free.argtypes = [ctypes.c_void_p]
    lib.st_count.restype = ctypes.c_int
    lib.st_count.argtypes = [ctypes.c_void_p]
    lib.st_dim.restype = ctypes.c_int
    lib.st_dim.argtypes = [ctypes.c_void_p]
    f32p = ctypes.POINTER(ctypes.c_float)
    u8p = ctypes.POINTER(ctypes.c_uint8)
    i64p = ctypes.POINTER(ctypes.c_int64)
    i32p = ctypes.POINTER(ctypes.c_int)
    lib.st_set.restype = ctypes.c_int
    lib.st_set.argtypes = [ctypes.c_void_p, ctypes.c_int, f32p, u8p, i64p]
    lib.st_delete.restype = ctypes.c_int
    lib.st_delete.argtypes = [ctypes.c_void_p, ctypes.c_int, f32p]
    lib.st_lookup.restype = ctypes.c_int
    lib.st_lookup.argtypes = [ctypes.c_void_p, f32p]
    lib.st_value_len.restype = ctypes.c_int64
    lib.st_value_len.argtypes = [ctypes.c_void_p, ctypes.c_int]
    lib.st_value_copy.argtypes = [ctypes.c_void_p, ctypes.c_int, u8p]
    lib.st_key_copy.argtypes = [ctypes.c_void_p, ctypes.c_int, f32p]
    lib.st_find.restype = ctypes.c_int
    lib.st_find.argtypes = [ctypes.c_void_p, f32p, ctypes.c_int, i32p, f32p]
    return lib


def _f32(a) -> np.ndarray:
    return np.ascontiguousarray(a, dtype=np.float32)


class LocalStore:
    def __init__(self, dim: int):
        self.dim = dim
        self._lib = _lib()
        self._s = self._lib.st_new(dim)
        self._lock = threading.Lock()

    def _keys_ptr(self, keys: np.ndarray):
        keys = _f32(keys).reshape(-1, self.dim)
        return keys, keys.ctypes.data_as(ctypes.POINTER(ctypes.c_float))

    def set(self, keys, values: list[bytes]):
        keys, kp = self._keys_ptr(keys)
        if len(values) != keys.shape[0]:
            raise ValueError("keys/values length mismatch")
        blob = b"".join(values)
        offsets = np.zeros(len(values) + 1, np.int64)
        np.cumsum([len(v) for v in values], out=offsets[1:])
        with self._lock:
            self._lib.st_set(
                self._s, keys.shape[0], kp,
                ctypes.cast(ctypes.create_string_buffer(blob, len(blob) or 1),
                            ctypes.POINTER(ctypes.c_uint8)),
                offsets.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)))

    def get(self, keys) -> list[bytes | None]:
        keys, _ = self._keys_ptr(keys)
        out = []
        with self._lock:
            for row_key in keys:
                kp = row_key.ctypes.data_as(ctypes.POINTER(ctypes.c_float))
                idx = self._lib.st_lookup(self._s, kp)
                if idx < 0:
                    out.append(None)
                    continue
                n = self._lib.st_value_len(self._s, idx)
                buf = (ctypes.c_uint8 * max(n, 1))()
                self._lib.st_value_copy(self._s, idx, buf)
                out.append(bytes(buf[:n]))
        return out

    def delete(self, keys) -> int:
        keys, kp = self._keys_ptr(keys)
        with self._lock:
            return self._lib.st_delete(self._s, keys.shape[0], kp)

    def find(self, key, top_k: int):
        """→ (keys [m, dim] f32, values list[bytes], similarities [m] f32)"""
        key = _f32(key).reshape(self.dim)
        rows = (ctypes.c_int * top_k)()
        sims = (ctypes.c_float * top_k)()
        with self._lock:
            m = self._lib.st_find(
                self._s, key.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
                top_k, rows, sims)
            keys_out = np.zeros((m, self.dim), np.float32)
            vals = []
            for i in range(m):
                self._lib.st_key_copy(
                    self._s, rows[i],
                    keys_out[i].ctypes.data_as(ctypes.POINTER(ctypes.c_float)))
                n = self._lib.st_value_len(self._s, rows[i])
                buf = (ctypes.c_uint8 * max(n, 1))()
                self._lib.st_value_copy(self._s, rows[i], buf)
                vals.append(bytes(buf[:n]))
        return keys_out, vals, np.array(sims[:m], np.float32)

    def __len__(self):
        with self._lock:
            return self._lib.st_count(self._s)

    def __del__(self):
        if getattr(self, "_s", None):
            self._lib.st_free(self._s)
            self._s = None
