"""Explorer: public dashboard of federated serving networks.

Reference: /root/reference/core/explorer/{database.go,discovery.go} + the
explorer CLI (core/cli/explorer.go) and routes
(core/http/routes/explorer.go: GET /, POST /network/add, GET /networks).

The reference crawls libp2p networks by token; this build's federation layer
is HTTP (federation/__init__.py — the libp2p overlay is a documented
exclusion), so a "network" here is a federated load-balancer endpoint. The
discovery server polls each network's `/federation/workers` to refresh its
cluster/worker table and evicts networks after N consecutive failures —
the same lifecycle as the reference's DiscoveryServer (discovery.go:26-43,
failedToken)."""
from __future__ import annotations

import dataclasses
import fcntl
import json
import os
import threading
import time
import urllib.request


@dataclasses.dataclass
class NetworkData:
    name: str = ""
    description: str = ""
    url: str = ""                 # federated LB endpoint
    clusters: list = dataclasses.field(default_factory=list)
    failures: int = 0

    def to_dict(self):
        return dataclasses.asdict(self)


class Database:
    """JSON file database with advisory file locking (database.go role:
    safe across processes via flock, across threads via a mutex)."""

    def __init__(self, path: str):
        self.path = path
        self._lock = threading.Lock()
        self._data: dict[str, NetworkData] = {}
        self._load()

    def _flock(self):
        lock = open(self.path + ".lock", "w")
        fcntl.flock(lock, fcntl.LOCK_EX)
        return lock

    def _load(self):
        if not os.path.exists(self.path):
            return
        with open(self.path) as f:
            try:
                raw = json.load(f)
            except ValueError:
                raw = {}
        known = {f.name for f in dataclasses.fields(NetworkData)}
        self._data = {
            k: NetworkData(**{kk: vv for kk, vv in v.items() if kk in known})
            for k, v in raw.items()}

    def _save(self):
        tmp = self.path + ".tmp"
        with open(tmp, "w") as f:
            json.dump({k: v.to_dict() for k, v in self._data.items()}, f,
                      indent=1)
        os.replace(tmp, self.path)

    def get(self, token: str) -> NetworkData | None:
        lk = self._flock()
        try:
            with self._lock:
                self._load()
                return self._data.get(token)
        finally:
            lk.close()

    def set(self, token: str, nd: NetworkData):
        lk = self._flock()
        try:
            with self._lock:
                self._load()
                self._data[token] = nd
                self._save()
        finally:
            lk.close()

    def delete(self, token: str):
        lk = self._flock()
        try:
            with self._lock:
                self._load()
                self._data.pop(token, None)
                self._save()
        finally:
            lk.close()

    def token_list(self) -> list[str]:
        lk = self._flock()
        try:
            with self._lock:
                self._load()
                return sorted(self._data)
        finally:
            lk.close()

    def update(self, token: str, fn):
        """Atomic read-modify-write: `fn(NetworkData|None) -> NetworkData|None`
        runs under both locks (None return deletes). get()+set() would drop
        concurrent writers' updates between the two lock windows."""
        lk = self._flock()
        try:
            with self._lock:
                self._load()
                nd = fn(self._data.get(token))
                if nd is None:
                    self._data.pop(token, None)
                else:
                    self._data[token] = nd
                self._save()
        finally:
            lk.close()


class DiscoveryServer:
    """Keeps the db in sync with live network state (discovery.go:26-43):
    polls each network's /federation/workers; evicts after `threshold`
    consecutive failures."""

    def __init__(self, db: Database, interval: float = 50.0,
                 threshold: int = 3, timeout: float = 5.0):
        self.db = db
        self.interval = interval
        self.threshold = threshold
        self.timeout = timeout
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    def sync_once(self):
        for token in self.db.token_list():
            nd = self.db.get(token)
            if nd is None:
                continue
            try:
                with urllib.request.urlopen(
                        nd.url.rstrip("/") + "/federation/workers",
                        timeout=self.timeout) as r:
                    workers = json.load(r)
                clusters = [{
                    "workers": [w.get("url", "") for w in workers],
                    "type": "federated",
                    "network_id": token,
                }]

                def ok(cur, clusters=clusters):
                    if cur is None:
                        return None
                    cur.clusters = clusters
                    cur.failures = 0
                    return cur

                self.db.update(token, ok)
            except Exception:
                def fail(cur, threshold=self.threshold):
                    if cur is None:
                        return None
                    cur.failures += 1
                    return None if cur.failures >= threshold else cur

                self.db.update(token, fail)

    def start(self):
        if self._thread:
            return

        def loop():
            while not self._stop.wait(self.interval):
                self.sync_once()

        self._thread = threading.Thread(target=loop, daemon=True)
        self._thread.start()

    def stop(self):
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=5)
            self._thread = None


_DASHBOARD = """<!doctype html>
<html><head><title>LocalAI-TPU Explorer</title><style>
body{font-family:system-ui;margin:2rem;background:#0b1020;color:#e6e8ef}
h1{color:#7aa2ff} .net{border:1px solid #2a3350;border-radius:8px;
padding:1rem;margin:.6rem 0;background:#121a33}
.small{color:#8b93a7;font-size:.85rem} code{color:#9ece6a}
input,textarea{width:100%;margin:.2rem 0;background:#1a2342;border:1px solid
#2a3350;color:#e6e8ef;border-radius:4px;padding:.4rem}
button{background:#7aa2ff;border:0;border-radius:4px;padding:.5rem 1rem;
margin-top:.4rem}</style></head>
<body><h1>Federated networks</h1><div id=nets></div>
<h2>Register a network</h2>
<input id=name placeholder=name><input id=url placeholder=http://lb:9090>
<textarea id=desc placeholder=description></textarea>
<button onclick="add()">Add</button>
<script>
// network fields are untrusted (public POST endpoint): build DOM nodes and
// assign via textContent only — never innerHTML
function el(tag,cls,text){const e=document.createElement(tag);
 if(cls)e.className=cls;if(text!==undefined)e.textContent=text;return e;}
async function refresh(){
 const r=await fetch('networks');const nets=await r.json();
 const box=document.getElementById('nets');box.replaceChildren();
 for(const n of nets){
  const d=el('div','net');
  d.append(el('b','',n.name),' ',el('code','',n.url),
   document.createElement('br'),el('span','',n.description));
  const w=(n.clusters||[]).map(c=>c.workers.length+' workers').join(', ');
  d.append(el('div','small',(w||'no data yet')+' — failures: '+n.failures));
  box.append(d);
 }
}
async function add(){
 const r=await fetch('network/add',{method:'POST',headers:{'Content-Type':
 'application/json'},body:JSON.stringify({name:name.value,url:url.value,
 description:desc.value})});
 if(!r.ok){alert('registration failed ('+r.status+'): '+await r.text()+
 (r.status==401?' — this explorer requires signed registration '+
 '(LOCALAI_FEDERATION_TOKEN); use the API with an X-LocalAI-Federation '+
 'header':''));return;}
 refresh();
}
refresh();setInterval(refresh,10000);
</script></body></html>"""


def build_explorer_app(db: Database, register_token: str = ""):
    """aiohttp app with the reference's explorer routes
    (routes/explorer.go:10-12). `register_token` gates /network/add behind a
    shared-token HMAC signature (federation/auth.py) so arbitrary parties
    cannot pollute the flock registry."""
    from aiohttp import web

    async def dashboard(request):
        return web.Response(text=_DASHBOARD, content_type="text/html")

    async def networks(request):
        out = []
        for token in db.token_list():
            nd = db.get(token)
            if nd:
                d = nd.to_dict()
                d["token"] = token
                out.append(d)
        return web.json_response(out)

    async def add_network(request):
        if register_token:
            from localai_tpu.federation.auth import HEADER, verify

            raw = await request.read()
            if not verify(register_token, request.headers.get(HEADER),
                          request.method, request.path_qs, raw):
                raise web.HTTPUnauthorized(text="registration token required")
        body = await request.json()
        url = (body.get("url") or body.get("token") or "").strip()
        if not url:
            raise web.HTTPBadRequest(text="url required")
        token = body.get("token") or url
        if db.get(token) is not None:
            raise web.HTTPConflict(text="network already registered")
        db.set(token, NetworkData(
            name=body.get("name", ""), url=url,
            description=body.get("description", "")))
        return web.json_response({"ok": True, "token": token})

    app = web.Application()
    app.router.add_get("/", dashboard)
    app.router.add_get("/networks", networks)
    app.router.add_post("/network/add", add_network)
    return app


def run_explorer(args) -> int:
    """CLI `explorer` (reference core/cli/explorer.go)."""
    import asyncio

    from aiohttp import web

    db = Database(getattr(args, "pool_database", "explorer.json"))
    ds = None
    if getattr(args, "with_sync", False) or getattr(args, "only_sync", False):
        ds = DiscoveryServer(db,
                             interval=float(getattr(args, "interval", 50.0)),
                             threshold=int(getattr(args, "threshold", 3)))
    if getattr(args, "only_sync", False):
        while True:
            ds.sync_once()
            time.sleep(ds.interval)
    if ds:
        ds.start()
    host, _, port = getattr(args, "address", "127.0.0.1:8509").rpartition(":")

    async def serve():
        import os

        runner = web.AppRunner(build_explorer_app(
            db, register_token=os.environ.get(
                "LOCALAI_FEDERATION_TOKEN", "")))
        await runner.setup()
        site = web.TCPSite(runner, host or "127.0.0.1", int(port))
        await site.start()
        print(f"explorer on {host or '127.0.0.1'}:{port}", flush=True)
        while True:
            await asyncio.sleep(3600)

    try:
        asyncio.run(serve())
    except KeyboardInterrupt:
        pass
    finally:
        if ds:
            ds.stop()
    return 0
