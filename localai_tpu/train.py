"""Minimal training step over the flagship decoder.

The reference is inference-only (SURVEY §5 "Checkpoint / resume"), but the
TPU-native framework keeps a real train step for fine-tuning and for the
driver's multi-chip dry-run: data-parallel batch over the 'data' mesh axis,
Megatron-style tensor parallelism over 'model' (param_specs), XLA inserting
the psum/all-gather collectives.
"""
from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import optax

from localai_tpu.models.llama import LlamaConfig, forward_train
from localai_tpu.parallel.mesh import constrain
from jax.sharding import PartitionSpec as P


def causal_lm_loss(params, cfg: LlamaConfig, tokens):
    """Next-token cross-entropy over a [B, S] batch (mean over real tokens)."""
    tokens = constrain(tokens, P("data", None))
    logits = forward_train(params, cfg, tokens[:, :-1])
    targets = tokens[:, 1:]
    logp = jax.nn.log_softmax(logits, axis=-1)
    nll = -jnp.take_along_axis(logp, targets[..., None], axis=-1)[..., 0]
    return nll.mean()


def make_train_step(cfg: LlamaConfig, optimizer: optax.GradientTransformation):
    """Returns train_step(params, opt_state, tokens) -> (params, opt_state, loss).
    jit it under an active mesh; params sharded per param_specs; batch on 'data'."""

    def train_step(params, opt_state, tokens):
        loss, grads = jax.value_and_grad(causal_lm_loss)(params, cfg, tokens)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss

    return train_step
