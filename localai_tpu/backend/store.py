"""Stores backend servicer — the local-store backend process
(/root/reference/backend/go/local-store/store.go Set/Get/Delete/Find RPCs)
over the native C++ store."""
from __future__ import annotations

import grpc

from localai_tpu.backend import pb
from localai_tpu.backend.base import BackendServicer


class StoreServicer(BackendServicer):
    def __init__(self):
        self.store = None

    def LoadModel(self, request, context):
        # store needs no model; dim fixed on first Set
        return pb.Result(success=True, message="ok")

    def _ensure(self, dim: int, context):
        from localai_tpu.stores import LocalStore

        if self.store is None:
            self.store = LocalStore(dim)
        elif self.store.dim != dim:
            context.abort(grpc.StatusCode.INVALID_ARGUMENT,
                          f"key dim {dim} != store dim {self.store.dim}")
        return self.store

    def StoresSet(self, request, context):
        if len(request.keys) != len(request.values):
            context.abort(grpc.StatusCode.INVALID_ARGUMENT,
                          "keys/values length mismatch")
        if not request.keys:
            return pb.Result(success=True)
        st = self._ensure(len(request.keys[0].floats), context)
        import numpy as np

        keys = np.array([list(k.floats) for k in request.keys], np.float32)
        st.set(keys, [v.bytes for v in request.values])
        return pb.Result(success=True)

    def StoresDelete(self, request, context):
        if not request.keys or self.store is None:
            return pb.Result(success=True)
        import numpy as np

        keys = np.array([list(k.floats) for k in request.keys], np.float32)
        self.store.delete(keys)
        return pb.Result(success=True)

    def StoresGet(self, request, context):
        resp = pb.StoresGetResult()
        if self.store is None:
            return resp
        import numpy as np

        keys = np.array([list(k.floats) for k in request.keys], np.float32)
        for k, v in zip(request.keys, self.store.get(keys)):
            if v is None:
                continue
            resp.keys.append(k)
            resp.values.append(pb.StoresValue(bytes=v))
        return resp

    def StoresFind(self, request, context):
        resp = pb.StoresFindResult()
        if self.store is None:
            return resp
        keys, vals, sims = self.store.find(
            list(request.key.floats), max(request.top_k, 1))
        for i in range(len(vals)):
            resp.keys.append(pb.StoresKey(floats=keys[i].tolist()))
            resp.values.append(pb.StoresValue(bytes=vals[i]))
            resp.similarities.append(float(sims[i]))
        return resp
