"""Image/video generation backend servicer (reference: diffusers backend
GenerateImage/GenerateVideo, /root/reference/backend/python/diffusers/
backend.py; stablediffusion-ggml gosd.cpp)."""
from __future__ import annotations

import threading

import grpc

from localai_tpu.backend import pb
from localai_tpu.backend.base import BackendServicer


class ImageServicer(BackendServicer):
    def __init__(self):
        self.model = None
        self._lock = threading.Lock()

    def LoadModel(self, request, context):
        with self._lock:
            if self.model is None:
                from localai_tpu.models.diffusion import DiffusionModel

                self.model = DiffusionModel(seed=request.seed or 0)
            return pb.Result(success=True, message="ok")

    def GenerateImage(self, request, context):
        if self.model is None:
            context.abort(grpc.StatusCode.FAILED_PRECONDITION, "no model")
        if not request.dst:
            context.abort(grpc.StatusCode.INVALID_ARGUMENT, "dst required")
        self.model.generate_image(
            request.positive_prompt or "",
            request.dst,
            width=request.width or 256,
            height=request.height or 256,
            steps=request.step or 12,
            seed=request.seed or 0,
        )
        return pb.Result(success=True, message=request.dst)

    def GenerateVideo(self, request, context):
        if self.model is None:
            context.abort(grpc.StatusCode.FAILED_PRECONDITION, "no model")
        if not request.dst:
            context.abort(grpc.StatusCode.INVALID_ARGUMENT, "dst required")
        self.model.generate_video(
            request.prompt or "",
            request.dst,
            num_frames=request.num_frames or 8,
            fps=request.fps or 4,
            seed=request.seed or 0,
        )
        return pb.Result(success=True, message=request.dst)
