"""Image/video generation backend servicer (reference: diffusers backend
GenerateImage/GenerateVideo, /root/reference/backend/python/diffusers/
backend.py; stablediffusion-ggml gosd.cpp)."""
from __future__ import annotations

import threading

import grpc

from localai_tpu.backend import pb
from localai_tpu.backend.base import BackendServicer


class _LatentWrapper:
    """LatentDiffusion → the DiffusionModel file-output surface. With a
    motion adapter (`video` = models/video_diffusion.VideoDiffusion) video
    requests run the TEMPORAL pipeline — frames denoise jointly under the
    motion modules — instead of the per-frame fallback."""

    def __init__(self, pipe, video=None):
        self.pipe = pipe
        self.video = video

    def generate_image(self, prompt, dst, *, negative_prompt="", width=512,
                       height=512, steps=20, seed=0):
        from PIL import Image

        arr = self.pipe.txt2img(prompt, negative_prompt=negative_prompt,
                                width=width, height=height, steps=steps,
                                seed=seed)
        Image.fromarray(arr).save(dst)
        return dst

    def generate_video(self, prompt, dst, *, num_frames=8, fps=4,
                       width=128, height=128, steps=8, seed=0):
        from PIL import Image

        if self.video is not None:
            arr = self.video.txt2video(prompt, width=width, height=height,
                                       num_frames=num_frames, steps=steps,
                                       seed=seed)
            frames = [Image.fromarray(f) for f in arr]
        else:
            # no motion adapter: per-frame sampling (last-resort fallback)
            cond, uncond = self.pipe.encode_prompts(prompt)
            frames = [Image.fromarray(self.pipe.sample(
                cond, uncond, width=width, height=height, steps=steps,
                seed=seed + f)) for f in range(num_frames)]
        frames[0].save(dst, save_all=True, append_images=frames[1:],
                       duration=int(1000 / fps), loop=0)
        return dst


class _FluxWrapper:
    """FluxPipeline → the DiffusionModel file-output surface. Flux is a
    guidance-distilled rectified-flow model: no negative prompt, few steps."""

    def __init__(self, pipe):
        self.pipe = pipe

    def generate_image(self, prompt, dst, *, negative_prompt="", width=512,
                       height=512, steps=4, seed=0):
        from PIL import Image

        arr = self.pipe.txt2img(prompt, width=width, height=height,
                                steps=min(steps, 8), seed=seed)
        Image.fromarray(arr).save(dst)
        return dst

    def generate_video(self, prompt, dst, *, num_frames=8, fps=4,
                       width=128, height=128, steps=4, seed=0):
        from PIL import Image

        frames = [Image.fromarray(self.pipe.txt2img(
            prompt, width=width, height=height, steps=min(steps, 8),
            seed=seed + f)) for f in range(num_frames)]
        frames[0].save(dst, save_all=True, append_images=frames[1:],
                       duration=int(1000 / fps), loop=0)
        return dst


class ImageServicer(BackendServicer):
    def __init__(self):
        self.model = None
        self._lock = threading.Lock()

    def LoadModel(self, request, context):
        import os

        with self._lock:
            if self.model is None:
                model_dir = request.model
                if request.model_path and not os.path.isdir(model_dir):
                    model_dir = os.path.join(request.model_path,
                                             request.model)
                from localai_tpu.models.latent_diffusion import (
                    is_diffusers_checkpoint,
                )

                from localai_tpu.models.flux import is_flux_checkpoint

                try:
                    if model_dir and is_flux_checkpoint(model_dir):
                        from localai_tpu.models.flux import FluxPipeline

                        self.model = _FluxWrapper(FluxPipeline(
                            model_dir, dtype=request.dtype or "float32"))
                    elif model_dir and is_diffusers_checkpoint(model_dir):
                        # real SD-class checkpoint (diffusers layout); a
                        # motion_adapter/ subdir upgrades video to the
                        # temporal AnimateDiff-style pipeline
                        from localai_tpu.models.latent_diffusion import (
                            LatentDiffusion,
                        )
                        from localai_tpu.models.video_diffusion import (
                            VideoDiffusion, is_video_checkpoint,
                        )

                        if is_video_checkpoint(model_dir):
                            vid = VideoDiffusion(
                                model_dir, dtype=request.dtype or "float32")
                            self.model = _LatentWrapper(vid.base, vid)
                        else:
                            self.model = _LatentWrapper(LatentDiffusion(
                                model_dir, dtype=request.dtype or "float32"))
                    elif model_dir and os.path.isdir(model_dir):
                        # an explicit checkpoint that is NOT a diffusers
                        # layout must fail loudly, never silently produce
                        # random-weights noise
                        return pb.Result(
                            success=False,
                            message=f"{model_dir} is not a diffusers-layout "
                                    f"checkpoint (no model_index.json)")
                    else:
                        from localai_tpu.models.diffusion import DiffusionModel

                        self.model = DiffusionModel(seed=request.seed or 0)
                except Exception as e:
                    return pb.Result(success=False,
                                     message=f"{type(e).__name__}: {e}")
            return pb.Result(success=True, message="ok")

    def GenerateImage(self, request, context):
        if self.model is None:
            context.abort(grpc.StatusCode.FAILED_PRECONDITION, "no model")
        if not request.dst:
            context.abort(grpc.StatusCode.INVALID_ARGUMENT, "dst required")
        self.model.generate_image(
            request.positive_prompt or "",
            request.dst,
            negative_prompt=request.negative_prompt or "",
            width=request.width or 256,
            height=request.height or 256,
            steps=request.step or 12,
            seed=request.seed or 0,
        )
        return pb.Result(success=True, message=request.dst)

    def GenerateVideo(self, request, context):
        if self.model is None:
            context.abort(grpc.StatusCode.FAILED_PRECONDITION, "no model")
        if not request.dst:
            context.abort(grpc.StatusCode.INVALID_ARGUMENT, "dst required")
        self.model.generate_video(
            request.prompt or "",
            request.dst,
            num_frames=request.num_frames or 8,
            fps=request.fps or 4,
            seed=request.seed or 0,
        )
        return pb.Result(success=True, message=request.dst)
