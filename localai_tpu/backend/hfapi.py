"""Hugging Face Inference-API passthrough backend — no local compute.

Reference: /root/reference/backend/go/huggingface/langchain.go — LoadModel
takes the HF model id + HUGGINGFACEHUB_API_TOKEN, Predict posts the prompt to
the hosted Inference API. PredictStream replays the full completion as one
chunk (the reference does the same; the hosted API is not streamed).

The endpoint base is overridable via ModelOptions.options JSON
({"endpoint": ...}) — used by tests (zero-egress image) and for
Inference-Endpoints deployments.
"""
from __future__ import annotations

import json
import os
import threading
import urllib.request

import grpc

from localai_tpu.backend import pb
from localai_tpu.backend.base import BackendServicer

DEFAULT_ENDPOINT = "https://api-inference.huggingface.co/models"


class HFApiServicer(BackendServicer):
    def __init__(self):
        self.model = ""
        self.token = ""
        self.endpoint = DEFAULT_ENDPOINT
        self._state = pb.StatusResponse.UNINITIALIZED
        self._lock = threading.Lock()

    def LoadModel(self, request, context):
        with self._lock:
            opts = {}
            if request.options:
                try:
                    opts = json.loads(request.options)
                except ValueError as e:
                    # a typo'd options blob must not silently fall back to
                    # the public endpoint with the env token
                    self._state = pb.StatusResponse.ERROR
                    return pb.Result(
                        success=False,
                        message=f"invalid options JSON: {e}")
            token = (opts.get("token")
                     or os.environ.get("HUGGINGFACEHUB_API_TOKEN", ""))
            if not token:
                self._state = pb.StatusResponse.ERROR
                return pb.Result(
                    success=False,
                    message="no huggingface token provided "
                            "(HUGGINGFACEHUB_API_TOKEN)")
            self.model = request.model
            self.token = token
            self.endpoint = opts.get("endpoint", DEFAULT_ENDPOINT).rstrip("/")
            self._state = pb.StatusResponse.READY
            return pb.Result(success=True, message="ok")

    def _predict_text(self, request) -> str:
        params: dict = {"return_full_text": False}
        if request.tokens:
            params["max_new_tokens"] = request.tokens
        if request.temperature:
            params["temperature"] = request.temperature
        if request.top_k:
            params["top_k"] = request.top_k
        if request.top_p:
            params["top_p"] = request.top_p
        body = json.dumps({"inputs": request.prompt,
                           "parameters": params}).encode()
        req = urllib.request.Request(
            f"{self.endpoint}/{self.model}", data=body,
            headers={"Authorization": f"Bearer {self.token}",
                     "Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=600) as r:
            out = json.load(r)
        if isinstance(out, list) and out and "generated_text" in out[0]:
            text = out[0]["generated_text"]
        elif isinstance(out, dict) and "generated_text" in out:
            text = out["generated_text"]
        else:
            raise ValueError(f"unexpected Inference API reply: {out!r}")
        for stop in request.stop_prompts:
            i = text.find(stop)
            if i != -1:
                text = text[:i]
        return text

    def _require_loaded(self, context):
        if not self.model:
            context.abort(grpc.StatusCode.FAILED_PRECONDITION,
                          "no model loaded (call LoadModel first)")

    def Predict(self, request, context):
        self._require_loaded(context)
        try:
            text = self._predict_text(request)
        except Exception as e:
            context.abort(grpc.StatusCode.UNAVAILABLE,
                          f"{type(e).__name__}: {e}")
        return pb.Reply(message=text.encode(), finish_reason="stop")

    def PredictStream(self, request, context):
        self._require_loaded(context)
        try:
            text = self._predict_text(request)
        except Exception as e:
            context.abort(grpc.StatusCode.UNAVAILABLE,
                          f"{type(e).__name__}: {e}")
        yield pb.Reply(message=text.encode(), finish_reason="stop")

    def Status(self, request, context):
        return pb.StatusResponse(state=self._state)
