"""Base servicer + descriptor-driven gRPC registration.

grpc_tools isn't in this image, so instead of generated service stubs the
handlers are derived from the proto DESCRIPTOR at runtime — same wire format,
no codegen. The Base class returns UNIMPLEMENTED for every RPC so each backend
role overrides only what it supports (the capability-negotiation idiom,
reference /root/reference/pkg/grpc/base/base.go:16-124).
"""
from __future__ import annotations

import grpc

from localai_tpu.backend import pb


def _unimplemented(name):
    def handler(self, request, context):
        context.abort(grpc.StatusCode.UNIMPLEMENTED,
                      f"{name} not implemented by this backend")

    handler.__name__ = name
    return handler


class BackendServicer:
    """Override the RPCs your backend supports; the rest stay UNIMPLEMENTED."""

    def Health(self, request, context):
        return pb.Reply(message=b"OK")

    def GetTrace(self, request, context):
        """Telemetry export (every role): this process's recorded spans as
        Chrome-trace events in Reply.message JSON. Roles with a device-step
        profiler (llm) override to add the stage breakdown."""
        import json
        import os

        from localai_tpu import telemetry

        return pb.Reply(message=json.dumps({
            "spans": telemetry.chrome_events(),
            "profile": {},
            "pid": os.getpid(),
        }).encode())


for _m in pb.SERVICE.methods:
    if not hasattr(BackendServicer, _m.name):
        setattr(BackendServicer, _m.name, _unimplemented(_m.name))


def add_backend_servicer(server: grpc.Server, servicer: BackendServicer):
    """Register `servicer` under the Backend service using generic handlers."""
    sym = pb._pb2  # message classes by name

    handlers = {}
    for m in pb.SERVICE.methods:
        req_cls = getattr(sym, m.input_type.name)
        resp_cls = getattr(sym, m.output_type.name)
        fn = getattr(servicer, m.name)
        make = (grpc.unary_stream_rpc_method_handler if m.server_streaming
                else grpc.unary_unary_rpc_method_handler)
        handlers[m.name] = make(
            fn,
            request_deserializer=req_cls.FromString,
            response_serializer=resp_cls.SerializeToString,
        )
    server.add_generic_rpc_handlers(
        (grpc.method_handlers_generic_handler(pb.SERVICE_NAME, handlers),)
    )
