"""Object-detection backend servicer — the rfdetr backend role.

Reference: /root/reference/backend/python/rfdetr/backend.py — LoadModel pulls
an RF-DETR model, Detect(src) returns boxes + confidence + class_name. Here
the model is the JAX DETR family (models/detr.py) loading HF
DetrForObjectDetection checkpoints.
"""
from __future__ import annotations

import os
import threading

import grpc

from localai_tpu.backend import pb
from localai_tpu.backend.base import BackendServicer


class DetectServicer(BackendServicer):
    def __init__(self):
        self.detector = None
        self.model_name = ""
        self._state = pb.StatusResponse.UNINITIALIZED
        self._lock = threading.Lock()

    def LoadModel(self, request, context):
        with self._lock:
            if self.detector is not None:
                return pb.Result(success=True, message="already loaded")
            self._state = pb.StatusResponse.BUSY
            try:
                from localai_tpu.models.detr import (
                    Detector, load_detr_config, load_detr_params,
                )

                model_dir = request.model
                if request.model_path and not os.path.isdir(model_dir):
                    model_dir = os.path.join(request.model_path, request.model)
                if not os.path.isdir(model_dir):
                    raise FileNotFoundError(
                        f"model directory not found: {model_dir}")
                cfg = load_detr_config(model_dir)
                params = load_detr_params(model_dir, cfg)
                self.detector = Detector(cfg, params)
                self.model_name = request.model
                self._state = pb.StatusResponse.READY
                return pb.Result(success=True, message="ok")
            except Exception as e:
                self._state = pb.StatusResponse.ERROR
                return pb.Result(success=False,
                                 message=f"{type(e).__name__}: {e}")

    def Detect(self, request, context):
        if self.detector is None:
            context.abort(grpc.StatusCode.FAILED_PRECONDITION,
                          "no model loaded (call LoadModel first)")
        if not request.src or not os.path.isfile(request.src):
            context.abort(grpc.StatusCode.INVALID_ARGUMENT,
                          f"src is not a readable file: {request.src!r}")
        try:
            dets = self.detector.detect(request.src)
        except Exception as e:
            context.abort(grpc.StatusCode.INTERNAL,
                          f"{type(e).__name__}: {e}")
        return pb.DetectResponse(detections=[
            pb.Detection(x=d.x, y=d.y, width=d.width, height=d.height,
                         confidence=d.confidence, class_name=d.class_name)
            for d in dets])

    def Status(self, request, context):
        return pb.StatusResponse(state=self._state)
