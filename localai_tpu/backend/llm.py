"""The flagship LLM backend servicer — the llama.cpp-grpc-server role
(/root/reference/backend/cpp/llama-cpp/grpc-server.cpp:505,571,1003,1373,1552),
re-built over the TPU engine: LoadModel reads HF safetensors into (optionally
mesh-sharded) jax.Arrays, Predict/PredictStream drive the continuous-batching
Engine, Embedding runs the bucketed pooled encoder.
"""
from __future__ import annotations

import json
import os
import resource
import threading
import time

import grpc

from localai_tpu import telemetry
from localai_tpu.backend import pb
from localai_tpu.backend.base import BackendServicer
from localai_tpu.backend.client import REQUEST_ID_KEY
from localai_tpu.ops.sampling import SamplingParams
from localai_tpu.testing import faults


def _inject_faults(context):
    """Chaos-harness hooks (LOCALAI_FAULT): deterministic gRPC-status faults
    on the generation path. No-ops (one env lookup) in normal serving."""
    if faults.fire("unavailable") is not None:
        context.abort(grpc.StatusCode.UNAVAILABLE,
                      "injected UNAVAILABLE (LOCALAI_FAULT)")
    if faults.fire("deadline") is not None:
        context.abort(grpc.StatusCode.DEADLINE_EXCEEDED,
                      "injected DEADLINE_EXCEEDED (LOCALAI_FAULT)")


def _request_id(context) -> str:
    """The HTTP layer's request id, if the client attached one (metadata
    propagation — backend/client.py _trace_md)."""
    try:
        for k, v in context.invocation_metadata():
            if k == REQUEST_ID_KEY:
                return v
    except Exception:
        pass
    return ""


class LLMServicer(BackendServicer):
    def __init__(self, preloaded=None):
        """`preloaded=(engine, cfg, tok, name)` serves an engine built by the
        caller (the multi-host worker path, core/worker.py) — LoadModel then
        reports already-loaded instead of constructing a second engine."""
        self.engine = None
        self.embedder = None
        self.scorer = None
        self.tok = None
        self.cfg = None
        self.model_name = ""
        self._state = pb.StatusResponse.UNINITIALIZED
        self._load_lock = threading.Lock()
        if preloaded is not None:
            self.engine, self.cfg, self.tok, self.model_name = preloaded
            self._state = pb.StatusResponse.READY

    # ------------------------------------------------------------ lifecycle

    def LoadModel(self, request, context):
        with self._load_lock:
            if self.engine is not None or self.embedder is not None:
                return pb.Result(success=True, message="already loaded")
            self._state = pb.StatusResponse.BUSY
            try:
                # lockdep: allow(lock-blocking) — the load lock serializes
                # the WHOLE load (weights + engine start + warmup compiles +
                # prewarm streams, minutes of blocking): that is its job.
                # It is the backend process's outermost lock (rank 0)
                self._load(request)
                self._state = pb.StatusResponse.READY
                return pb.Result(success=True, message="ok")
            except Exception as e:  # surface load errors to the control plane
                self._state = pb.StatusResponse.ERROR
                return pb.Result(success=False, message=f"{type(e).__name__}: {e}")

    def _load(self, request):
        import jax

        from localai_tpu.engine import Engine, EngineConfig
        from localai_tpu.engine.loader import (
            load_config, load_params, load_tokenizer,
        )
        from localai_tpu.engine.embedder import Embedder
        from localai_tpu.models.llama import max_model_axis
        from localai_tpu.parallel.mesh import MeshConfig, build_mesh

        model_dir = request.model
        if request.model_path and not os.path.exists(model_dir):
            model_dir = os.path.join(request.model_path, request.model)
        if os.path.isfile(model_dir) and model_dir.endswith(".gguf"):
            # GGUF ingestion (reference: llama.cpp serves GGUF natively;
            # here it converts once to the HF layout — services/gguf.py)
            from localai_tpu.services.gguf import resolve_gguf

            model_dir = resolve_gguf(model_dir)
        if not os.path.isdir(model_dir):
            raise FileNotFoundError(f"model directory not found: {model_dir}")

        from localai_tpu.models.bert import is_bert_dir

        if is_bert_dir(model_dir):
            # encoder checkpoint (BertModel/RobertaModel/...): the universal
            # embeddings role (reference transformers backend,
            # backend.py:37,323) — no generation engine, Embedding RPC only
            self._load_bert(request, model_dir)
            return

        cfg = load_config(model_dir, dtype=request.dtype or None)
        devices = jax.devices()
        mesh = None
        if request.mesh_data or request.mesh_model:
            # explicit mesh request: honor it (invalid shapes fail loudly)
            data = request.mesh_data or 1
            model = request.mesh_model or (len(devices) // data)
            mesh = build_mesh(MeshConfig(data=data, model=model),
                              devices[: data * model])
        elif len(devices) > 1:
            # auto-TP over as many devices as the model dims divide into —
            # quantized dtypes included: the loader quantizes per host-read
            # shard under param_specs(qbits=...), so the flagship int8
            # recipe boards the full mesh (a draft model rides the mesh
            # too — sharded when its dims divide the axis, replicated
            # otherwise)
            model = max_model_axis(cfg, len(devices))
            if model > 1:
                mesh = build_mesh(MeshConfig(data=1, model=model),
                                  devices[:model])

        from localai_tpu.ops.kvcache import is_quant_kind

        # normalize exactly like the engine does below: quant in EITHER
        # field means int8 KV
        kv_kind = "int8" if (is_quant_kind(request.cache_type_key)
                             or is_quant_kind(request.cache_type_value)) \
            else ""
        context_size = request.context_size or min(2048, cfg.max_position)

        draft_dir = dcfg = None
        if request.draft_model:
            draft_dir = request.draft_model
            if request.model_path and not os.path.isdir(draft_dir):
                draft_dir = os.path.join(request.model_path, draft_dir)
            dcfg = load_config(draft_dir, dtype=request.dtype or None)

        from localai_tpu.system.memory import estimate

        # per chip: weights shard over the TP ('model') axis only (data
        # replicas hold full copies); the KV cache shards over both axes
        # (kv_cache_spec: slots on 'data', kv heads on 'model')
        shards = 1 if mesh is None else int(
            dict(zip(mesh.axis_names, mesh.devices.shape)).get("model", 1))
        kv_shards = 1 if mesh is None else int(mesh.devices.size)
        est = estimate(cfg, slots=request.parallel or 4,
                       context=context_size,
                       dtype=request.dtype or cfg.dtype,
                       cache_type=kv_kind, draft_cfg=dcfg, shards=shards,
                       kv_shards=kv_shards, kv_pages=request.kv_pages)
        if est.fits is False:
            import logging

            logging.getLogger("localai_tpu").warning(
                "model may not fit HBM: need ~%.1f GiB of %.1f GiB per chip "
                "(weights %.1f + kv %.1f + working %.1f, %d chip(s))",
                est.total_bytes / 2**30, (est.hbm_bytes or 0) / 2**30,
                est.weights_bytes / 2**30, est.kv_cache_bytes / 2**30,
                est.working_bytes / 2**30, shards)

        params = load_params(model_dir, cfg, dtype=request.dtype or None,
                             mesh=mesh)
        tok = load_tokenizer(model_dir)
        # single-shot prefill up to the chunk size; longer prompts prefill in
        # chunk-sized pieces interleaved with running decodes
        chunk = min(512, context_size)
        buckets = tuple(request.prefill_buckets) or tuple(
            b for b in (64, 256, 512) if b <= chunk
        ) or (chunk,)
        draft = None
        if dcfg is not None:
            # speculative decoding (reference DraftModel, backend.proto:218)
            dspecs = None
            if mesh is not None:
                from localai_tpu.models.llama import replicated_specs

                model_ax = int(dict(zip(
                    mesh.axis_names, mesh.devices.shape)).get("model", 1))
                if max_model_axis(dcfg, model_ax) != model_ax:
                    dspecs = replicated_specs(
                        dcfg, qbits={"int8": 8, "q8": 8, "int4": 4,
                                     "q4": 4}.get(request.dtype))
            draft = (dcfg, load_params(draft_dir, dcfg,
                                       dtype=request.dtype or None,
                                       mesh=mesh, specs=dspecs))
        # one storage kind for both K and V (quantize when either side asks;
        # the reference allows split k/v types — grpc-server.cpp:236-251)
        cache_type = kv_kind
        # KV lifecycle tier rides the ModelOptions.options JSON blob (no
        # dedicated proto field — same lane as the hfapi endpoint override)
        kv_policy, kv_cold_pages, kv_host_bytes = "", 0, 0
        if request.options:
            import json

            opts = json.loads(request.options)  # typos fail the load loudly
            kv_policy = str(opts.get("kv_policy", ""))
            kv_cold_pages = int(opts.get("kv_cold_pages", 0))
            kv_host_bytes = int(opts.get("kv_host_bytes", 0))
        self.engine = Engine(cfg, params, tok, EngineConfig(
            max_slots=request.parallel or 4,
            max_context=context_size,
            prefill_buckets=buckets,
            prefill_chunk=chunk,
            mesh=mesh,
            gamma=request.n_draft or 4,
            cache_type=cache_type,
            kv_pages=request.kv_pages,
            kv_policy=kv_policy,
            kv_cold_pages=kv_cold_pages,
            kv_host_bytes=kv_host_bytes,
        ), draft=draft)
        if request.embeddings:
            from localai_tpu.engine.embedder import CrossScorer

            self.embedder = Embedder(cfg, params, buckets=buckets, mesh=mesh)
            self.scorer = CrossScorer(cfg, params, buckets=buckets, mesh=mesh)
        from localai_tpu.models.llava import is_llava, load_vision

        self.vision = None
        if is_llava(model_dir):
            # vision-language checkpoint: the CLIP tower + projector serve
            # request.images (the reference's mmproj / vLLM-multimodal role)
            self.vision = load_vision(model_dir)
        self.cfg, self.tok = cfg, tok
        self.model_name = request.model
        self.engine.start()
        if os.environ.get("LOCALAI_NO_PREWARM") != "1":
            self._prewarm()

    def _prewarm(self):
        """Compile the serving hot path before LoadModel returns READY (the
        llama.cpp server warms its graph the same way): K=1 admission, the
        fused decode block, and the fast-sampling tail. Without this the
        FIRST user request pays tens of seconds of XLA compiles on TPU."""
        from localai_tpu.engine import GenRequest
        from localai_tpu.ops.sampling import SamplingParams

        try:
            # pre-compile every decode-loop variant, sort-free sampling
            # tier, and remaining scan-ladder width directly (all-inactive
            # dispatches) — the streamed requests below then only pay the
            # admission-bucket compiles, and the first USER request pays
            # nothing (the bench's window-0 204 tok/s vs 2760 steady-state
            # gap was exactly these mid-stream compiles)
            self.engine.warmup()
            n = 3 * self.engine.ec.decode_block + 2
            # three warm requests: the sort-free fast path (greedy/top_k),
            # its 8x escalation tier (wide top_k), and the full-sort path
            # (top_k=0 MUST be explicit — the dataclass default is 40,
            # which would silently warm the fast path twice)
            W = self.engine.ec.sampling_topk_width
            warm = [SamplingParams(temperature=0.0, top_k=40),
                    SamplingParams(temperature=0.8, top_p=0.9, top_k=0,
                                   seed=1)]
            if W and 2 * W <= self.cfg.vocab_size:
                warm.insert(1, SamplingParams(temperature=0.8, top_k=2 * W,
                                              seed=2))
            for sp in warm:
                _, q = self.engine.submit(GenRequest(
                    prompt_ids=[1], max_tokens=n, ignore_eos=True,
                    params=sp))
                while not q.get(timeout=600).finished:
                    pass
        except Exception:
            import logging

            logging.getLogger("localai_tpu").warning(
                "prewarm failed; first request will pay compiles",
                exc_info=True)
        finally:
            # the synthetic warm requests must not pollute the serving SLO
            # percentiles (warmup() snapshots the dispatch counters the same
            # way)
            slo = telemetry.maybe_slo()
            if slo is not None:
                slo.reset()

    def _load_bert(self, request, model_dir: str):
        """Embedding-only load path for BERT-family encoders: generation RPCs
        stay FAILED_PRECONDITION (engine is None), Embedding serves."""
        from localai_tpu.engine.loader import load_tokenizer
        from localai_tpu.models.bert import (
            BertEmbedder, load_bert_config, load_bert_params,
        )

        cfg = load_bert_config(model_dir, dtype=request.dtype or None)
        params = load_bert_params(model_dir, cfg)
        buckets = tuple(request.prefill_buckets) or (64, 256, 512)
        self.embedder = BertEmbedder(cfg, params, buckets=buckets)
        try:
            self.tok = load_tokenizer(model_dir)
        except FileNotFoundError:
            # tokenizer-less checkpoint still serves the prompt_ids path
            self.tok = None
        self.cfg = cfg
        self.model_name = request.model

    # ------------------------------------------------------------ helpers

    def _require_engine(self, context):
        if self.engine is None:
            context.abort(grpc.StatusCode.FAILED_PRECONDITION,
                          "no model loaded (call LoadModel first)")

    def _prompt_ids(self, request, context) -> list[int]:
        if request.prompt_ids:
            return list(request.prompt_ids)
        if self.tok is None:
            context.abort(grpc.StatusCode.FAILED_PRECONDITION,
                          "no tokenizer; pass prompt_ids")
        if request.use_tokenizer_template and request.messages_json:
            messages = json.loads(request.messages_json)
            # tool schemas render into the prompt via the chat template's
            # `tools` variable (engine/tokenizer.apply_chat_template) — the
            # grammar constrains the OUTPUT shape, but the model can only
            # pick sensible tools/arguments if it actually SEES them
            # (reference: chat.go:266-312 renders schemas before
            # constraining; VERDICT Missing #1)
            tools = None
            if request.tools_json:
                try:
                    tools = json.loads(request.tools_json) or None
                except json.JSONDecodeError:
                    context.abort(grpc.StatusCode.INVALID_ARGUMENT,
                                  "tools_json is not valid JSON")
            return self.tok.encode_chat(messages, tools=tools)
        return self.tok.encode(request.prompt)

    @staticmethod
    def _sampling(request) -> SamplingParams:
        return SamplingParams(
            temperature=request.temperature,
            top_k=request.top_k or 0,
            top_p=request.top_p or 1.0,
            min_p=request.min_p,
            typical_p=request.typical_p or 1.0,
            repeat_penalty=request.repeat_penalty or 1.0,
            presence_penalty=request.presence_penalty,
            frequency_penalty=request.frequency_penalty,
            seed=request.seed if request.seed else -1,
            logit_bias=dict(request.logit_bias) or None,
        )

    def _submit(self, request, context, trace_id: str = "",
                trace_parent: int = 0):
        from localai_tpu.engine import GenRequest

        resume = None
        max_tokens = request.tokens or 128
        if request.resume_json:
            # preemption resume (ISSUE 19): the request carries a ResumeToken
            # — prompt becomes original+emitted, the payload drives the
            # engine's RNG/grammar/detok fixups, and the token budget shrinks
            # by what the preempted stream already produced
            from localai_tpu.engine.resume import ResumeToken

            try:
                tok = ResumeToken.from_json(request.resume_json)
            except (ValueError, KeyError, TypeError) as e:
                context.abort(grpc.StatusCode.INVALID_ARGUMENT,
                              f"bad resume_json: {e}")
            ids = tok.resume_prompt
            resume = tok.payload()
            max_tokens = max(1, max_tokens - tok.generated)
        else:
            ids = self._prompt_ids(request, context)
        mm_embeds = mm_positions = None
        if request.images:
            if self.vision is None:
                context.abort(grpc.StatusCode.INVALID_ARGUMENT,
                              "model has no vision tower; images unsupported")
            try:
                ids, mm_embeds, mm_positions = self._encode_images(
                    ids, list(request.images))
            except Exception as e:
                # bad base64 (binascii.Error), not-an-image payloads
                # (PIL.UnidentifiedImageError ⊂ OSError), placeholder-count
                # mismatches (ValueError) — all client errors, never fatal
                context.abort(grpc.StatusCode.INVALID_ARGUMENT,
                              f"bad image: {e}")
        req = GenRequest(
            prompt_ids=ids,
            params=self._sampling(request),
            max_tokens=max_tokens,
            resume=resume,
            stop=tuple(request.stop_prompts),
            ignore_eos=request.ignore_eos,
            logprobs=request.logprobs,
            grammar=request.grammar,
            context_shift=request.context_shift,
            prompt_cache_path=request.prompt_cache_path,
            prompt_cache_ro=request.prompt_cache_ro,
            mm_embeds=mm_embeds,
            mm_positions=mm_positions,
            trace_id=trace_id,
            trace_parent=trace_parent,
            # remaining HTTP-request budget → absolute engine deadline: an
            # expired slot is evicted (finish "timeout") instead of decoding
            # tokens nobody will read
            deadline=(time.monotonic() + request.deadline_ms / 1e3
                      if request.deadline_ms else 0.0),
        )
        try:
            rid, out = self.engine.submit(req)
        except (ValueError, RuntimeError) as e:
            context.abort(grpc.StatusCode.INVALID_ARGUMENT, str(e))
        # RPC termination (client cancel/disconnect, deadline) evicts the
        # slot — the unary analog of the stream's call.cancel() path. Fires
        # on normal completion too, where cancel() is a no-op. (Direct
        # servicer tests pass context=None.)
        if context is not None:
            context.add_callback(lambda: self.engine.cancel(rid))
        return rid, out, ids

    def _encode_images(self, ids, images):
        """b64 images + prompt ids with <image> placeholders → (expanded ids,
        mm_embeds [K, H], mm_positions [K]). The CLIP tower + projector run
        as their own jit — per-request prefill-side work, off the decode
        loop (models/llava.py)."""
        import numpy as np

        from localai_tpu.models.llava import (
            decode_image_b64, encode_images, expand_image_tokens,
            preprocess_image,
        )

        vcfg, vparams, meta = self.vision
        px = np.concatenate(
            [preprocess_image(decode_image_b64(i), vcfg) for i in images])
        feats = np.asarray(encode_images(vparams, vcfg, meta, px),
                           np.float32)                  # [N, n_tok, H]
        n_tok = feats.shape[1]
        if meta.image_token_index not in ids and len(images) == 1:
            # prompt without a placeholder (plain chat with an attachment):
            # image goes first, like llava's "<image>\n<prompt>" convention
            ids = [meta.image_token_index] + list(ids)
        ids, positions = expand_image_tokens(
            ids, len(images), n_tok, meta.image_token_index)
        return ids, feats.reshape(-1, feats.shape[-1]), positions

    # ------------------------------------------------------------ inference

    def Predict(self, request, context):
        self._require_engine(context)
        _inject_faults(context)
        t0 = time.monotonic()
        trace_id = _request_id(context)
        tr = telemetry.maybe_tracer()
        gspan = tr.begin("grpc.Predict", cat="grpc",
                         args={"request_id": trace_id}) if tr else None
        text, ids, logprobs, ttft = [], [], [], 0.0
        o = None
        try:
            rid, out, _ = self._submit(request, context, trace_id=trace_id,
                                       trace_parent=gspan.sid if gspan else 0)
            while True:
                o = out.get()
                if o.token_id >= 0 and not ttft:
                    ttft = time.monotonic() - t0
                if o.text:
                    text.append(o.text)
                if o.token_id >= 0:
                    ids.append(o.token_id)
                    logprobs.append(o.logprob)
                if o.finished:
                    break
        finally:
            # a _submit abort / severed stream must still close the span, or
            # the request's trace never reaches the ring buffer
            if gspan is not None:
                tr.finish(gspan, tokens=o.generated_tokens if o else 0,
                          ttft_s=ttft)
        return pb.Reply(
            message="".join(text).encode(),
            tokens=o.generated_tokens,
            prompt_tokens=o.prompt_tokens,
            timing_prompt_processing=ttft,
            timing_token_generation=time.monotonic() - t0 - ttft,
            logprobs=logprobs if request.logprobs else [],
            token_ids=ids,
            finish_reason=o.finish_reason or "",
            timings_json=json.dumps(o.timings) if o.timings else "",
        )

    def PredictStream(self, request, context):
        self._require_engine(context)
        _inject_faults(context)
        stall = faults.fire("stall_stream")
        # preemption chaos kinds (ISSUE 19): `preempt:grace` raises SIGTERM
        # once the first token is out (the spill-drain path — server.py's
        # handler runs servicer.preempt and the terminal "preempted" reply
        # flushes through this still-open stream); `kill9_middecode:N` SIGKILLs
        # the process at the N-th emitted token — no drain, no checkpoint,
        # the HTTP bridge must resume from its own accumulated state
        pre_grace = faults.fire("preempt")
        kill_at = faults.fire("kill9_middecode")
        t0 = time.monotonic()
        trace_id = _request_id(context)
        tr = telemetry.maybe_tracer()
        gspan = tr.begin("grpc.PredictStream", cat="grpc",
                         args={"request_id": trace_id}) if tr else None
        ttft = 0.0
        sent_text = False
        emitted = 0
        first = True
        o = None
        try:
            rid, out, ids = self._submit(request, context, trace_id=trace_id,
                                         trace_parent=gspan.sid if gspan
                                         else 0)
            while True:
                o = out.get()
                if sent_text and stall:
                    # stall-mid-stream fault: the first TEXT chunk went out
                    # (so the client has provably received bytes), then the
                    # backend wedges for `stall` seconds (chaos harness)
                    time.sleep(stall)
                    stall = None
                if o.text:
                    sent_text = True
                if o.token_id >= 0:
                    emitted += 1
                    if not ttft:
                        ttft = time.monotonic() - t0
                resume_json = ""
                if first and not o.finished:
                    # minimal checkpoint on the FIRST chunk: the tokenized
                    # prompt, so the HTTP bridge can rebuild prompt+emitted
                    # for resume/deterministic-replay after an ungraceful
                    # death (no spill-drain ran, no full token exists)
                    resume_json = json.dumps({"v": 1, "prompt_ids": ids})
                elif o.finish_reason == "preempted" and o.resume is not None:
                    # spill-drain checkpoint: the full ResumeToken rides the
                    # terminal reply out before the process exits
                    resume_json = json.dumps(o.resume)
                first = False
                yield pb.Reply(
                    message=o.text.encode(),
                    tokens=o.generated_tokens,
                    prompt_tokens=o.prompt_tokens,
                    timing_prompt_processing=ttft if o.finished else 0.0,
                    timing_token_generation=(time.monotonic() - t0 - ttft)
                    if o.finished else 0.0,
                    logprobs=[o.logprob]
                    if request.logprobs and o.token_id >= 0 else [],
                    token_ids=[o.token_id] if o.token_id >= 0 else [],
                    finish_reason=o.finish_reason or "",
                    timings_json=(json.dumps(o.timings)
                                  if o.finished and o.timings else ""),
                    resume_json=resume_json,
                )
                if o.finished:
                    return
                if emitted and pre_grace is not None:
                    import signal

                    os.environ["LOCALAI_PREEMPT_GRACE"] = str(pre_grace)
                    pre_grace = None
                    os.kill(os.getpid(), signal.SIGTERM)
                if (kill_at is not None
                        and emitted >= max(1, int(kill_at))):
                    import signal

                    os.kill(os.getpid(), signal.SIGKILL)
        finally:
            # client disconnects mid-stream (GeneratorExit) and _submit
            # aborts land here too — the span must always close
            if gspan is not None:
                tr.finish(gspan, tokens=o.generated_tokens if o else 0,
                          ttft_s=ttft)

    # ------------------------------------------------------------ aux RPCs

    def TokenizeString(self, request, context):
        if self.tok is None:
            context.abort(grpc.StatusCode.FAILED_PRECONDITION, "no tokenizer")
        ids = self.tok.encode(request.prompt)
        return pb.TokenizationResponse(length=len(ids), tokens=ids)

    def Embedding(self, request, context):
        if self.embedder is None:
            context.abort(grpc.StatusCode.FAILED_PRECONDITION,
                          "model loaded without embeddings=true")
        if request.prompts:
            # batched path: the whole input list in one RPC, one bucketed
            # device call (reference transformers/backend.py:323 batches too)
            if self.tok is None:
                context.abort(grpc.StatusCode.FAILED_PRECONDITION,
                              "no tokenizer; batched embeddings need one")
            ids_batch = [self.tok.encode(p) for p in request.prompts]
            try:
                vecs = self.embedder.embed(ids_batch)
            except ValueError as e:
                context.abort(grpc.StatusCode.INVALID_ARGUMENT, str(e))
            return pb.EmbeddingResult(
                vectors=[pb.EmbeddingVector(values=v.tolist()) for v in vecs],
                prompt_tokens=sum(len(i) for i in ids_batch))
        ids = self._prompt_ids(request, context)
        try:
            vec = self.embedder.embed([ids])[0]
        except ValueError as e:
            context.abort(grpc.StatusCode.INVALID_ARGUMENT, str(e))
        return pb.EmbeddingResult(embeddings=vec.tolist(),
                                  prompt_tokens=len(ids))

    def Rerank(self, request, context):
        """Cross-encoder rerank (reference Rerank RPC, grpc-server.cpp:1466 /
        rerankers backend): each document scored by the LM's conditional
        log-likelihood given the query — query+document attend jointly
        (engine/embedder.py CrossScorer), not bi-encoder cosine."""
        if self.scorer is None:
            context.abort(grpc.StatusCode.FAILED_PRECONDITION,
                          "model loaded without embeddings=true")
        if not request.query or not request.documents:
            context.abort(grpc.StatusCode.INVALID_ARGUMENT,
                          "query and documents required")
        q_ids = self.tok.encode(request.query)
        d_ids = [self.tok.encode(d, add_bos=False)
                 for d in request.documents]
        try:
            sims = self.scorer.score(q_ids, d_ids)
        except ValueError as e:
            context.abort(grpc.StatusCode.INVALID_ARGUMENT, str(e))
        order = sims.argsort()[::-1]
        top_n = request.top_n or len(order)
        resp = pb.RerankResult()
        for i in order[:top_n]:
            resp.results.append(pb.RerankedDocument(
                index=int(i), text=request.documents[int(i)],
                relevance_score=float(sims[int(i)])))
        return resp

    def Status(self, request, context):
        rss = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss * 1024
        return pb.StatusResponse(
            state=self._state,
            memory=pb.MemoryUsageData(total=rss, breakdown={"rss_peak": rss}),
        )

    def GetMetrics(self, request, context):
        m = dict(self.engine.metrics) if self.engine else {}
        if self.engine is not None and self.engine._prof is not None:
            # flattened stage profile (prof_<stage>_{count,total_ms,p50_ms,
            # tok_s}) rides the existing str→double metrics surface
            m.update(self.engine._prof.flat())
        slo = telemetry.maybe_slo()
        if slo is not None:
            # SLO histograms (hist_<metric>__<path>__{bN,count,sum} +
            # ttft_ms_p50/p95) ride the same surface; the HTTP layer rebuilds
            # true Prometheus histogram series from these at scrape time
            m.update(slo.flat())
        sched = getattr(self.engine, "_sched", None) if self.engine else None
        if sched is not None:
            # tick-ledger counters + any CACHED rooflines (sched_* keys —
            # ISSUE 13); flat() never compiles, so scrapes stay cheap
            m.update(sched.flat())
        return pb.MetricsResponse(metrics={k: float(v) for k, v in m.items()})

    def GetTrace(self, request, context):
        slo = telemetry.maybe_slo()
        payload = {
            "spans": telemetry.chrome_events(),
            "profile": (self.engine._prof.report()
                        if self.engine is not None
                        and self.engine._prof is not None else {}),
            # SLO percentile snapshot + flight-recorder dump (ISSUE 11):
            # the /debug/slo and /debug/flightrec lanes across the process
            # boundary, reusing the JSON-in-Reply transport
            "slo": slo.snapshot() if slo is not None else {},
            # scheduler X-ray (ISSUE 13): recent tick records + reason-code
            # counters + per-variant rooflines (the first call pays the
            # per-variant AOT cost-analysis compile, then it's cached)
            "sched": (self.engine.sched_snapshot()
                      if self.engine is not None else {}),
            # host KV tier occupancy (ISSUE 17): /debug/slo's kv_host
            # section; {} unless the engine runs with kv_host_bytes > 0
            "kvhost": (self.engine.kvhost_snapshot()
                       if self.engine is not None else {}),
            "flightrec": telemetry.flightrec().dump(),
            "pid": os.getpid(),
            "model": self.model_name,
        }
        return pb.Reply(message=json.dumps(payload).encode())

    def preempt(self, grace: float = 0.0) -> list[dict]:
        """Spill-drain the engine (ISSUE 19): freeze live slots, spill their
        KV into the host pool, and emit terminal "preempted" replies carrying
        ResumeTokens through the open streams. Returns the resume manifest
        (server.py's SIGTERM fast-path calls this before stopping)."""
        if self.engine is None:
            return []
        return self.engine.preempt(grace)

    def shutdown(self):
        if self.engine is not None:
            self.engine.stop()
