"""`python -m localai_tpu.backend --addr 127.0.0.1:PORT --backend llm`"""
import argparse
import sys

from localai_tpu.backend.server import ROLES, serve_blocking


def main(argv=None):
    p = argparse.ArgumentParser(prog="localai_tpu.backend")
    p.add_argument("--addr", default="127.0.0.1:50051")
    p.add_argument("--backend", default="llm", choices=sorted(ROLES))
    args = p.parse_args(argv)
    return serve_blocking(addr=args.addr, backend=args.backend)


if __name__ == "__main__":
    sys.exit(main())
