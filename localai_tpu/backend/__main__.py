"""`python -m localai_tpu.backend --addr 127.0.0.1:PORT --backend llm`"""
import argparse
import sys

from localai_tpu.backend.server import ROLES, serve_blocking


def main(argv=None):
    import os

    # must run before any jax device use (the hermetic-CPU test knob; the
    # axon site hook otherwise owns backend selection)
    plat = os.environ.get("LOCALAI_JAX_PLATFORM")
    if plat:
        import jax

        jax.config.update("jax_platforms", plat)
    p = argparse.ArgumentParser(prog="localai_tpu.backend")
    p.add_argument("--addr", default="127.0.0.1:50051")
    p.add_argument("--backend", default="llm", choices=sorted(ROLES))
    args = p.parse_args(argv)
    return serve_blocking(addr=args.addr, backend=args.backend)


if __name__ == "__main__":
    sys.exit(main())
