"""`python -m localai_tpu.backend --addr 127.0.0.1:PORT --backend llm`"""
import argparse
import sys

from localai_tpu.backend.server import ROLES, serve_blocking


def main(argv=None):
    import os

    # must run before any jax device use (the hermetic-CPU test knob; the
    # axon site hook otherwise owns backend selection)
    plat = os.environ.get("LOCALAI_JAX_PLATFORM")
    if plat:
        import jax

        jax.config.update("jax_platforms", plat)
    p = argparse.ArgumentParser(prog="localai_tpu.backend")
    p.add_argument("--addr", default="127.0.0.1:50051")
    p.add_argument("--backend", default="llm", choices=sorted(ROLES))
    args = p.parse_args(argv)
    # chaos-harness spawn faults (localai_tpu/testing/faults.py): crash
    # before binding (the dead-child / port-TOCTOU shape the manager must
    # detect fast) or stall before health (slow-start)
    from localai_tpu.testing import faults

    arg = faults.fire("spawn_crash")
    if arg is not None:
        sys.exit(int(arg) or 3)
    arg = faults.fire("slow_start")
    if arg:
        import time

        time.sleep(arg)
    return serve_blocking(addr=args.addr, backend=args.backend)


if __name__ == "__main__":
    sys.exit(main())
