"""gRPC backend processes — the L1/L2 process boundary.

One proto contract (`backend.proto`), many backend roles (llm, embedding,
whisper, store, ...), each a separate process spawned by the control plane on
a localhost port (reference: /root/reference/pkg/model/process.go:93-160).
"""
from localai_tpu.backend import pb  # noqa: F401
from localai_tpu.backend.base import BackendServicer  # noqa: F401
from localai_tpu.backend.client import BackendClient  # noqa: F401
from localai_tpu.backend.server import serve, serve_blocking  # noqa: F401
