"""Backend process entrypoint: one gRPC server on a localhost port.

Spawn contract mirrors the reference's backend launch
(`--addr 127.0.0.1:<freeport>`, health-polled by the loader —
/root/reference/pkg/model/initializers.go:57-129): the control plane starts
`python -m localai_tpu.backend --addr ... --backend llm`, polls Health, then
issues LoadModel.
"""
from __future__ import annotations

import signal
import threading
from concurrent import futures

import grpc

from localai_tpu.backend.base import BackendServicer, add_backend_servicer

# role registry — the backend zoo (reference SURVEY §2.2/2.3 rows); roles are
# lazy imports so a store-only process never touches jax.
ROLES = {}


def _role(name):
    def reg(fn):
        ROLES[name] = fn
        return fn

    return reg


@_role("llm")
def _make_llm():
    from localai_tpu.backend.llm import LLMServicer

    return LLMServicer()


@_role("image")
def _make_image():
    from localai_tpu.backend.image import ImageServicer

    return ImageServicer()


@_role("whisper")
def _make_whisper():
    from localai_tpu.backend.whisper import WhisperServicer

    return WhisperServicer()


@_role("tts")
def _make_tts():
    from localai_tpu.backend.whisper import TTSServicer

    return TTSServicer()


@_role("huggingface")
def _make_hfapi():
    from localai_tpu.backend.hfapi import HFApiServicer

    return HFApiServicer()


@_role("detect")
def _make_detect():
    from localai_tpu.backend.detect import DetectServicer

    return DetectServicer()


@_role("store")
def _make_store():
    from localai_tpu.backend.store import StoreServicer

    return StoreServicer()


@_role("base")
def _make_base():
    return BackendServicer()


def serve(addr: str = "127.0.0.1:50051", backend: str = "llm",
          max_workers: int = 16, servicer=None):
    """Start a backend server; returns (grpc.Server, servicer, bound_port).
    `servicer` overrides role construction (multi-host worker preloads one)."""
    if servicer is None:
        if backend not in ROLES:
            raise ValueError(
                f"unknown backend role {backend!r}; have {sorted(ROLES)}")
        servicer = ROLES[backend]()
    server = grpc.server(
        futures.ThreadPoolExecutor(max_workers=max_workers),
        options=[("grpc.max_receive_message_length", 128 * 1024 * 1024),
                 ("grpc.max_send_message_length", 128 * 1024 * 1024)],
    )
    add_backend_servicer(server, servicer)
    port = server.add_insecure_port(addr)
    if port == 0:
        raise OSError(f"could not bind {addr}")
    server.start()
    return server, servicer, port


def serve_blocking(addr: str = "127.0.0.1:50051", backend: str = "llm",
                   servicer=None) -> int:
    server, servicer, port = serve(addr, backend, servicer=servicer)
    print(f"backend[{backend}] serving on port {port}", flush=True)
    stop = threading.Event()

    def _preempt_then_stop():
        # preemption fast-path (ISSUE 19): spill-drain live slots so their
        # terminal "preempted" replies (carrying ResumeTokens) flush through
        # the still-open streams, THEN stop. The drain runs off the signal
        # handler thread — engine.preempt blocks until the freeze completes.
        import os

        try:
            grace = float(os.environ.get("LOCALAI_PREEMPT_GRACE", "0") or 0)
            servicer.preempt(grace)
        except Exception:
            import traceback

            traceback.print_exc()
        finally:
            stop.set()

    def _sig(signum, frame):
        if signum == signal.SIGTERM and hasattr(servicer, "preempt"):
            threading.Thread(target=_preempt_then_stop, daemon=True).start()
        else:
            stop.set()

    signal.signal(signal.SIGTERM, _sig)
    signal.signal(signal.SIGINT, _sig)
    stop.wait()
    if hasattr(servicer, "shutdown"):
        servicer.shutdown()
    server.stop(grace=5).wait(10)
    return 0


def serve_preloaded(addr: str, servicer) -> int:
    """Serve an already-constructed servicer (multi-host worker rank 0)."""
    return serve_blocking(addr, backend="worker", servicer=servicer)
