"""Import shim for the protoc-generated message module.

protoc's --python_out emits `backend_pb2` expecting itself on sys.path; this
re-exports it as `localai_tpu.backend.pb` so the package namespace stays clean.
Regenerate with:
  protoc --python_out=localai_tpu/backend -I localai_tpu/backend \
      localai_tpu/backend/backend.proto
"""
import os
import sys

_here = os.path.dirname(__file__)
if _here not in sys.path:
    sys.path.insert(0, _here)

from backend_pb2 import *  # noqa: F401,F403,E402
import backend_pb2 as _pb2  # noqa: E402

DESCRIPTOR = _pb2.DESCRIPTOR
SERVICE = DESCRIPTOR.services_by_name["Backend"]
SERVICE_NAME = SERVICE.full_name
