"""Whisper transcription backend servicer (reference:
/root/reference/backend/go/whisper/gowhisper.go — AudioTranscription with
segments — plus the silero VAD backend's VAD RPC, vad.go:1-58)."""
from __future__ import annotations

import os
import threading

import grpc
import numpy as np

from localai_tpu.backend import pb
from localai_tpu.backend.base import BackendServicer


class WhisperServicer(BackendServicer):
    def __init__(self):
        self.model = None
        self._lock = threading.Lock()

    def LoadModel(self, request, context):
        with self._lock:
            if self.model is not None:
                return pb.Result(success=True, message="already loaded")
            try:
                from localai_tpu.models.whisper import WhisperModel

                model_dir = request.model
                if request.model_path and not os.path.isdir(model_dir):
                    model_dir = os.path.join(request.model_path, request.model)
                self.model = WhisperModel(model_dir, dtype=request.dtype or None)
                return pb.Result(success=True, message="ok")
            except Exception as e:
                return pb.Result(success=False,
                                 message=f"{type(e).__name__}: {e}")

    def AudioTranscription(self, request, context):
        if self.model is None:
            context.abort(grpc.StatusCode.FAILED_PRECONDITION, "no model")
        from localai_tpu.audio.transcode import to_pcm16k
        from localai_tpu.audio.vad import detect_segments_auto

        try:
            # WAV natively; other containers via the ffmpeg shell-out role
            # (reference pkg/utils/ffmpeg.go)
            audio = to_pcm16k(request.dst)
        except Exception as e:
            context.abort(grpc.StatusCode.INVALID_ARGUMENT,
                          f"cannot read audio: {e}")
        # VAD-split → one whisper pass per speech segment (segments shape of
        # the reference's whisper_full segments)
        spans = detect_segments_auto(audio) or (
            [(0.0, len(audio) / 16000.0)] if len(audio) else [])
        resp = pb.TranscriptResult()
        texts = []
        for i, (s, e) in enumerate(spans):
            chunk = audio[int(s * 16000): int(e * 16000)]
            toks = self.model.transcribe_tokens(chunk)
            text = (self.model.tokenizer.decode(toks, skip_special_tokens=True)
                    if self.model.tokenizer else " ".join(map(str, toks)))
            texts.append(text.strip())
            resp.segments.append(pb.TranscriptSegment(
                id=i, start=int(s * 1e9), end=int(e * 1e9),
                text=text.strip(), tokens=toks))
        resp.text = " ".join(t for t in texts if t)
        return resp

    def VAD(self, request, context):
        from localai_tpu.audio.vad import detect_segments_auto

        audio = np.asarray(list(request.audio), np.float32)
        resp = pb.VADResponse()
        for s, e in detect_segments_auto(audio):
            resp.segments.append(pb.VADSegment(start=s, end=e))
        return resp


class TTSServicer(BackendServicer):
    """Neural (VITS/MMS) or DSP TTS + sound generation (reference
    piper/bark role, backend/go/piper/piper.go:1-49). LoadModel with a VITS
    checkpoint dir arms the neural voice; without one the dependency-free
    formant synthesizer serves the contract."""

    def __init__(self):
        self.voice = None

    def LoadModel(self, request, context):
        self.voice = None            # a re-load must not keep a stale voice
        model_dir = request.model
        if request.model_path and model_dir and not os.path.isdir(model_dir):
            model_dir = os.path.join(request.model_path, request.model)
        if model_dir and os.path.isdir(model_dir):
            from localai_tpu.models.vits import VitsTTS, is_vits_dir

            if is_vits_dir(model_dir):
                try:
                    self.voice = VitsTTS(model_dir)
                except Exception as e:
                    return pb.Result(success=False,
                                     message=f"{type(e).__name__}: {e}")
        return pb.Result(success=True, message="ok")

    def TTS(self, request, context):
        if not request.dst:
            context.abort(grpc.StatusCode.INVALID_ARGUMENT, "dst required")
        from localai_tpu.audio.pcm import write_wav

        if self.voice is not None:
            audio = self.voice.synthesize(request.text)
            write_wav(request.dst, audio, self.voice.rate)
            return pb.Result(success=True, message=request.dst)
        from localai_tpu.audio.tts import synthesize

        audio = synthesize(request.text, voice=request.voice or "default",
                           language=request.language or "en")
        write_wav(request.dst, audio, 16000)
        return pb.Result(success=True, message=request.dst)

    def SoundGeneration(self, request, context):
        if not request.dst:
            context.abort(grpc.StatusCode.INVALID_ARGUMENT, "dst required")
        from localai_tpu.audio.pcm import write_wav
        from localai_tpu.audio.tts import generate_sound

        audio = generate_sound(request.text,
                               duration=request.duration or 2.0)
        write_wav(request.dst, audio, 16000)
        return pb.Result(success=True, message=request.dst)

    def VAD(self, request, context):
        from localai_tpu.audio.vad import detect_segments_auto

        audio = np.asarray(list(request.audio), np.float32)
        resp = pb.VADResponse()
        for s, e in detect_segments_auto(audio):
            resp.segments.append(pb.VADSegment(start=s, end=e))
        return resp
