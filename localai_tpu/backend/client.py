"""Typed gRPC client for the Backend contract — the control-plane side
(reference: /root/reference/pkg/grpc/client.go:53-519, one wrapper per RPC,
plus spawn-time health polling initializers.go:110-129).

No generated stubs (no grpc_tools in image): callables are derived from the
proto DESCRIPTOR, same wire format.
"""
from __future__ import annotations

import json
import time
from typing import Iterator

import grpc

from localai_tpu import telemetry
from localai_tpu.backend import pb
from localai_tpu.core import resilience

# gRPC metadata key carrying the HTTP request id into the backend process
# (server/http.py middleware → here → backend/llm.py → GenRequest.trace_id)
REQUEST_ID_KEY = "x-localai-request-id"


def _trace_md():
    """Metadata tuple propagating the current context's request id (None
    when no request id is bound — the common non-traced path)."""
    rid = telemetry.current_request_id()
    return ((REQUEST_ID_KEY, rid),) if rid else None


class BackendClient:
    def __init__(self, addr: str):
        self.addr = addr
        # match the server's raised caps (server.py): a batched embedding
        # reply (256 × 4096 f32) exceeds gRPC's 4MB default
        self._channel = grpc.insecure_channel(addr, options=[
            ("grpc.max_receive_message_length", 128 * 1024 * 1024),
            ("grpc.max_send_message_length", 128 * 1024 * 1024),
            # spawn-time poll: the first connects race the child's bind, and
            # gRPC's default reconnect backoff then grows toward minutes —
            # longer than the whole health budget. Cap it; this channel only
            # ever talks to a subprocess on loopback.
            ("grpc.initial_reconnect_backoff_ms", 250),
            ("grpc.min_reconnect_backoff_ms", 250),
            ("grpc.max_reconnect_backoff_ms", 2000),
        ])
        self._calls = {}
        self._req_cls = {}
        sym = pb._pb2
        for m in pb.SERVICE.methods:
            req_cls = getattr(sym, m.input_type.name)
            resp_cls = getattr(sym, m.output_type.name)
            make = (self._channel.unary_stream if m.server_streaming
                    else self._channel.unary_unary)
            self._req_cls[m.name] = req_cls
            self._calls[m.name] = make(
                f"/{pb.SERVICE_NAME}/{m.name}",
                request_serializer=req_cls.SerializeToString,
                response_deserializer=resp_cls.FromString,
            )

    def close(self):
        self._channel.close()

    # ---------------------------------------------------- deadline plumbing

    @staticmethod
    def _timeout(default: float) -> float:
        """Shrink an RPC timeout to the current request's remaining deadline
        budget (core/resilience contextvar, minted by the HTTP middleware —
        asyncio.to_thread copies the context into worker threads)."""
        rem = resilience.deadline_remaining()
        if rem is None:
            return default
        return max(min(default, rem), 0.001)

    def _request(self, method: str, kw: dict):
        """Build the request message; PredictOptions additionally carries the
        remaining deadline in-band (deadline_ms) so the ENGINE can evict an
        expired slot instead of decoding tokens nobody will read."""
        cls = self._req_cls[method]
        if cls is pb.PredictOptions and "deadline_ms" not in kw:
            rem = resilience.deadline_remaining()
            if rem is not None:
                kw["deadline_ms"] = max(int(rem * 1000), 1)
        return cls(**kw)

    def start(self, method: str, timeout: float = 600.0, **kw):
        """Begin a unary RPC and return its grpc Future — the cancellable
        form the HTTP layer uses so a client disconnect can abort the call
        (`fut.cancel()`) the way `call.cancel()` already works for streams."""
        fut = self._calls[method].future(
            self._request(method, kw), timeout=self._timeout(timeout),
            metadata=_trace_md())
        tr = telemetry.maybe_tracer()
        if tr is not None:
            # same rpc.<Method> span the blocking wrappers record, closed
            # when the future settles (completion, error, or cancel). The
            # request id is captured HERE — the done callback runs on a gRPC
            # thread without this request's contextvars.
            args = {"addr": self.addr}
            rid = telemetry.current_request_id()
            if rid:
                args["request_id"] = rid
            s = tr.begin(f"rpc.{method}", cat="rpc", args=args)
            fut.add_done_callback(lambda _f: tr.finish(s))
        return fut

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    # ------------------------------------------------------------ health

    def health(self, timeout: float = 5.0, wait: bool = False) -> bool:
        try:
            r = self._calls["Health"](pb.HealthMessage(), timeout=timeout,
                                      wait_for_ready=wait)
            return r.message == b"OK"
        except grpc.RpcError:
            return False

    def wait_ready(self, attempts: int = 60, sleep: float = 0.5) -> bool:
        """Spawn-time health poll (reference initializers.go:110-129).
        wait_for_ready queues the RPC until the channel connects (instead of
        failing fast from backoff state), so a slow child startup costs one
        deadline, not the whole budget."""
        for _ in range(attempts):
            if self.health(timeout=2.0, wait=True):
                return True
            time.sleep(sleep)
        return False

    # ------------------------------------------------------------ RPCs

    def load_model(self, timeout: float = 600.0, **kw) -> "pb.Result":
        return self._calls["LoadModel"](pb.ModelOptions(**kw), timeout=timeout)

    def predict(self, timeout: float = 600.0, **kw) -> "pb.Reply":
        with telemetry.span("rpc.Predict", cat="rpc", addr=self.addr):
            return self._calls["Predict"](self._request("Predict", kw),
                                          timeout=self._timeout(timeout),
                                          metadata=_trace_md())

    def predict_stream(self, timeout: float = 600.0, **kw) -> Iterator["pb.Reply"]:
        # the span covers only the stream OPEN — iteration happens on the
        # caller's pump thread; the backend-side grpc.PredictStream span
        # carries the full generation interval
        with telemetry.span("rpc.PredictStream.open", cat="rpc",
                            addr=self.addr):
            return self._calls["PredictStream"](
                self._request("PredictStream", kw),
                timeout=self._timeout(timeout),
                metadata=_trace_md())

    def embedding(self, timeout: float = 600.0, **kw) -> "pb.EmbeddingResult":
        with telemetry.span("rpc.Embedding", cat="rpc", addr=self.addr):
            return self._calls["Embedding"](self._request("Embedding", kw),
                                            timeout=self._timeout(timeout),
                                            metadata=_trace_md())

    def tokenize(self, prompt: str, timeout: float = 60.0) -> "pb.TokenizationResponse":
        return self._calls["TokenizeString"](pb.PredictOptions(prompt=prompt),
                                             timeout=timeout)

    def rerank(self, timeout: float = 600.0, **kw) -> "pb.RerankResult":
        return self._calls["Rerank"](pb.RerankRequest(**kw), timeout=timeout)

    def status(self, timeout: float = 10.0) -> "pb.StatusResponse":
        return self._calls["Status"](pb.HealthMessage(), timeout=timeout)

    def metrics(self, timeout: float = 10.0) -> dict:
        r = self._calls["GetMetrics"](pb.MetricsRequest(), timeout=timeout)
        return dict(r.metrics)

    def trace(self, timeout: float = 30.0) -> dict:
        """Backend telemetry snapshot: {"spans": [chrome events],
        "profile": {stage breakdown}, "pid": N} (GetTrace RPC)."""
        r = self._calls["GetTrace"](pb.MetricsRequest(), timeout=timeout)
        return json.loads(r.message.decode() or "{}")

    def tts(self, timeout: float = 600.0, **kw) -> "pb.Result":
        return self._calls["TTS"](pb.TTSRequest(**kw), timeout=timeout)

    def sound_generation(self, timeout: float = 600.0, **kw) -> "pb.Result":
        return self._calls["SoundGeneration"](
            pb.SoundGenerationRequest(**kw), timeout=timeout)

    def transcribe(self, timeout: float = 600.0, **kw) -> "pb.TranscriptResult":
        return self._calls["AudioTranscription"](pb.TranscriptRequest(**kw),
                                                 timeout=timeout)

    def vad(self, audio, timeout: float = 600.0) -> "pb.VADResponse":
        return self._calls["VAD"](pb.VADRequest(audio=audio), timeout=timeout)

    def generate_image(self, timeout: float = 600.0, **kw) -> "pb.Result":
        return self._calls["GenerateImage"](pb.GenerateImageRequest(**kw),
                                            timeout=timeout)

    def generate_video(self, timeout: float = 600.0, **kw) -> "pb.Result":
        return self._calls["GenerateVideo"](pb.GenerateVideoRequest(**kw),
                                            timeout=timeout)

    def detect(self, src: str, timeout: float = 600.0) -> "pb.DetectResponse":
        return self._calls["Detect"](pb.DetectOptions(src=src),
                                     timeout=timeout)

    def stores_set(self, keys, values, timeout: float = 60.0) -> "pb.Result":
        return self._calls["StoresSet"](pb.StoresSetOptions(
            keys=[pb.StoresKey(floats=k) for k in keys],
            values=[pb.StoresValue(bytes=v) for v in values]), timeout=timeout)

    def stores_get(self, keys, timeout: float = 60.0) -> "pb.StoresGetResult":
        return self._calls["StoresGet"](pb.StoresGetOptions(
            keys=[pb.StoresKey(floats=k) for k in keys]), timeout=timeout)

    def stores_delete(self, keys, timeout: float = 60.0) -> "pb.Result":
        return self._calls["StoresDelete"](pb.StoresDeleteOptions(
            keys=[pb.StoresKey(floats=k) for k in keys]), timeout=timeout)

    def stores_find(self, key, top_k: int, timeout: float = 60.0) -> "pb.StoresFindResult":
        return self._calls["StoresFind"](pb.StoresFindOptions(
            key=pb.StoresKey(floats=key), top_k=top_k), timeout=timeout)
