"""Preemption-safe resume tokens (ISSUE 19).

A ``ResumeToken`` is a host-side snapshot of everything a generation slot
needs to continue byte-exactly after the backend process dies or is
preempted: the original prompt ids, the tokens emitted so far, the
per-slot sampler RNG key (device state read back at preempt time), the
characters already released downstream, the KV chain hashes spilled into
the host pool, and the remaining deadline budget.

Resume is modelled as a *normal* request whose prompt is
``prompt_ids + emitted`` — KV reuse then falls out of the existing
prefix-cache / ``HostKVPool`` re-admission path, and the per-token
occurrence counts rebuilt by admission match the uninterrupted run by
construction.  The extra fixups (RNG key install, grammar/detokenizer
replay, suppressed re-emission of already-sent text) are driven by the
``resume`` payload attached to ``GenRequest``.

This module is deliberately numpy/stdlib-only so the HTTP process and
tests can round-trip tokens without importing JAX.
"""
from __future__ import annotations

import dataclasses
import json
from typing import Any

RESUME_VERSION = 1


@dataclasses.dataclass
class ResumeToken:
    """Checkpoint of one in-flight generation."""

    prompt_ids: list[int]            # original prompt token ids
    emitted: list[int]               # token ids emitted before preemption
    key: list[int] | None = None     # per-slot RNG key (2 x u32) read from
                                     # the device sampler at preempt; None
                                     # for greedy or hard-death resumes
    sent_chars: int = 0              # detokenized chars already released
    generated: int = 0               # emitted-token count (len(emitted)
                                     # unless the caller trimmed the list)
    chain: list[str] = dataclasses.field(default_factory=list)
                                     # hex chain hashes of the full KV
                                     # blocks spilled to the host pool
    deadline_left: float = 0.0       # remaining per-request budget (s);
                                     # 0 = no deadline
    request_id: str = ""             # original request id (log continuity)
    model: str = ""                  # model name the slot belonged to

    def __post_init__(self) -> None:
        if self.generated == 0:
            self.generated = len(self.emitted)

    @property
    def resume_prompt(self) -> list[int]:
        """Prompt for the resume request: original prompt + emitted."""
        return list(self.prompt_ids) + list(self.emitted)

    def payload(self) -> dict[str, Any]:
        """Engine-side ``GenRequest.resume`` payload."""
        return {
            "emitted": len(self.emitted),
            "key": list(self.key) if self.key is not None else None,
            "sent_chars": int(self.sent_chars),
        }

    def to_dict(self) -> dict[str, Any]:
        d = dataclasses.asdict(self)
        d["v"] = RESUME_VERSION
        return d

    def to_json(self) -> str:
        return json.dumps(self.to_dict(), separators=(",", ":"))

    @classmethod
    def from_dict(cls, d: dict[str, Any]) -> "ResumeToken":
        if int(d.get("v", RESUME_VERSION)) != RESUME_VERSION:
            raise ValueError(f"unsupported resume token version {d.get('v')}")
        key = d.get("key")
        return cls(
            prompt_ids=[int(t) for t in d.get("prompt_ids", [])],
            emitted=[int(t) for t in d.get("emitted", [])],
            key=[int(k) for k in key] if key is not None else None,
            sent_chars=int(d.get("sent_chars", 0)),
            generated=int(d.get("generated", 0)),
            chain=[str(h) for h in d.get("chain", [])],
            deadline_left=float(d.get("deadline_left", 0.0)),
            request_id=str(d.get("request_id", "")),
            model=str(d.get("model", "")),
        )

    @classmethod
    def from_json(cls, s: str) -> "ResumeToken":
        return cls.from_dict(json.loads(s))
